"""Anomaly notifiers: decide FIX vs CHECK vs IGNORE; alert integrations.

Reference: detector/notifier/AnomalyNotifier.java (SPI),
AnomalyNotificationResult.java, SelfHealingNotifier.java:68-104 (per-type
self-healing switches; broker failures alert after
`broker.failure.alert.threshold.ms` and self-heal after
`broker.failure.self.healing.threshold.ms`), SlackSelfHealingNotifier.java
(webhook alerting — modeled as a pluggable alert callback since this
environment has no egress).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Callable, Protocol

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    ExecutionStuck,
    FleetLeaseLost,
    OptimizerDegraded,
)


class Action(enum.Enum):
    """Reference AnomalyNotificationResult.Action."""

    FIX = "FIX"
    CHECK = "CHECK"
    IGNORE = "IGNORE"


@dataclasses.dataclass(frozen=True)
class AnomalyNotificationResult:
    action: Action
    delay_ms: int = 0

    @staticmethod
    def fix() -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(Action.FIX)

    @staticmethod
    def check(delay_ms: int) -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(Action.CHECK, delay_ms)

    @staticmethod
    def ignore() -> "AnomalyNotificationResult":
        return AnomalyNotificationResult(Action.IGNORE)


class AnomalyNotifier(Protocol):
    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        ...

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        ...


class SelfHealingNotifier:
    """Reference detector/notifier/SelfHealingNotifier.java.

    Broker failures are special-cased: alert after alert_threshold_ms from
    the earliest failure, FIX only after self_healing_threshold_ms — giving
    ops a window to bring a broker back before replicas are rebuilt.
    """

    def __init__(
        self,
        *,
        self_healing: dict[AnomalyType, bool] | None = None,
        broker_failure_alert_threshold_ms: int = 15 * 60 * 1000,
        broker_failure_self_healing_threshold_ms: int = 30 * 60 * 1000,
        alert_handler: Callable[[Anomaly, bool], None] | None = None,
        now_ms: Callable[[], int] | None = None,
    ):
        self._enabled = {t: False for t in AnomalyType}
        if self_healing:
            self._enabled.update(self_healing)
        self.alert_threshold_ms = broker_failure_alert_threshold_ms
        self.self_healing_threshold_ms = broker_failure_self_healing_threshold_ms
        self._alert = alert_handler or (lambda anomaly, auto_fix: None)
        self._now = now_ms or (lambda: int(time.time() * 1000))
        self.alerts: list[tuple[Anomaly, bool]] = []

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing(self, anomaly_type: AnomalyType, enabled: bool):
        self._enabled[anomaly_type] = enabled

    def _send_alert(self, anomaly: Anomaly, auto_fix: bool):
        self.alerts.append((anomaly, auto_fix))
        self._alert(anomaly, auto_fix)

    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly)
        if isinstance(anomaly, (OptimizerDegraded, ExecutionStuck,
                                FleetLeaseLost)):
            # nothing to fix (the supervisor's half-open probe / the
            # executor's reaper / the lease heartbeat's re-acquisition
            # already IS the recovery path) but operators must hear about
            # it immediately — alert, then ignore
            self._send_alert(anomaly, False)
            return AnomalyNotificationResult.ignore()
        if not self._enabled.get(anomaly.anomaly_type, False) or not anomaly.fixable:
            return AnomalyNotificationResult.ignore()
        self._send_alert(anomaly, True)
        return AnomalyNotificationResult.fix()

    def _on_broker_failure(self, anomaly: BrokerFailures) -> AnomalyNotificationResult:
        """Reference SelfHealingNotifier.onBrokerFailure:68-104."""
        if not anomaly.failed_brokers:
            return AnomalyNotificationResult.ignore()
        earliest = min(anomaly.failed_brokers.values())
        now = self._now()
        alert_time = earliest + self.alert_threshold_ms
        fix_time = earliest + self.self_healing_threshold_ms
        if now < alert_time:
            return AnomalyNotificationResult.check(alert_time - now)
        heal = self._enabled.get(AnomalyType.BROKER_FAILURE, False)
        if now < fix_time:
            self._send_alert(anomaly, False)
            return AnomalyNotificationResult.check(fix_time - now)
        self._send_alert(anomaly, heal)
        return AnomalyNotificationResult.fix() if heal else AnomalyNotificationResult.ignore()


class SlackSelfHealingNotifier(SelfHealingNotifier):
    """SelfHealingNotifier that POSTs alerts to a Slack incoming webhook
    (reference detector/notifier/SlackSelfHealingNotifier.java).

    The HTTP POST rides `poster` (injectable for tests / alternate
    webhook-compatible sinks); delivery failures are swallowed — alerting
    must never break anomaly handling (the reference logs and continues).
    """

    def __init__(
        self,
        webhook_url: str,
        *,
        channel: str | None = None,
        username: str = "cruise-control-tpu",
        poster: Callable[[str, bytes], None] | None = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.webhook_url = webhook_url
        self.channel = channel
        self.username = username
        self._post = poster or self._default_post
        self._alert = self._slack_alert  # route SelfHealingNotifier alerts

    @staticmethod
    def _default_post(url: str, body: bytes) -> None:
        import urllib.request

        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        urllib.request.urlopen(req, timeout=10).read()

    def _slack_alert(self, anomaly: Anomaly, auto_fix: bool) -> None:
        import json

        text = (
            f":warning: {anomaly.anomaly_type.name}: {anomaly.description()} "
            f"(self-healing {'STARTED' if auto_fix else 'disabled'})"
        )
        payload: dict = {"text": text, "username": self.username}
        if self.channel:
            payload["channel"] = self.channel
        try:
            self._post(self.webhook_url, json.dumps(payload).encode())
        except Exception:  # noqa: BLE001 — alert delivery is best-effort
            pass


class NoopNotifier:
    """Ignore everything (reference NoopNotifier)."""

    def on_anomaly(self, anomaly: Anomaly) -> AnomalyNotificationResult:
        return AnomalyNotificationResult.ignore()

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}
