"""AnomalyDetector — priority queue + handler dispatch + state tracking.

Reference: detector/AnomalyDetector.java:47 (detectors wired :63-68,
startDetection():189, AnomalyHandlerTask:318 FIX/CHECK/IGNORE dispatch,
skip-and-backoff while the executor is busy), AnomalyDetectorState.java
(rolling per-type history, rates), AnomalyMetrics.java
(mean-time-between-anomalies, self-healing-enabled ratio).

Self-healing fixes dispatch through the SelfHealingActions protocol —
implemented by the service facade: goal violation -> rebalance, broker
failure -> remove_brokers, disk failure -> fix_offline_replicas, slow
brokers -> demote/remove (reference RebalanceRunnable/RemoveBrokersRunnable/
FixOfflineReplicasRunnable/DemoteBrokerRunnable self-healing constructors).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import threading
import time
from collections import deque
from typing import Protocol

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    SlowBrokers,
    TopicReplicationFactorAnomaly,
)
from cruise_control_tpu.detector.notifier import Action, AnomalyNotifier

log = logging.getLogger(__name__)


class SelfHealingActions(Protocol):
    """Fix entry points the service facade provides."""

    def rebalance(self, reason: str) -> bool:
        ...

    def remove_brokers(self, broker_ids: list[int], reason: str) -> bool:
        ...

    def demote_brokers(self, broker_ids: list[int], reason: str) -> bool:
        ...

    def fix_offline_replicas(self, reason: str) -> bool:
        ...

    def fix_topic_replication_factor(self, topics: dict[str, int], target_rf: int, reason: str) -> bool:
        ...

    @property
    def is_busy(self) -> bool:
        ...


@dataclasses.dataclass
class AnomalyRecord:
    anomaly: Anomaly
    status: str  # DETECTED / IGNORED / CHECKED / FIX_STARTED / FIX_FAILED_TO_START
    handled_ms: int


class AnomalyDetectorState:
    """Rolling anomaly history + self-healing metrics
    (reference detector/AnomalyDetectorState.java, AnomalyMetrics.java)."""

    def __init__(self, history_size: int = 50):
        self.recent: dict[AnomalyType, deque[AnomalyRecord]] = {
            t: deque(maxlen=history_size) for t in AnomalyType
        }
        self.ignored = 0
        self.fixed = 0
        self._detection_times: dict[AnomalyType, list[int]] = {t: [] for t in AnomalyType}

    def record(self, anomaly: Anomaly, status: str, now_ms: int):
        self.recent[anomaly.anomaly_type].append(AnomalyRecord(anomaly, status, now_ms))
        self._detection_times[anomaly.anomaly_type].append(now_ms)
        if status == "IGNORED":
            self.ignored += 1
        if status == "FIX_STARTED":
            self.fixed += 1

    def mean_time_between_anomalies_ms(self, anomaly_type: AnomalyType) -> float:
        """Reference MeanTimeBetweenAnomaliesMs."""
        times = self._detection_times[anomaly_type]
        if len(times) < 2:
            return 0.0
        return (times[-1] - times[0]) / (len(times) - 1)

    def to_json(self, notifier: AnomalyNotifier) -> dict:
        healing = notifier.self_healing_enabled()
        return {
            "selfHealingEnabled": [t.name for t, on in healing.items() if on],
            "selfHealingDisabled": [t.name for t, on in healing.items() if not on],
            "recentAnomalies": {
                t.name: [
                    {
                        "description": r.anomaly.description(),
                        "status": r.status,
                        "detectionMs": r.anomaly.detected_ms,
                    }
                    for r in self.recent[t]
                ]
                for t in AnomalyType
            },
            "meanTimeBetweenAnomaliesMs": {
                t.name: self.mean_time_between_anomalies_ms(t) for t in AnomalyType
            },
            "numSelfHealingStarted": self.fixed,
            "numIgnored": self.ignored,
        }


class AnomalyDetector:
    """Queue + dispatch (reference AnomalyDetector.java:47).

    Synchronous mode: call `register_detector(...)` then `run_once()` per
    detection round (deterministic for tests and for the service's
    scheduler).  `start(interval)` runs rounds on a daemon thread like the
    reference's scheduled executor.
    """

    def __init__(
        self,
        notifier: AnomalyNotifier,
        actions: SelfHealingActions,
        *,
        now_ms=None,
        sensors=None,
        history_size: int = 10,
        tracer=None,
    ):
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.trace import TRACER

        self.notifier = notifier
        self.actions = actions
        # history_size: reference num.cached.recent.anomaly.states (default 10)
        self.state = AnomalyDetectorState(history_size=history_size)
        self.sensors = sensors if sensors is not None else REGISTRY
        #: flight recorder: each handled anomaly is a `detector.handle`
        #: ROOT span, and a FIX dispatch's whole pipeline (model build,
        #: optimize, execution) nests under it — the trace of a
        #: self-healing action reads exactly like a user request's
        self.tracer = tracer if tracer is not None else TRACER

        def _healing_ratio() -> float:
            enabled = notifier.self_healing_enabled()
            return sum(enabled.values()) / max(1, len(enabled))

        # reference AnomalyMetrics self-healing-enabled ratio sensor
        self.sensors.gauge("anomaly-detector.self-healing-enabled-ratio", _healing_ratio)
        self._queue: list[tuple[int, int, Anomaly]] = []  # (priority, seq, anomaly)
        self._seq = 0
        self._detectors: list = []  # (detect_fn, interval_s | None)
        self._next_due: list[float] = []  # monotonic deadline per detector
        self._now = now_ms or (lambda: int(time.time() * 1000))
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: re-check delays scheduled by CHECK actions: (due_ms, anomaly)
        self._delayed: list[tuple[int, int, Anomaly]] = []

    def register_detector(
        self,
        detect_fn,
        *,
        interval_s: float | None = None,
        error_backoff_s: float | None = None,
    ):
        """detect_fn() -> Anomaly | None (bound method of a detector).

        interval_s: per-detector cadence override (reference
        AnomalyDetectorConfig {goal.violation,metric.anomaly,disk.failure,
        topic.anomaly}.detection.interval.ms, :161-204); None means every
        scheduled round.  error_backoff_s: after a detector raises, it is
        not retried for this long (reference
        broker.failure.detection.backoff.ms)."""
        self._detectors.append((detect_fn, interval_s, error_backoff_s))
        self._next_due.append(0.0)

    def add_anomaly(self, anomaly: Anomaly):
        with self._lock:
            heapq.heappush(
                self._queue, (anomaly.anomaly_type.priority, self._seq, anomaly)
            )
            self._seq += 1

    # ------------------------------------------------------------------

    def run_once(self, *, respect_intervals: bool = False) -> list[AnomalyRecord]:
        """One detection + handling round.

        respect_intervals=True (the scheduled loop) skips detectors whose
        per-detector cadence has not elapsed; the default runs every
        detector — deterministic for tests and for forced rounds."""
        now = self._now()
        with self._lock:
            # re-enqueue due delayed checks
            due = [x for x in self._delayed if x[0] <= now]
            self._delayed = [x for x in self._delayed if x[0] > now]
            for _, _, anomaly in due:
                self.add_anomaly(anomaly)
        mono = time.monotonic()
        for i, (detect, interval_s, error_backoff_s) in enumerate(self._detectors):
            if respect_intervals and mono < self._next_due[i]:
                continue
            if respect_intervals:
                # only scheduled rounds advance the cadence clock — a forced
                # round must not postpone an already-due scheduled run
                self._next_due[i] = mono + (interval_s or 0.0)
            try:
                anomaly = detect()
            except Exception:  # noqa: BLE001 — a broken detector must not stop the loop
                if error_backoff_s:
                    self._next_due[i] = max(
                        self._next_due[i], mono + error_backoff_s
                    )
                continue
            if anomaly is not None:
                self.add_anomaly(anomaly)
        return self._drain()

    def _drain(self) -> list[AnomalyRecord]:
        handled = []
        while True:
            with self._lock:
                if not self._queue:
                    break
                _, _, anomaly = heapq.heappop(self._queue)
            handled.append(self._handle(anomaly))
        return handled

    def _handle(self, anomaly: Anomaly) -> AnomalyRecord:
        """Reference AnomalyHandlerTask:318."""
        with self.tracer.span(
            "detector.handle",
            component="detector",
            root=True,  # detector loop: never attach to a request context
            anomaly_type=anomaly.anomaly_type.name,
        ) as sp:
            rec = self._handle_traced(anomaly)
            sp.set(status=rec.status)
            return rec

    def _handle_traced(self, anomaly: Anomaly) -> AnomalyRecord:
        now = self._now()
        # only FIXABLE anomalies wait for the executor: an alert-only one
        # (EXECUTION_STUCK, OPTIMIZER_DEGRADED) never touches it, and
        # EXECUTION_STUCK in particular is raised DURING an execution —
        # parking it for busy re-checks would delay the operator alert
        # exactly while the wedged move is news
        if self.actions.is_busy and anomaly.fixable:
            # executor busy: re-check later (reference handleAnomalyInProgress);
            # NOT counted in the rate sensors — a busy-delayed anomaly cycling
            # through _handle is one event, not many
            with self._lock:
                self._delayed.append((now + 30_000, self._seq, anomaly))
                self._seq += 1
            rec = AnomalyRecord(anomaly, "CHECKED", now)
            self.state.record(anomaly, "CHECKED", now)
            return rec
        # per-type rate + mean-time-between-anomalies sensors (reference
        # detector/AnomalyMetrics.java, MeanTimeBetweenAnomaliesMs.java)
        self.sensors.meter(
            f"anomaly-detector.{anomaly.anomaly_type.name.lower()}.rate"
        ).mark()
        self.sensors.meter("anomaly-detector.mean-time-between-anomalies").mark()
        result = self.notifier.on_anomaly(anomaly)
        if result.action == Action.IGNORE:
            status = "IGNORED"
        elif result.action == Action.CHECK:
            with self._lock:
                self._delayed.append((now + result.delay_ms, self._seq, anomaly))
                self._seq += 1
            status = "CHECKED"
        else:
            started = self._fix(anomaly)
            status = "FIX_STARTED" if started else "FIX_FAILED_TO_START"
        self.state.record(anomaly, status, now)
        return AnomalyRecord(anomaly, status, now)

    def _fix(self, anomaly: Anomaly) -> bool:
        a = self.actions
        try:
            if isinstance(anomaly, GoalViolations):
                return a.rebalance(reason=anomaly.description())
            if isinstance(anomaly, BrokerFailures):
                return a.remove_brokers(
                    sorted(anomaly.failed_brokers), reason=anomaly.description()
                )
            if isinstance(anomaly, DiskFailures):
                return a.fix_offline_replicas(reason=anomaly.description())
            if isinstance(anomaly, SlowBrokers):
                ids = sorted(anomaly.slow_brokers)
                if anomaly.remove_slow_brokers:
                    return a.remove_brokers(ids, reason=anomaly.description())
                return a.demote_brokers(ids, reason=anomaly.description())
            if isinstance(anomaly, TopicReplicationFactorAnomaly):
                return a.fix_topic_replication_factor(
                    anomaly.bad_topics, anomaly.target_rf, reason=anomaly.description()
                )
        except Exception:  # noqa: BLE001 — fix failure is recorded, not fatal
            return False
        return False

    # ------------------------------------------------------------------

    def start(self, interval_s: float = 30.0):
        if self._thread is not None and self._thread.is_alive():
            # double-start guard: a retried facade start_up (e.g. fleet-HA
            # activation after a partial failure) must not leak a second
            # detection loop thread
            return
        # detectors without an explicit cadence run at the base interval;
        # the loop wakes often enough to honor the shortest cadence
        self._detectors = [
            (fn, i if i else interval_s, eb) for fn, i, eb in self._detectors
        ]
        tick = min([interval_s] + [i for _, i, _ in self._detectors])

        def loop():
            # individual detector exceptions are already contained inside
            # run_once; this catch covers the HANDLING side (notifier, fix
            # dispatch, state recording) — an exception escaping there used
            # to kill the thread silently and end anomaly detection for
            # the life of the process
            while not self._stop.wait(tick):
                try:
                    self.run_once(respect_intervals=True)
                except Exception:  # noqa: BLE001 — the loop must keep ticking
                    self.sensors.counter("detector.loop-failures").inc()
                    log.warning("anomaly detection round failed", exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True, name="anomaly-detector")
        self._thread.start()

    def shutdown(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def detector_state(self) -> dict:
        out = self.state.to_json(self.notifier)
        # why the last self-healing fix did not start, when the actions
        # implementation tracks it (service/facade.SelfHealingAdapter)
        info = getattr(self.actions, "fix_failure_info", None)
        if info:
            out["lastSelfHealingFixFailure"] = dict(info)
        return out
