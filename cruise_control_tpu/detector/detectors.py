"""The five scheduled anomaly detectors.

Reference: detector/GoalViolationDetector.java:48 (per-goal optimize on a
fresh model), BrokerFailureDetector.java:44 (ZK liveness watch + persisted
failure times), DiskFailureDetector.java (logdir describe),
MetricAnomalyDetector.java + SlowBrokerFinder.java:99,255-267 (percentile
history + peer comparison), TopicAnomalyDetector +
TopicReplicationFactorAnomalyFinder / PartitionSizeAnomalyFinder.

The goal-violation check showcases the TPU rebuild: where the reference
re-runs the greedy optimizer per detection goal, here one batched
chain.evaluate() on the array model prices every goal at once.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable

import numpy as np

from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.detector.anomalies import (
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    SlowBrokers,
    TopicPartitionSizeAnomaly,
    TopicReplicationFactorAnomaly,
)
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.monitor.topology import ClusterTopology


class GoalViolationDetector:
    """Reference detector/GoalViolationDetector.java:48,106.

    Uses a slacker constraint than optimization (threshold multiplier,
    reference AnalyzerConfig goal.violation.distribution.threshold.multiplier)
    so detection does not flap on clusters optimization considers balanced.
    """

    def __init__(
        self,
        model_provider: Callable[[], ClusterState],
        chain: GoalChain,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        *,
        violation_tolerance: float = 1e-6,
    ):
        self.model_provider = model_provider
        self.chain = chain
        mult = constraint.goal_violation_distribution_threshold_multiplier
        if mult != 1.0:
            constraint = dataclasses.replace(
                constraint,
                balance_threshold=tuple(
                    1.0 + (t - 1.0) * mult for t in constraint.balance_threshold
                ),
                replica_count_balance_threshold=1.0
                + (constraint.replica_count_balance_threshold - 1.0) * mult,
                leader_replica_count_balance_threshold=1.0
                + (constraint.leader_replica_count_balance_threshold - 1.0) * mult,
                topic_replica_count_balance_threshold=1.0
                + (constraint.topic_replica_count_balance_threshold - 1.0) * mult,
            )
        self.constraint = constraint
        self.tol = violation_tolerance

    def detect(self) -> GoalViolations | None:
        state = self.model_provider()
        _, violations, _ = self.chain.evaluate(state, constraint=self.constraint)
        violations = np.asarray(violations)
        names = self.chain.names()
        hard = self.chain.hard_mask()
        fixable, unfixable = [], []
        alive_cap = (
            np.asarray(state.broker_capacity)
            * (np.asarray(state.broker_alive) & np.asarray(state.broker_valid))[:, None]
        ).sum(0)
        total_load = float(np.asarray(state.replica_load_leader).sum(0)[Resource.DISK])
        for i, name in enumerate(names):
            if violations[i] <= self.tol:
                continue
            # a capacity goal whose total demand exceeds capacity is unfixable
            # by moves (reference marks unfixable via optimization failure)
            if hard[i] and name == "DiskCapacityGoal" and total_load > alive_cap[Resource.DISK]:
                unfixable.append(name)
            else:
                fixable.append(name)
        if not fixable and not unfixable:
            return None
        return GoalViolations(
            fixable_violations=fixable, unfixable_violations=unfixable
        )


class BrokerFailureDetector:
    """Reference detector/BrokerFailureDetector.java:44 — watches broker
    liveness and persists first-failure times so restarts don't reset the
    self-healing clock (reference persists to a ZK node :123-127; here a
    JSON file)."""

    def __init__(
        self,
        topology_provider: Callable[[], ClusterTopology],
        *,
        persist_path: str | None = None,
        now_ms: Callable[[], int] | None = None,
    ):
        self.topology_provider = topology_provider
        self.persist_path = persist_path
        self._now = now_ms or (lambda: int(time.time() * 1000))
        self._failure_times: dict[int, int] = {}
        self._load()

    def _load(self):
        if self.persist_path and os.path.exists(self.persist_path):
            with open(self.persist_path) as f:
                self._failure_times = {int(k): int(v) for k, v in json.load(f).items()}

    def _persist(self):
        if self.persist_path:
            with open(self.persist_path, "w") as f:
                json.dump(self._failure_times, f)

    def detect(self) -> BrokerFailures | None:
        topo = self.topology_provider()
        dead = {b.broker_id for b in topo.brokers if not b.alive}
        now = self._now()
        changed = False
        for b in dead:
            if b not in self._failure_times:
                self._failure_times[b] = now
                changed = True
        for b in list(self._failure_times):
            if b not in dead:  # broker came back
                del self._failure_times[b]
                changed = True
        if changed:
            self._persist()
        if not self._failure_times:
            return None
        return BrokerFailures(failed_brokers=dict(self._failure_times))


class DiskFailureDetector:
    """Reference detector/DiskFailureDetector.java — offline logdirs."""

    def __init__(self, topology_provider: Callable[[], ClusterTopology]):
        self.topology_provider = topology_provider

    def detect(self) -> DiskFailures | None:
        topo = self.topology_provider()
        failed = {
            b.broker_id: list(b.offline_logdirs)
            for b in topo.brokers
            if b.alive and b.offline_logdirs
        }
        if not failed:
            return None
        return DiskFailures(failed_disks=failed)


class SlowBrokerFinder:
    """Reference detector/SlowBrokerFinder.java:99,255-267.

    Multi-family evidence: each broker reports SEVERAL latency-ish metric
    families (byte-rate-normalized log-flush time, request-latency means,
    queue sizes — reference collectSlowBrokerMetrics uses byte rates AND
    request latencies).  A family votes "slow" when the broker is
    simultaneously high versus its own history (percentile) and versus
    current peers (ratio to the peer median); a broker is flagged only when
    a MAJORITY of its evaluated families agree — one noisy metric spiking
    cannot false-positive a broker.  Persistent slowness escalates from
    demote to remove.
    """

    def __init__(
        self,
        *,
        history_percentile: float = 90.0,
        peer_ratio: float = 3.0,
        history_windows: int = 20,
        #: consecutive detections before escalating to removal
        removal_threshold: int = 3,
    ):
        self.history_percentile = history_percentile
        self.peer_ratio = peer_ratio
        self.history_windows = history_windows
        self.removal_threshold = removal_threshold
        self._history: dict[tuple[int, str], list[float]] = {}
        self._strikes: dict[int, int] = {}

    def _family_votes(self, family: str, values: dict[int, float]) -> dict[int, float]:
        """-> broker -> peer-ratio for brokers this family votes slow."""
        peer_median = float(np.median(np.asarray(list(values.values()))))
        votes: dict[int, float] = {}
        for b, v in values.items():
            hist = self._history.setdefault((b, family), [])
            slow_vs_peers = peer_median > 0 and v > self.peer_ratio * peer_median
            slow_vs_history = (
                len(hist) >= 3 and v > float(np.percentile(hist, self.history_percentile))
            )
            if slow_vs_peers and (slow_vs_history or len(hist) < 3):
                votes[b] = v / max(peer_median, 1e-9)
                # anomalous samples stay out of the clean history so a
                # persistently slow broker keeps comparing against its
                # healthy baseline (reference keeps separate normal-state
                # history, SlowBrokerFinder.java:255-267)
            else:
                hist.append(v)
                del hist[: -self.history_windows]
        return votes

    def detect(
        self, broker_metrics: dict[int, float] | dict[int, dict[str, float]]
    ) -> SlowBrokers | None:
        """broker_metrics: per alive broker, either one latency value
        (single-family compatibility) or {family: value} evidence."""
        if len(broker_metrics) < 2:
            return None
        sample = next(iter(broker_metrics.values()))
        if not isinstance(sample, dict):
            broker_metrics = {b: {"metric": v} for b, v in broker_metrics.items()}

        # evaluate each family across the brokers reporting it
        by_family: dict[str, dict[int, float]] = {}
        for b, fams in broker_metrics.items():
            for f, v in fams.items():
                by_family.setdefault(f, {})[b] = v
        votes: dict[int, list[float]] = {}
        evaluated: dict[int, int] = {}
        for f, values in by_family.items():
            # a family nobody reports a nonzero value for carries no signal
            # — counting it toward the evidence bar would let unpopulated
            # metric columns (a sampler that lacks the source) silently
            # raise the majority threshold past what real data can reach
            if len(values) < 2 or all(v == 0 for v in values.values()):
                continue
            for b in values:
                evaluated[b] = evaluated.get(b, 0) + 1
            for b, ratio in self._family_votes(f, values).items():
                votes.setdefault(b, []).append(ratio)

        slow: dict[int, float] = {}
        for b, ratios in votes.items():
            need = max(1, evaluated.get(b, 1) // 2 + 1)  # STRICT majority
            if len(ratios) >= need:
                slow[b] = float(np.mean(ratios))
                self._strikes[b] = self._strikes.get(b, 0) + 1
        for b in evaluated:
            if b not in slow:
                self._strikes.pop(b, None)
        if not slow:
            return None
        remove = any(self._strikes.get(b, 0) >= self.removal_threshold for b in slow)
        return SlowBrokers(slow_brokers=slow, remove_slow_brokers=remove)


class TopicReplicationFactorAnomalyFinder:
    """Reference detector/TopicReplicationFactorAnomalyFinder.java — topics
    whose partitions run below the target replication factor."""

    def __init__(
        self,
        topology_provider: Callable[[], ClusterTopology],
        target_rf: int = 2,
        topic_config_provider=None,
    ):
        """topic_config_provider (reference topic.config.provider.class):
        when present, a topic's effective floor is
        max(target_rf, min.insync.replicas + 1) — RF == minISR cannot
        survive a broker loss without dropping under min-ISR."""
        self.topology_provider = topology_provider
        self.target_rf = target_rf
        self.topic_config_provider = topic_config_provider

    def detect(self) -> TopicReplicationFactorAnomaly | None:
        from cruise_control_tpu.monitor.topic_config import min_insync_replicas_map

        topo = self.topology_provider()
        topics = sorted({p.topic for p in topo.partitions})
        floors = {t: self.target_rf for t in topics}
        if self.topic_config_provider is not None:
            # one batch DescribeConfigs for ALL topics per detection tick
            for t, min_isr in min_insync_replicas_map(
                self.topic_config_provider, topics
            ).items():
                floors[t] = max(floors[t], min_isr + 1)
        bad: dict[str, int] = {}
        for p in topo.partitions:
            rf = len(p.replicas)
            if rf < floors[p.topic]:
                bad[p.topic] = min(bad.get(p.topic, rf), rf)
        if not bad:
            return None
        return TopicReplicationFactorAnomaly(bad_topics=bad, target_rf=self.target_rf)


class PartitionSizeAnomalyFinder:
    """Reference detector/PartitionSizeAnomalyFinder.java — partitions whose
    disk footprint exceeds a threshold."""

    def __init__(
        self,
        model_provider: Callable[[], ClusterState],
        catalog_provider: Callable[[], object],
        max_partition_size: float = 1e6,
        excluded_topics_pattern: str = "",
    ):
        """max_partition_size (reference
        self.healing.partition.size.threshold.byte, default 500MiB);
        excluded_topics_pattern (reference
        topic.excluded.from.partition.size.check)."""
        import re

        self.model_provider = model_provider
        self.catalog_provider = catalog_provider
        self.max_partition_size = max_partition_size
        self._excluded = (
            re.compile(excluded_topics_pattern) if excluded_topics_pattern else None
        )

    def detect(self) -> TopicPartitionSizeAnomaly | None:
        state = self.model_provider()
        catalog = self.catalog_provider()
        lead = np.asarray(state.replica_is_leader) & np.asarray(state.replica_valid)
        sizes = np.asarray(state.replica_load_leader)[:, Resource.DISK]
        parts = np.asarray(state.replica_partition)
        oversized: dict[tuple[str, int], float] = {}
        for r in np.nonzero(lead & (sizes > self.max_partition_size))[0]:
            key = catalog.partition_key(int(parts[r])) if catalog else ("?", int(parts[r]))
            if self._excluded is not None and self._excluded.fullmatch(key[0]):
                continue
            oversized[key] = float(sizes[r])
        if not oversized:
            return None
        return TopicPartitionSizeAnomaly(oversized=oversized)
