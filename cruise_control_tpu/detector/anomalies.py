"""Anomaly taxonomy.

Reference: cruise-control-core detector/Anomaly.java + AnomalyType.java
(SPI) and the main-module payloads: detector/GoalViolations.java,
BrokerFailures.java, DiskFailures.java, SlowBrokers.java,
TopicReplicationFactorAnomaly.java, TopicPartitionSizeAnomaly.java.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time


class AnomalyType(enum.Enum):
    """Reference KafkaAnomalyType; priority order matters — lower value is
    handled first (reference AnomalyDetector priority queue)."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    #: the optimizer's device supervisor opened its circuit breaker —
    #: proposals are being served by the CPU greedy fallback (no reference
    #: analog: the reference has no accelerator to lose)
    OPTIMIZER_DEGRADED = 5
    #: the executor's stuck-move reaper cancelled a reassignment whose
    #: progress watermark stalled past executor.reaper.stuck.timeout.s
    EXECUTION_STUCK = 6
    #: this instance lost a cluster's ownership lease (fleet HA) — the
    #: cluster stepped down to read-only degraded mode while a peer
    #: instance takes over execution
    FLEET_LEASE_LOST = 7
    #: the device scheduler's overload protection engaged (fleet
    #: scheduler, fleet/scheduler.py): background cycles are being shed
    #: or browned out and interactive admissions may 429 — the shared
    #: device cannot keep up with the fleet's demand
    FLEET_OVERLOAD = 8
    #: an SLO's error budget is burning past its multi-window threshold
    #: (common/slo.py): proposal freshness, streaming publish latency,
    #: cold-start or urgent queue-wait is sustainedly out of objective
    SLO_BURN = 9
    #: the decision ledger's calibration loop (analyzer/ledger.py +
    #: service/facade.py) measured SUSTAINED prediction error: the goal
    #: scores/broker loads the engine predicted for executed proposals
    #: keep diverging from what the cluster actually measured afterwards
    MODEL_DRIFT = 10
    #: a mesh anneal lost a device (or a collective stalled on one) and
    #: the optimizer degraded to a narrower mesh width, resuming from the
    #: last carry checkpoint (parallel/ft.py) — capacity is reduced but
    #: proposals are still device-served
    MESH_DEGRADED = 11

    @property
    def priority(self) -> int:
        return self.value


_ids = itertools.count()


@dataclasses.dataclass
class Anomaly:
    anomaly_type: AnomalyType
    detected_ms: int = dataclasses.field(default_factory=lambda: int(time.time() * 1000))
    anomaly_id: int = dataclasses.field(default_factory=lambda: next(_ids))
    #: whether the fix path is expected to change anything
    fixable: bool = True

    def description(self) -> str:
        return self.anomaly_type.name

    def __lt__(self, other: "Anomaly") -> bool:
        return (self.anomaly_type.priority, self.detected_ms) < (
            other.anomaly_type.priority,
            other.detected_ms,
        )


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """Reference detector/GoalViolations.java — which goals are violated,
    split by whether optimization could fix them."""

    anomaly_type: AnomalyType = AnomalyType.GOAL_VIOLATION
    fixable_violations: list[str] = dataclasses.field(default_factory=list)
    unfixable_violations: list[str] = dataclasses.field(default_factory=list)

    def description(self) -> str:
        return (
            f"GoalViolations(fixable={self.fixable_violations}, "
            f"unfixable={self.unfixable_violations})"
        )


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """Reference detector/BrokerFailures.java."""

    anomaly_type: AnomalyType = AnomalyType.BROKER_FAILURE
    failed_brokers: dict[int, int] = dataclasses.field(default_factory=dict)  # id -> failed_ms

    def description(self) -> str:
        return f"BrokerFailures({sorted(self.failed_brokers)})"


@dataclasses.dataclass
class DiskFailures(Anomaly):
    """Reference detector/DiskFailures.java — (broker -> offline logdirs)."""

    anomaly_type: AnomalyType = AnomalyType.DISK_FAILURE
    failed_disks: dict[int, list[str]] = dataclasses.field(default_factory=dict)

    def description(self) -> str:
        return f"DiskFailures({self.failed_disks})"


@dataclasses.dataclass
class SlowBrokers(Anomaly):
    """Reference detector/SlowBrokers.java (a MetricAnomaly flavor)."""

    anomaly_type: AnomalyType = AnomalyType.METRIC_ANOMALY
    slow_brokers: dict[int, float] = dataclasses.field(default_factory=dict)  # id -> severity
    #: remove (true) vs demote (false) — reference SlowBrokerFinder config
    remove_slow_brokers: bool = False

    def description(self) -> str:
        return f"SlowBrokers({self.slow_brokers}, remove={self.remove_slow_brokers})"


@dataclasses.dataclass
class TopicReplicationFactorAnomaly(Anomaly):
    """Reference detector/TopicReplicationFactorAnomaly.java."""

    anomaly_type: AnomalyType = AnomalyType.TOPIC_ANOMALY
    bad_topics: dict[str, int] = dataclasses.field(default_factory=dict)  # topic -> observed RF
    target_rf: int = 2

    def description(self) -> str:
        return f"TopicReplicationFactorAnomaly({self.bad_topics} -> rf={self.target_rf})"


@dataclasses.dataclass
class OptimizerDegraded(Anomaly):
    """The device supervisor's circuit breaker opened: the optimizer is
    serving CPU-greedy proposals (common/device_watchdog.DeviceSupervisor).

    Not self-healable by this detector — recovery is the supervisor's
    half-open probe closing the breaker — so fixable=False: the notifier
    alerts operators and the anomaly is recorded, nothing is 'fixed'."""

    anomaly_type: AnomalyType = AnomalyType.OPTIMIZER_DEGRADED
    failure_class: str = "unknown"  # hang / compile / oom / transient
    last_error: str = ""
    open_epoch: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"OptimizerDegraded(class={self.failure_class}, "
            f"epoch={self.open_epoch}, last_error={self.last_error!r})"
        )


@dataclasses.dataclass
class ExecutionStuck(Anomaly):
    """The executor's stuck-move reaper cancelled a reassignment that made
    no progress for executor.reaper.stuck.timeout.s (executor/executor.py
    _reap_stuck_move).

    Not self-healable: the reaper already acted (rollback via per-partition
    cancellation, or DEAD when the controller cannot cancel) — the anomaly
    exists so operators hear about the wedged move through the notifier and
    it lands in the /state anomaly history."""

    anomaly_type: AnomalyType = AnomalyType.EXECUTION_STUCK
    topic: str = ""
    partition: int = -1
    execution_id: int = -1
    uuid: str = ""
    stalled_s: float = 0.0
    #: True when the controller rolled the partition back to its original
    #: replica set; False means the task was declared DEAD
    rolled_back: bool = False
    fixable: bool = False

    def description(self) -> str:
        return (
            f"ExecutionStuck({self.topic}-{self.partition}, "
            f"task={self.execution_id}, stalled={self.stalled_s:.0f}s, "
            f"{'rolled back' if self.rolled_back else 'DEAD'})"
        )


@dataclasses.dataclass
class FleetLeaseLost(Anomaly):
    """This instance's lease on a cluster expired or was taken over
    (fleet/leases.py) — the cluster is now in read-only degraded mode
    here: proposals//state//fleet keep serving, the executor halted via
    the force-stop path, and every further journal append or cluster
    mutation is fenced on the stale epoch.

    Not self-healable: recovery is either re-acquiring the lease (the
    heartbeat keeps trying) or the peer holder serving the cluster —
    alert-only, like OPTIMIZER_DEGRADED."""

    anomaly_type: AnomalyType = AnomalyType.FLEET_LEASE_LOST
    cluster_id: str = ""
    instance_id: str = ""
    epoch: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"FleetLeaseLost(cluster={self.cluster_id}, "
            f"instance={self.instance_id}, epoch={self.epoch})"
        )


@dataclasses.dataclass
class FleetOverload(Anomaly):
    """The device scheduler entered an overload episode
    (fleet/scheduler.py): the engine-dispatch queue breached its
    depth/deadline-miss threshold, so background cycles are being shed
    (or browned out under sustained overload) and interactive admissions
    may be 429'd.  Fired ONCE per episode by the scheduler itself.

    Not self-healable by the detector: the scheduler's shed/brownout
    ladder IS the mitigation — alert-only, like OPTIMIZER_DEGRADED, so
    operators learn the instance is past its density budget (add an
    instance, or shard the fleet: ROADMAP item 2c)."""

    anomaly_type: AnomalyType = AnomalyType.FLEET_OVERLOAD
    queue_depth: int = 0
    deadline_miss_ratio: float = 0.0
    episode: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"FleetOverload(episode={self.episode}, "
            f"queueDepth={self.queue_depth}, "
            f"missRatio={self.deadline_miss_ratio})"
        )


@dataclasses.dataclass
class SloBurn(Anomaly):
    """An SLO registry (common/slo.py) observed its error budget burning
    at >= `slo.burn.threshold` times the sustainable rate over BOTH the
    fast and the slow window — a sustained breach, not a blip.  Fired
    EXACTLY once per breach episode by the registry itself; the episode
    re-arms only after the fast window recovers.

    Not self-healable by the detector: whatever is burning the budget
    (overload, a wedged device, a slow cold start) has its own
    mitigation ladder — alert-only, like OPTIMIZER_DEGRADED and
    FLEET_OVERLOAD, so operators hear the objective is at risk while
    the budget still has headroom."""

    anomaly_type: AnomalyType = AnomalyType.SLO_BURN
    slo: str = ""
    cluster_id: str = ""
    objective: float = 0.0
    fast_burn_rate: float = 0.0
    slow_burn_rate: float = 0.0
    episode: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"SloBurn(slo={self.slo}, cluster={self.cluster_id or '-'}, "
            f"objective={self.objective}, burn={self.fast_burn_rate}x fast / "
            f"{self.slow_burn_rate}x slow, episode={self.episode})"
        )


@dataclasses.dataclass
class ModelDrift(Anomaly):
    """The calibration loop observed SUSTAINED prediction error: across
    the last `samples` calibrated executions, the mean absolute error
    between the goal scores the engine PREDICTED (decision records,
    analyzer/ledger.py) and the scores MEASURED after the moves landed
    crossed `analyzer.calibration.drift.threshold`.  Fired EXACTLY once
    per drift episode by the facade's calibration detector; the episode
    re-arms once the mean error falls back under the threshold.

    Not self-healable: a drifting model means the capacity model / goal
    chain inputs (broker capacities, CPU model, sample quality) need a
    human look — alert-only, like OPTIMIZER_DEGRADED."""

    anomaly_type: AnomalyType = AnomalyType.MODEL_DRIFT
    cluster_id: str = ""
    samples: int = 0
    mean_goal_error: float = 0.0
    mean_load_error: float = 0.0
    threshold: float = 0.0
    episode: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"ModelDrift(cluster={self.cluster_id or '-'}, "
            f"goalErr={self.mean_goal_error:.4g}, "
            f"loadErr={self.mean_load_error:.4g} over {self.samples} "
            f"calibrations, threshold={self.threshold:.4g}, "
            f"episode={self.episode})"
        )


@dataclasses.dataclass
class MeshDegraded(Anomaly):
    """A mesh anneal lost one or more devices (or a collective stalled on
    them) and the optimizer's fault-tolerance ladder (parallel/ft.py)
    rebuilt the mesh over the survivors at a reduced width, resuming from
    the last slice-boundary checkpoint.

    Fired EXACTLY once per degrade episode by the facade's mesh-ft
    detector; the episode re-arms when a run completes back at full
    width.  Not self-healable by this detector — the width ladder IS the
    mitigation, and recovery to full width is the per-width breaker's
    half-open probe — so alert-only, like OPTIMIZER_DEGRADED."""

    anomaly_type: AnomalyType = AnomalyType.MESH_DEGRADED
    lost_devices: list[int] = dataclasses.field(default_factory=list)
    from_width: int = 0
    to_width: int = 0
    failure_class: str = "unknown"  # device_lost / collective_stall
    episode: int = 0
    fixable: bool = False

    def description(self) -> str:
        return (
            f"MeshDegraded(lost={self.lost_devices}, "
            f"width={self.from_width}->{self.to_width}, "
            f"class={self.failure_class}, episode={self.episode})"
        )


@dataclasses.dataclass
class TopicPartitionSizeAnomaly(Anomaly):
    """Reference detector/TopicPartitionSizeAnomaly.java."""

    anomaly_type: AnomalyType = AnomalyType.TOPIC_ANOMALY
    oversized: dict[tuple[str, int], float] = dataclasses.field(default_factory=dict)
    fixable: bool = False  # reference: self-healing not supported for this one

    def description(self) -> str:
        return f"TopicPartitionSizeAnomaly({len(self.oversized)} partitions)"
