"""Synthetic workload generation against a ClusterTopology.

Plays the role the embedded-cluster harness plays in the reference tests
(reference CCKafkaIntegrationTestHarness + CruiseControlMetricsReporter
producing real metrics): a MetricSampler implementation that fabricates
plausible per-partition metric samples so the whole monitor -> analyzer ->
executor -> detector pipeline can run without a Kafka cluster.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, MetricDef
from cruise_control_tpu.monitor.sampling import (
    BrokerEntity,
    MetricSample,
    PartitionEntity,
    SamplingResult,
)
from cruise_control_tpu.monitor.topology import ClusterTopology


@dataclasses.dataclass
class WorkloadSpec:
    mean_cpu: float = 1.0
    mean_nw_in: float = 200.0
    mean_nw_out: float = 240.0
    mean_disk: float = 1000.0
    deviation: float = 0.3  # lognormal sigma across partitions
    jitter: float = 0.05  # per-sample noise
    #: per-topic multipliers to create hot topics
    topic_multipliers: dict[str, float] = dataclasses.field(default_factory=dict)


class SyntheticWorkloadSampler:
    """Deterministic per-partition workload with per-sample jitter."""

    def __init__(
        self,
        topology: ClusterTopology,
        spec: WorkloadSpec | None = None,
        *,
        metric_def: MetricDef = KAFKA_METRIC_DEF,
        seed: int = 0,
    ):
        self.topology = topology
        self.spec = spec or WorkloadSpec()
        self.metric_def = metric_def
        self._rng = np.random.default_rng(seed)
        self._topic_ids: dict[str, int] = {}
        for p in topology.partitions:
            self._topic_ids.setdefault(p.topic, len(self._topic_ids))
        # per-partition base rates, fixed at construction
        self._base: dict[tuple[int, int], np.ndarray] = {}
        s = self.spec
        for p in topology.partitions:
            mult = s.topic_multipliers.get(p.topic, 1.0)
            base = np.array(
                [s.mean_cpu, s.mean_nw_in, s.mean_nw_out, s.mean_disk], np.float64
            ) * mult * np.exp(self._rng.normal(0.0, s.deviation, 4))
            self._base[(self._topic_ids[p.topic], p.partition)] = base

    def topic_id(self, topic: str) -> int:
        return self._topic_ids[topic]

    def get_samples(self, assigned_partitions, start_ms: int, end_ms: int) -> SamplingResult:
        m = self.metric_def
        cpu = m.metric_id("CPU_USAGE")
        nwin = m.metric_id("LEADER_BYTES_IN")
        nwout = m.metric_id("LEADER_BYTES_OUT")
        disk = m.metric_id("DISK_USAGE")
        rep_in = m.metric_id("REPLICATION_BYTES_IN_RATE")
        t = (start_ms + end_ms) // 2
        samples = []
        for e in assigned_partitions:
            base = self._base.get((e.topic, e.partition))
            if base is None:
                continue
            noise = np.exp(self._rng.normal(0.0, self.spec.jitter, 4))
            vals = np.zeros(m.num_metrics, np.float32)
            vals[cpu] = base[0] * noise[0]
            vals[nwin] = base[1] * noise[1]
            vals[nwout] = base[2] * noise[2]
            vals[disk] = base[3] * noise[3]
            samples.append(MetricSample(e, t, vals))
        # per-broker samples with CPU linear in byte rates — gives the
        # /train regression a learnable ground truth (reference: the broker
        # reporter emits BrokerMetricSamples the TrainingTask harvests).
        # Only the ASSIGNED partitions contribute, so sub-batch fetches
        # don't double-count broker rates.
        assigned = {(e.topic, e.partition) for e in assigned_partitions}
        broker_samples = []
        per_broker: dict[int, np.ndarray] = {}
        for p in self.topology.partitions:
            key = (self._topic_ids[p.topic], p.partition)
            if key not in assigned:
                continue
            base = self._base.get(key)
            if base is None:
                continue
            for b in p.replicas:
                row = per_broker.setdefault(b, np.zeros(3, np.float64))
                if b == p.leader:
                    row[0] += base[1]  # leader bytes in
                    row[1] += base[2]  # leader bytes out
                else:
                    row[2] += base[1]  # replication (follower) bytes in
        for b, (lbin, lbout, fbin) in sorted(per_broker.items()):
            vals = np.zeros(m.num_metrics, np.float32)
            noise = float(np.exp(self._rng.normal(0.0, self.spec.jitter)))
            vals[nwin] = lbin
            vals[nwout] = lbout
            vals[rep_in] = fbin
            vals[cpu] = (2e-4 * lbin + 5e-5 * lbout + 1e-4 * fbin) * noise
            broker_samples.append(MetricSample(BrokerEntity(b), t, vals))
        return SamplingResult(samples, broker_samples)

    def drift(self, factor: float, topic: str | None = None) -> None:
        """Scale the per-partition base rates in place — a deterministic
        load trend for streaming-controller tests and `bench.py
        --streaming` (real clusters drift between metric windows; the
        static base would make every window's delta zero)."""
        tid = None if topic is None else self._topic_ids.get(topic)
        for (t, _p), base in self._base.items():
            if tid is None or t == tid:
                base *= factor

    def all_partition_entities(self) -> list[PartitionEntity]:
        return [
            PartitionEntity(self._topic_ids[p.topic], p.partition)
            for p in self.topology.partitions
        ]


def synthetic_topology(
    num_brokers: int = 6,
    num_racks: int = 3,
    topics: dict[str, int] | None = None,
    replication: int = 2,
    *,
    dead_brokers: tuple[int, ...] = (),
    seed: int = 0,
) -> ClusterTopology:
    """Small random topology for integration-style tests."""
    from cruise_control_tpu.monitor.topology import BrokerNode, PartitionInfo

    rng = np.random.default_rng(seed)
    topics = topics or {"T0": 8, "T1": 8}
    brokers = tuple(
        BrokerNode(
            i,
            rack=f"r{i % num_racks}",
            host=f"h{i}",
            alive=i not in dead_brokers,
        )
        for i in range(num_brokers)
    )
    parts = []
    for t, n in topics.items():
        for p in range(n):
            reps = rng.choice(num_brokers, size=min(replication, num_brokers), replace=False)
            parts.append(
                PartitionInfo(t, p, leader=int(reps[0]), replicas=tuple(int(x) for x in reps))
            )
    return ClusterTopology(brokers=brokers, partitions=tuple(parts))
