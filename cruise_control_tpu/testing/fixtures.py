"""Synthetic cluster fixtures for tests and benchmarks.

Semantics modeled on the reference's test generators:
  * deterministic fixtures — reference
    cruise-control/src/test/java/.../common/DeterministicCluster.java
  * randomized generator — reference
    cruise-control/src/test/java/.../model/RandomCluster.java:36-100
These are re-designed (not ported): they emit array-encoded ClusterState
directly via ClusterModelBuilder.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models.builder import BrokerSpec, ClusterModelBuilder, PartitionSpec
from cruise_control_tpu.models.state import ClusterState


def small_cluster() -> ClusterState:
    """3 brokers on 3 racks, 2 topics, deliberately unbalanced.

    Loose analog of DeterministicCluster.smallClusterModel (reference
    common/DeterministicCluster.java:52-149): broker 0 overloaded, broker 2
    nearly empty.
    """
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    b.add_broker(BrokerSpec(0, rack="r0", capacity=cap))
    b.add_broker(BrokerSpec(1, rack="r1", capacity=cap))
    b.add_broker(BrokerSpec(2, rack="r2", capacity=cap))
    loads = {
        ("T1", 0): [18.0, 90.0, 100.0, 750.0],
        ("T1", 1): [15.0, 80.0, 90.0, 650.0],
        ("T2", 0): [12.0, 70.0, 80.0, 550.0],
        ("T2", 1): [10.0, 60.0, 70.0, 450.0],
    }
    # all leaders and most replicas piled on broker 0
    b.add_partition(PartitionSpec("T1", 0, [0, 1], np.array(loads[("T1", 0)], np.float32)))
    b.add_partition(PartitionSpec("T1", 1, [0, 1], np.array(loads[("T1", 1)], np.float32)))
    b.add_partition(PartitionSpec("T2", 0, [0, 2], np.array(loads[("T2", 0)], np.float32)))
    b.add_partition(PartitionSpec("T2", 1, [0, 1], np.array(loads[("T2", 1)], np.float32)))
    return b.build()


def rack_violated_cluster() -> ClusterState:
    """Both replicas of each partition on the same rack — RackAwareGoal must fix.

    Analog of DeterministicCluster.rackAwareSatisfiable semantics
    (reference common/DeterministicCluster.java:178-206).
    """
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    b.add_broker(BrokerSpec(0, rack="r0", capacity=cap))
    b.add_broker(BrokerSpec(1, rack="r0", capacity=cap))
    b.add_broker(BrokerSpec(2, rack="r1", capacity=cap))
    b.add_broker(BrokerSpec(3, rack="r1", capacity=cap))
    load = np.array([5.0, 20.0, 25.0, 100.0], np.float32)
    b.add_partition(PartitionSpec("T1", 0, [0, 1], load))  # same rack r0
    b.add_partition(PartitionSpec("T1", 1, [2, 3], load))  # same rack r1
    b.add_partition(PartitionSpec("T1", 2, [0, 2], load))  # ok
    return b.build()


def dead_broker_cluster() -> ClusterState:
    """4 brokers, one dead — self-healing must evacuate it.

    Analog of DeterministicCluster dead-broker fixtures (reference
    common/DeterministicCluster.java:356)."""
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    for i in range(4):
        b.add_broker(BrokerSpec(i, rack=f"r{i % 2}", capacity=cap, alive=(i != 3)))
    load = np.array([4.0, 15.0, 20.0, 80.0], np.float32)
    for p in range(6):
        brokers = [(p + i) % 4 for i in range(2)]
        b.add_partition(PartitionSpec("T1", p, brokers, load))
    return b.build()


def jbod_cluster() -> ClusterState:
    """4 JBOD brokers (2 logdirs each, one failed) with skewed disk load —
    exercises the intra-broker disk axes (D > 1, bad_disks) that the
    single-disk fixtures never touch."""
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 3000.0], np.float32)
    b.add_broker(BrokerSpec(0, rack="r0", capacity=cap, disk_capacities=[1000.0, 2000.0]))
    b.add_broker(BrokerSpec(1, rack="r0", capacity=cap,
                            disk_capacities=[1500.0, 1500.0], bad_disks=[1]))
    b.add_broker(BrokerSpec(2, rack="r1", capacity=cap, disk_capacities=[2000.0, 1000.0]))
    b.add_broker(BrokerSpec(3, rack="r1", capacity=cap, disk_capacities=[1500.0, 1500.0]))
    load = np.array([5.0, 40.0, 50.0, 400.0], np.float32)
    for p in range(6):
        brokers = [p % 4, (p + 1) % 4]
        b.add_partition(PartitionSpec(
            "T1", p, brokers, load, replica_disks=[p % 2, 0]
        ))
    return b.build()


@dataclasses.dataclass
class RandomClusterSpec:
    """Knobs of the random generator (reference common/ClusterProperty.java)."""

    num_brokers: int = 50
    num_racks: int = 5
    num_topics: int = 20
    num_partitions: int = 1000
    min_replication: int = 2
    max_replication: int = 3
    mean_cpu: float = 2.0  # per-partition leader CPU %
    mean_nw_in: float = 100.0
    mean_nw_out: float = 120.0
    mean_disk: float = 500.0
    deviation: float = 0.5  # lognormal-ish spread
    broker_capacity: tuple[float, float, float, float] = (100.0, 20_000.0, 20_000.0, 500_000.0)
    num_dead_brokers: int = 0
    num_new_brokers: int = 0
    skew: float = 0.0  # 0 = uniform placement; >0 biases placement to low-id brokers
    replica_capacity: int | None = None  # pad replica axis to this
    disks_per_broker: int = 1  # >1 = JBOD (reference config/capacityJBOD.json)


def random_cluster(spec: RandomClusterSpec, seed: int = 0) -> ClusterState:
    rng = np.random.default_rng(seed)
    b = ClusterModelBuilder(replica_capacity=spec.replica_capacity)
    cap = np.asarray(spec.broker_capacity, np.float32)
    D = max(1, spec.disks_per_broker)
    disks = (
        [float(cap[Resource.DISK]) / D] * D if D > 1 else None
    )  # JBOD: split capacity evenly across logdirs
    for i in range(spec.num_brokers):
        alive = i < spec.num_brokers - spec.num_dead_brokers
        new = i >= spec.num_brokers - spec.num_new_brokers if alive else False
        b.add_broker(
            BrokerSpec(i, rack=f"r{i % spec.num_racks}", capacity=cap, alive=alive,
                       new_broker=new, disk_capacities=disks)
        )
    means = np.array(
        [spec.mean_cpu, spec.mean_nw_in, spec.mean_nw_out, spec.mean_disk], np.float64
    )
    # placement weights: optionally skewed so the cluster starts unbalanced
    w = np.exp(-spec.skew * np.arange(spec.num_brokers) / max(1, spec.num_brokers - 1))
    # round-robin topic assignment so exactly num_partitions are generated
    for pid in range(spec.num_partitions):
        t = pid % spec.num_topics
        p = pid // spec.num_topics
        rf = int(rng.integers(spec.min_replication, spec.max_replication + 1))
        rf = min(rf, spec.num_brokers)
        brokers = rng.choice(spec.num_brokers, size=rf, replace=False, p=w / w.sum()).tolist()
        load = (means * np.exp(rng.normal(0.0, spec.deviation, NUM_RESOURCES))).astype(np.float32)
        rdisks = [int(x) for x in rng.integers(0, D, size=rf)] if D > 1 else None
        b.add_partition(
            PartitionSpec(f"T{t}", p, [int(x) for x in brokers], load, replica_disks=rdisks)
        )
    return b.build()


def random_cluster_fast(spec: RandomClusterSpec, seed: int = 0) -> ClusterState:
    """Vectorized large-cluster generator (bench scale: 200k partitions).

    Same distribution semantics as random_cluster but builds the ClusterState
    arrays directly with numpy — the per-partition Python loop of the
    builder is O(minutes) at LinkedIn scale, this is O(seconds).
    Weighted placement samples iid from the skew distribution and
    resamples the (rare) rows that drew duplicate brokers.
    """
    import jax.numpy as jnp

    from cruise_control_tpu.models.builder import default_follower_load
    from cruise_control_tpu.models.state import ClusterShape

    rng = np.random.default_rng(seed)
    B, P, T = spec.num_brokers, spec.num_partitions, spec.num_topics
    alive_count = B - spec.num_dead_brokers

    # broker axis
    cap = np.tile(np.asarray(spec.broker_capacity, np.float32), (B, 1))
    rack = (np.arange(B) % spec.num_racks).astype(np.int32)
    host = np.arange(B, dtype=np.int32)
    alive = np.arange(B) < alive_count
    new = np.zeros(B, bool)
    if spec.num_new_brokers:
        new[alive_count - spec.num_new_brokers: alive_count] = True

    # replication factors + replica slots
    rf = rng.integers(spec.min_replication, spec.max_replication + 1, size=P)
    rf = np.minimum(rf, B)
    R = int(rf.sum())
    r_part = np.repeat(np.arange(P, dtype=np.int32), rf)
    r_pos = (np.arange(R) - np.repeat(np.cumsum(rf) - rf, rf)).astype(np.int32)
    r_topic = (r_part % T).astype(np.int32)

    # weighted iid placement + duplicate fixup
    w = np.exp(-spec.skew * np.arange(B) / max(1, B - 1))
    cdf = np.cumsum(w / w.sum())
    r_broker = np.searchsorted(cdf, rng.random(R)).astype(np.int32)
    max_rf = int(rf.max())
    for _ in range(64):
        # detect duplicate (partition, broker) pairs
        key = r_part.astype(np.int64) * B + r_broker
        order = np.argsort(key, kind="stable")
        dup_sorted = np.zeros(R, bool)
        dup_sorted[1:] = key[order][1:] == key[order][:-1]
        dup = np.zeros(R, bool)
        dup[order] = dup_sorted
        if not dup.any():
            break
        r_broker[dup] = np.searchsorted(cdf, rng.random(int(dup.sum()))).astype(np.int32)
    else:
        raise RuntimeError("could not de-duplicate placement (too few brokers?)")

    # loads: per-partition lognormal around the means, shared by replicas
    means = np.array(
        [spec.mean_cpu, spec.mean_nw_in, spec.mean_nw_out, spec.mean_disk], np.float64
    )
    p_load = (means * np.exp(rng.normal(0.0, spec.deviation, (P, NUM_RESOURCES)))).astype(
        np.float32
    )
    r_ll = p_load[r_part]
    r_fl = np.stack([default_follower_load(row) for row in np.zeros((1, 4), np.float32)])
    # vectorized follower load: NW_OUT -> 0, CPU -> 0.3x
    r_fl = r_ll.copy()
    r_fl[:, Resource.NW_OUT] = 0.0
    r_fl[:, Resource.CPU] *= 0.3

    r_leader = r_pos == 0
    r_offline = ~alive[r_broker]

    D = max(1, spec.disks_per_broker)
    shape = ClusterShape(
        num_replicas=R,
        num_brokers=B,
        num_partitions=P,
        num_topics=T,
        num_racks=spec.num_racks,
        num_hosts=B,
        max_disks_per_broker=D,
    )
    # JBOD: split broker disk capacity evenly across D logdirs and place
    # replicas on random disks (reference config/capacityJBOD.json semantics)
    disk_cap = np.tile(cap[:, Resource.DISK:Resource.DISK + 1] / D, (1, D)).copy()
    r_disk = (
        rng.integers(0, D, R).astype(np.int32) if D > 1 else np.zeros(R, np.int32)
    )
    return ClusterState(
        replica_broker=jnp.asarray(r_broker),
        replica_partition=jnp.asarray(r_part),
        replica_topic=jnp.asarray(r_topic),
        replica_pos=jnp.asarray(r_pos),
        replica_is_leader=jnp.asarray(r_leader),
        replica_valid=jnp.ones(R, bool),
        replica_orig_broker=jnp.asarray(r_broker.copy()),
        replica_offline=jnp.asarray(r_offline),
        replica_disk=jnp.asarray(r_disk),
        replica_load_leader=jnp.asarray(r_ll),
        replica_load_follower=jnp.asarray(r_fl),
        broker_capacity=jnp.asarray(cap),
        broker_rack=jnp.asarray(rack),
        broker_host=jnp.asarray(host),
        broker_alive=jnp.asarray(alive),
        broker_new=jnp.asarray(new),
        broker_valid=jnp.ones(B, bool),
        disk_capacity=jnp.asarray(disk_cap),
        disk_alive=jnp.asarray(np.tile(alive[:, None], (1, D)).copy()),
        shape=shape,
    )
