"""In-process fake Kafka cluster speaking the real wire protocol.

The analog of the reference's embedded-cluster test harness
(CCEmbeddedBroker/CCKafkaIntegrationTestHarness,
cruise-control-metrics-reporter/src/test/java/.../utils/): contract tests
drive the production `KafkaClusterAdmin` through REAL sockets and REAL
binary frames against this server, so the codec, framing, routing, and
adapter logic are all exercised end to end without a JVM.

One listener thread per fake broker node (each on its own ephemeral port —
the client routes per-broker requests like DescribeLogDirs by address);
all listeners share one cluster state.  Reassignments park in an
in-progress set until `complete_reassignments()` — mirroring
SimulatedClusterAdmin.tick so both backends satisfy the same contract
suite; `auto_complete_after(n)` finishes them after n list polls to
exercise the executor's progress loop.
"""

from __future__ import annotations

import socket
import struct
import threading

from cruise_control_tpu.kafka import protocol as proto
from cruise_control_tpu.kafka.client import NO_REASSIGNMENT_IN_PROGRESS


class FakeKafkaCluster:
    def __init__(
        self,
        brokers: dict[int, dict],
        topics: dict[str, list[dict]],
        *,
        controller: int | None = None,
        scram_users: dict[str, str] | None = None,
    ):
        """brokers: id -> {"rack": str, "logdirs": [path, ...]}
        topics: name -> [{"partition", "leader", "replicas"}]
        scram_users: username -> password; when set, every connection must
        complete a SaslHandshake + SCRAM exchange before any other API
        (a SASL-only listener, like a secured real cluster)."""
        self._lock = threading.RLock()
        self.controller = controller if controller is not None else min(brokers)
        self.brokers: dict[int, dict] = {}
        self.topics = {
            t: {p["partition"]: dict(p) for p in parts} for t, parts in topics.items()
        }
        #: (topic, partition) -> target replica list
        self.reassignments: dict[tuple[str, int], list[int]] = {}
        #: (resource_type, name) -> {config: value}
        self.configs: dict[tuple[int, str], dict[str, str]] = {}
        #: logdir placement: broker -> path -> set[(topic, partition)]
        self.placement: dict[int, dict[str, set]] = {}
        #: >0 makes AlterReplicaLogDirs copies GRADUAL: the replica shows as
        #: a future replica under the target dir for this many
        #: DescribeLogDirs polls before the move applies (models
        #: KIP-113 async logdir copies)
        self.intra_copy_polls = 0
        #: broker -> {(topic, partition): [target path, polls left]}
        self.future_replicas: dict[int, dict[tuple[str, int], list]] = {}
        self._auto_complete_after: int | None = None
        self._list_polls = 0
        #: reassignments frozen by stall_reassignment: they stay listed as
        #: in-progress but complete_reassignments skips them (a wedged
        #: follower that never catches up — stuck-move reaper fodder)
        self.stalled: set[tuple[str, int]] = set()
        #: data plane: (topic, partition) -> [batch bytes]; offsets assigned
        #: at append like a real log
        self.logs: dict[tuple[str, int], list[bytes]] = {}
        self.log_end: dict[tuple[str, int], int] = {}
        self.scram_users = scram_users or {}
        #: brokers crashed via kill_broker (absent from metadata)
        self._dead: set[int] = set()
        self._servers: list[_BrokerListener] = []
        for bid, spec in sorted(brokers.items()):
            self.brokers[bid] = {"rack": spec.get("rack", ""), "port": None}
            dirs = spec.get("logdirs") or ["/data/d0"]
            self.placement[bid] = {d: set() for d in dirs}
            # every replica starts on the broker's first logdir
            first = dirs[0]
            for t, parts in self.topics.items():
                for p in parts.values():
                    if bid in p["replicas"]:
                        self.placement[bid][first].add((t, p["partition"]))

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "FakeKafkaCluster":
        for bid in self.brokers:
            listener = _BrokerListener(self, bid)
            listener.start()
            self.brokers[bid]["port"] = listener.port
            self._servers.append(listener)
        return self

    def stop(self) -> None:
        for s in self._servers:
            s.stop()
        self._servers.clear()

    def bootstrap(self) -> list[tuple[str, int]]:
        return [("127.0.0.1", self.brokers[min(self.brokers)]["port"])]

    # ------------------------------------------------------- test control

    def complete_reassignments(self) -> list[tuple[str, int]]:
        """Apply every parked reassignment (the SimulatedClusterAdmin.tick
        analog)."""
        with self._lock:
            done = []
            for (t, pidx), replicas in list(self.reassignments.items()):
                if (t, pidx) in self.stalled:
                    continue
                part = self.topics[t][pidx]
                old = part["replicas"]
                part["replicas"] = list(replicas)
                if part["leader"] not in replicas:
                    part["leader"] = replicas[0]
                # move physical placement for brokers that gained the replica
                for b in set(replicas) - set(old):
                    dirs = self.placement.get(b)
                    if dirs:
                        next(iter(dirs.values())).add((t, pidx))
                for b in set(old) - set(replicas):
                    for members in self.placement.get(b, {}).values():
                        members.discard((t, pidx))
                del self.reassignments[(t, pidx)]
                done.append((t, pidx))
            return done

    def auto_complete_after(self, polls: int) -> None:
        """Finish reassignments after `polls` ListPartitionReassignments
        calls — drives the executor's real progress-check loop."""
        self._auto_complete_after = polls
        self._list_polls = 0

    def stall_reassignment(self, topic: str, partition: int) -> None:
        """Freeze one reassignment: it stays in-progress (listed by
        ListPartitionReassignments) but never completes until unstalled —
        the wedged-move shape the executor's reaper exists for."""
        with self._lock:
            self.stalled.add((topic, partition))

    def unstall_reassignment(self, topic: str, partition: int) -> None:
        with self._lock:
            self.stalled.discard((topic, partition))

    def kill_broker(self, broker_id: int) -> None:
        """Chaos: crash one broker — its listener closes (connections die),
        it vanishes from Metadata responses, and partitions it led fail
        over to their first surviving replica (the controller's ISR
        election).  Its replica assignments REMAIN in the partition lists,
        which is exactly the referenced-but-absent signal the
        BrokerFailureDetector reads (kafka/admin.py topology derivation;
        reference BrokerFailureDetector.java:88 ZK watch analog)."""
        if broker_id == self.controller:
            raise ValueError("refusing to kill the controller in this fake")
        with self._lock:
            self._dead.add(broker_id)
            for parts in self.topics.values():
                for p in parts.values():
                    if p["leader"] == broker_id:
                        alive = [
                            b for b in p["replicas"]
                            if b != broker_id and b not in self._dead
                        ]
                        p["leader"] = alive[0] if alive else -1
        for s in self._servers:
            if s.node_id == broker_id:
                s.stop()

    # ------------------------------------------------------ request logic

    def handle(self, node_id: int, api: proto.Api, body: dict) -> dict:
        with self._lock:
            return getattr(self, f"_h_{api.name}")(node_id, body)

    def _h_ApiVersions(self, node, body):  # noqa: N802
        return {
            "error_code": 0,
            "api_keys": [
                {"api_key": a.key, "min_version": a.version, "max_version": a.version}
                for a in proto.ALL_APIS
            ],
        }

    def _h_Metadata(self, node, body):  # noqa: N802
        names = body["topics"]
        topics = self.topics if names is None else {
            t: self.topics[t] for t in names if t in self.topics
        }
        return {
            "brokers": [
                {"node_id": b, "host": "127.0.0.1", "port": info["port"],
                 "rack": info["rack"] or None}
                for b, info in sorted(self.brokers.items())
                if b not in self._dead
            ],
            "controller_id": self.controller,
            "topics": [
                {
                    "error_code": 0, "name": t, "is_internal": False,
                    "partitions": [
                        {
                            "error_code": 0, "partition_index": pidx,
                            "leader_id": p["leader"],
                            "replica_nodes": list(p["replicas"]),
                            "isr_nodes": list(p["replicas"]),
                        }
                        for pidx, p in sorted(parts.items())
                    ],
                }
                for t, parts in sorted(topics.items())
            ],
        }

    def _not_controller(self, api: proto.Api) -> dict | None:
        return None  # single-controller fake; routing correctness is covered
        # by the client retry test using `controller` reassignment

    def _h_AlterPartitionReassignments(self, node, body):  # noqa: N802
        responses = []
        for t in body["topics"] or []:
            parts = []
            for p in t["partitions"] or []:
                key = (t["name"], p["partition_index"])
                code, msg = 0, None
                if t["name"] not in self.topics or key[1] not in self.topics[t["name"]]:
                    code, msg = 3, "UNKNOWN_TOPIC_OR_PARTITION"
                elif p["replicas"] is None:
                    if key in self.reassignments:
                        del self.reassignments[key]
                    else:
                        code, msg = NO_REASSIGNMENT_IN_PROGRESS, "none in progress"
                elif set(p["replicas"]) == set(self.topics[t["name"]][key[1]]["replicas"]):
                    # pure reorder: every target replica is already in ISR, so
                    # real Kafka completes it immediately (no data movement)
                    self.topics[t["name"]][key[1]]["replicas"] = list(p["replicas"])
                else:
                    self.reassignments[key] = list(p["replicas"])
                parts.append(
                    {"partition_index": key[1], "error_code": code,
                     "error_message": msg}
                )
            responses.append({"name": t["name"], "partitions": parts})
        return {
            "throttle_time_ms": 0, "error_code": 0, "error_message": None,
            "responses": responses,
        }

    def _h_ListPartitionReassignments(self, node, body):  # noqa: N802
        self._list_polls += 1
        if (
            self._auto_complete_after is not None
            and self._list_polls >= self._auto_complete_after
        ):
            self.complete_reassignments()
        by_topic: dict[str, list[dict]] = {}
        for (t, pidx), target in sorted(self.reassignments.items()):
            current = self.topics[t][pidx]["replicas"]
            by_topic.setdefault(t, []).append({
                "partition_index": pidx,
                "replicas": sorted(set(current) | set(target)),
                "adding_replicas": sorted(set(target) - set(current)),
                "removing_replicas": sorted(set(current) - set(target)),
            })
        return {
            "throttle_time_ms": 0, "error_code": 0, "error_message": None,
            "topics": [
                {"name": t, "partitions": ps} for t, ps in sorted(by_topic.items())
            ],
        }

    def _h_ElectLeaders(self, node, body):  # noqa: N802
        results = []
        for t in body["topic_partitions"] or []:
            parts = []
            for pidx in t["partition_ids"] or []:
                part = self.topics.get(t["topic"], {}).get(pidx)
                if part is None:
                    parts.append({"partition_id": pidx, "error_code": 3,
                                  "error_message": "unknown"})
                    continue
                part["leader"] = part["replicas"][0]  # preferred election
                parts.append({"partition_id": pidx, "error_code": 0,
                              "error_message": None})
            results.append({"topic": t["topic"], "partition_results": parts})
        return {"throttle_time_ms": 0, "error_code": 0,
                "replica_election_results": results}

    def _h_IncrementalAlterConfigs(self, node, body):  # noqa: N802
        responses = []
        for r in body["resources"] or []:
            store = self.configs.setdefault((r["resource_type"], r["resource_name"]), {})
            for c in r["configs"] or []:
                if c["config_operation"] == 0:  # SET
                    store[c["name"]] = c["value"]
                else:  # DELETE
                    store.pop(c["name"], None)
            responses.append({
                "error_code": 0, "error_message": None,
                "resource_type": r["resource_type"],
                "resource_name": r["resource_name"],
            })
        return {"throttle_time_ms": 0, "responses": responses}

    def _h_CreateTopics(self, node, body):  # noqa: N802
        out = []
        ids = sorted(self.brokers)
        for t in body["topics"] or []:
            if t["name"] in self.topics:
                out.append({"name": t["name"], "error_code": 36})  # EXISTS
                continue
            n = max(1, t["num_partitions"])
            rf = max(1, min(t["replication_factor"], len(ids)))
            self.topics[t["name"]] = {
                p: {
                    "partition": p,
                    "leader": ids[p % len(ids)],
                    "replicas": [ids[(p + r) % len(ids)] for r in range(rf)],
                }
                for p in range(n)
            }
            out.append({"name": t["name"], "error_code": 0})
        return {"topics": out}

    def _h_Produce(self, node, body):  # noqa: N802
        responses = []
        for t in body["topic_data"] or []:
            name = t["name"]
            if name not in self.topics:
                # reporter auto-creates its topic
                # (CruiseControlMetricsReporter topic bootstrap)
                self.topics[name] = {
                    0: {"partition": 0, "leader": node, "replicas": [node]}
                }
            parts = []
            for pd in t["partition_data"] or []:
                key = (name, pd["index"])
                part = self.topics[name].get(pd["index"])
                code = 0
                base = -1
                if part is None:
                    code = 3  # UNKNOWN_TOPIC_OR_PARTITION
                elif part["leader"] != node:
                    code = 6  # NOT_LEADER_OR_FOLLOWER
                elif pd["records"]:
                    batch = bytearray(pd["records"])
                    base = self.log_end.get(key, 0)
                    struct.pack_into(">q", batch, 0, base)  # assign offsets
                    (count,) = struct.unpack_from(">i", batch, 57)
                    self.logs.setdefault(key, []).append(bytes(batch))
                    self.log_end[key] = base + count
                parts.append({
                    "index": pd["index"], "error_code": code,
                    "base_offset": base, "log_append_time_ms": -1,
                })
            responses.append({"name": name, "partition_responses": parts})
        return {"responses": responses, "throttle_time_ms": 0}

    def _h_Fetch(self, node, body):  # noqa: N802
        responses = []
        for t in body["topics"] or []:
            parts = []
            for p in t["partitions"] or []:
                key = (t["topic"], p["partition"])
                end = self.log_end.get(key, 0)
                part = self.topics.get(t["topic"], {}).get(p["partition"])
                code = 0
                data = b""
                if part is None:
                    code = 3
                elif part["leader"] != node:
                    code = 6
                else:
                    want = p["fetch_offset"]
                    chunks = []
                    for batch in self.logs.get(key, []):
                        (base,) = struct.unpack_from(">q", batch, 0)
                        (count,) = struct.unpack_from(">i", batch, 57)
                        if base + count > want:
                            chunks.append(batch)
                    data = b"".join(chunks)
                parts.append({
                    "partition_index": p["partition"], "error_code": code,
                    "high_watermark": end, "last_stable_offset": end,
                    "aborted_transactions": None,
                    "records": data,
                })
            responses.append({"topic": t["topic"], "partitions": parts})
        return {"throttle_time_ms": 0, "responses": responses}

    def _h_ListOffsets(self, node, body):  # noqa: N802
        topics = []
        for t in body["topics"] or []:
            parts = []
            for p in t["partitions"] or []:
                key = (t["name"], p["partition_index"])
                if p["timestamp"] == -2:  # earliest
                    off = 0
                else:  # latest
                    off = self.log_end.get(key, 0)
                parts.append({
                    "partition_index": p["partition_index"], "error_code": 0,
                    "timestamp": -1, "offset": off,
                })
            topics.append({"name": t["name"], "partitions": parts})
        return {"topics": topics}

    def _h_DescribeConfigs(self, node, body):  # noqa: N802
        results = []
        for r in body["resources"] or []:
            store = self.configs.get((r["resource_type"], r["resource_name"]), {})
            wanted = r["configuration_keys"]
            results.append({
                "error_code": 0, "error_message": None,
                "resource_type": r["resource_type"],
                "resource_name": r["resource_name"],
                "configs": [
                    {"name": k, "value": v, "read_only": False,
                     "is_default": False, "is_sensitive": False}
                    for k, v in sorted(store.items())
                    if wanted is None or k in wanted
                ],
            })
        return {"throttle_time_ms": 0, "results": results}

    def _h_AlterReplicaLogDirs(self, node, body):  # noqa: N802
        results: dict[str, list[dict]] = {}
        dirs = self.placement[node]
        for d in body["dirs"] or []:
            path = d["path"]
            for t in d["topics"] or []:
                for pidx in t["partitions"] or []:
                    code = 0
                    if path not in dirs:
                        code = 57  # LOG_DIR_NOT_FOUND
                    elif self.intra_copy_polls > 0:
                        # async copy: future replica until polled down
                        self.future_replicas.setdefault(node, {})[
                            (t["name"], pidx)
                        ] = [path, self.intra_copy_polls]
                    else:
                        for members in dirs.values():
                            members.discard((t["name"], pidx))
                        dirs[path].add((t["name"], pidx))
                    results.setdefault(t["name"], []).append(
                        {"partition_index": pidx, "error_code": code}
                    )
        return {
            "throttle_time_ms": 0,
            "results": [
                {"topic_name": t, "partitions": ps} for t, ps in sorted(results.items())
            ],
        }

    def _h_DescribeLogDirs(self, node, body):  # noqa: N802
        futures = self.future_replicas.get(node, {})
        results = []
        for path, members in sorted(self.placement[node].items()):
            topics: dict[str, list[dict]] = {}
            for t, pidx in sorted(members):
                topics.setdefault(t, []).append(
                    {"partition_index": pidx, "partition_size": 1024,
                     "offset_lag": 0, "is_future_key": False}
                )
            for (t, pidx), (target, _polls) in sorted(futures.items()):
                if target == path:
                    topics.setdefault(t, []).append(
                        {"partition_index": pidx, "partition_size": 512,
                         "offset_lag": 512, "is_future_key": True}
                    )
            results.append({
                "error_code": 0, "log_dir": path,
                "topics": [
                    {"name": t, "partitions": ps} for t, ps in sorted(topics.items())
                ],
            })
        # advance the copies AFTER reporting: each poll is progress; a copy
        # that reaches 0 lands on its target dir
        for key, entry in list(futures.items()):
            entry[1] -= 1
            if entry[1] <= 0:
                for members in self.placement[node].values():
                    members.discard(key)
                self.placement[node][entry[0]].add(key)
                del futures[key]
        return {"throttle_time_ms": 0, "results": results}


class _BrokerListener(threading.Thread):
    """One fake broker node: accept loop + per-connection frame handling."""

    def __init__(self, cluster: FakeKafkaCluster, node_id: int):
        super().__init__(daemon=True, name=f"fake-kafka-{node_id}")
        self.cluster = cluster
        self.node_id = node_id
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name=f"fake-kafka-{self.node_id}-conn",
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        # per-connection SASL gate (only when the cluster has scram users):
        # handshake -> scram rounds -> authenticated; anything else first
        # gets ILLEGAL_SASL_STATE and the connection is closed, like a real
        # SASL listener
        sasl_required = bool(self.cluster.scram_users)
        scram = None
        authenticated = not sasl_required
        try:
            while True:
                head = self._read_exact(conn, 4)
                if head is None:
                    return
                (size,) = struct.unpack(">i", head)
                payload = self._read_exact(conn, size)
                if payload is None:
                    return
                api, cid, _client, body = proto.decode_request(payload)
                if api.name == "SaslHandshake":
                    from cruise_control_tpu.kafka.sasl import _HASHES, ScramServer

                    mech = body["mechanism"]
                    if mech in _HASHES:
                        scram = ScramServer(mech, self.cluster.scram_users)
                        resp = {"error_code": 0, "mechanisms": sorted(_HASHES)}
                    else:
                        resp = {
                            "error_code": 33,  # UNSUPPORTED_SASL_MECHANISM
                            "mechanisms": sorted(_HASHES),
                        }
                elif api.name == "SaslAuthenticate":
                    if scram is None:
                        resp = {"error_code": 47, "error_message": "handshake first",
                                "auth_bytes": b""}  # ILLEGAL_SASL_STATE
                    else:
                        msg, done, ok = scram.respond(body["auth_bytes"])
                        if done and not ok:
                            resp = {
                                "error_code": 58,  # SASL_AUTHENTICATION_FAILED
                                "error_message": msg.decode(),
                                "auth_bytes": b"",
                            }
                            conn.sendall(proto.encode_response(api, cid, resp))
                            return
                        authenticated = authenticated or (done and ok)
                        resp = {"error_code": 0, "error_message": None,
                                "auth_bytes": msg}
                elif not authenticated:
                    # a real SASL listener disconnects on pre-auth requests
                    return
                else:
                    resp = self.cluster.handle(self.node_id, api, body)
                conn.sendall(proto.encode_response(api, cid, resp))
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytes | None:
        chunks = []
        while n:
            try:
                chunk = conn.recv(n)
            except OSError:
                return None
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)
