"""Deterministic fault injection for the supervised optimizer runtime.

Every breaker transition, retry schedule, and degraded-mode proposal must
be pinned by tests rather than by hoping the TPU misbehaves on cue.  This
module injects the failures the supervisor classifies — engine hangs,
raised XLA-shaped errors, OOMs — plus Kafka transport and admin faults,
all keyed by CALL COUNT (or a seeded pseudo-random rate), so a test can
say "the second engine invocation OOMs" and mean exactly that.

Two injection surfaces:

  * device ops — everything marked `@device_op` (Engine.run, the mesh
    layer's MeshEngine.run (sharded/grid), portfolio_run, and the
    watchdog's trivial-op probe) routes through ONE process-wide hook
    (common/device_watchdog.set_device_op_hook).  `device_fault` installs
    an interceptor on that seam; `device_wedged` is the composite that
    models the observed failure (MULTICHIP_r05): EVERY device op —
    including the recovery probe — blocks until the context exits.
  * arbitrary methods — `method_fault` (with the `slow` / `hanging` /
    `raising` / `dropping` effects) patches a bound method on any object
    or class: the simulated ClusterAdmin, the Kafka wire client, a
    notifier.

All context managers yield an `InjectionLog` (total calls seen, faults
fired) so tests assert the fault actually hit.  Hooks nest: an inner
injector delegates non-matching calls to whatever was installed before
it.  Everything is restored on exit, and hang injectors release their
blocked threads so abandoned supervisor workers finish instead of leaking
into the next test.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

from cruise_control_tpu.common import device_watchdog as _watchdog_mod
from cruise_control_tpu.common.device_watchdog import set_device_op_hook

#: every engine-invocation op name (the probe is separate on purpose:
#: error-class injectors must not break the recovery probe, only
#: `device_wedged` models a device that fails the probe too)
ENGINE_OPS = (
    "engine.run", "mesh.run", "portfolio.run",
    "scenario.batch-eval",
)
PROBE_OP = "probe"
#: the per-device attribution probe (mesh fault tolerance) — one tiny
#: dispatch per chip, the device object as args[0]
DEVICE_PROBE_OP = "device.probe"
ALL_DEVICE_OPS = ENGINE_OPS + (PROBE_OP,)


def _dispatch_device_ids(args) -> tuple[int, ...] | None:
    """Best-effort device ids a dispatch touches, from its receiver:
    a mesh engine exposes `.mesh` (all its devices), a per-device probe
    passes the jax Device itself (`.id`).  None when undeterminable —
    callers treat that as the default device (id 0), where single-device
    engine work lands."""
    if not args:
        return None
    recv = args[0]
    mesh = getattr(recv, "mesh", None)
    if mesh is not None:
        try:
            return tuple(int(d.id) for d in mesh.devices.flat)
        except Exception:  # noqa: BLE001 — attribution only
            return None
    did = getattr(recv, "id", None)
    if isinstance(did, int):
        return (did,)
    return None


class FaultSchedule:
    """Which call indices (0-based, per op / per method) a fault fires on.

    calls: explicit indices ("fail calls 0 and 2").  after/limit: a
    contiguous window ("fail everything from call 3", "the first 2
    calls").  rate+seed: seeded pseudo-random firing, deterministic per
    (seed, index) — reproducible chaos for soak-style tests.  Default
    fires on EVERY call.
    """

    def __init__(
        self,
        calls=None,
        *,
        after: int = 0,
        limit: int | None = None,
        rate: float | None = None,
        seed: int = 0,
    ):
        self.calls = frozenset(calls) if calls is not None else None
        self.after = after
        self.limit = limit
        self.rate = rate
        self.seed = seed

    def fires(self, n: int) -> bool:
        if self.calls is not None:
            return n in self.calls
        if n < self.after:
            return False
        if self.limit is not None and n >= self.after + self.limit:
            return False
        if self.rate is not None:
            # deterministic per (seed, index); int-mixed because tuple
            # seeding is deprecated
            return random.Random(self.seed * 1_000_003 + n).random() < self.rate
        return True


ALWAYS = FaultSchedule()


def first(n: int) -> FaultSchedule:
    """The first n calls fail, the rest succeed — the transient-recovery
    shape (retry tests)."""
    return FaultSchedule(limit=n)


class InjectionLog:
    """What an injector observed: total intercepted calls and fired
    faults, per op/method name.  Thread-safe — supervised ops run on
    worker threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self.calls: dict[str, int] = {}
        self.fired: dict[str, int] = {}

    def _record(self, name: str) -> int:
        """Count one call; returns its 0-based index for the schedule."""
        with self._lock:
            n = self.calls.get(name, 0)
            self.calls[name] = n + 1
            return n

    def _mark_fired(self, name: str) -> None:
        with self._lock:
            self.fired[name] = self.fired.get(name, 0) + 1

    @property
    def total_calls(self) -> int:
        with self._lock:
            return sum(self.calls.values())

    @property
    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())


# ----------------------------------------------------------------------
# effects
# ----------------------------------------------------------------------


class InjectedXlaError(RuntimeError):
    """Stand-in for jaxlib's XlaRuntimeError (same shape the classifier
    reads: RuntimeError carrying a gRPC-style status message)."""


def transient_error(op: str = "?") -> InjectedXlaError:
    return InjectedXlaError(
        f"INTERNAL: injected fault in {op}: Failed to execute XLA runtime program"
    )


def oom_error(op: str = "?") -> InjectedXlaError:
    return InjectedXlaError(
        f"RESOURCE_EXHAUSTED: injected fault in {op}: "
        "Out of memory allocating 9437184000 bytes"
    )


def compile_error(op: str = "?") -> InjectedXlaError:
    return InjectedXlaError(
        f"INVALID_ARGUMENT: injected fault in {op}: XLA compilation failure"
    )


def device_lost_error(op: str = "?", device_id: int = 0) -> InjectedXlaError:
    """The backend's 'this chip is gone' shape (classify_failure →
    DEVICE_LOST via the _DEVICE_LOST_MARKERS text match)."""
    return InjectedXlaError(
        f"INTERNAL: injected fault in {op}: DEVICE_LOST: "
        f"device {device_id} halted and was removed from the slice"
    )


# ----------------------------------------------------------------------
# device-op injection (the @device_op seam)
# ----------------------------------------------------------------------


@contextlib.contextmanager
def device_fault(effect, *, ops=ENGINE_OPS, schedule: FaultSchedule = ALWAYS):
    """Intercept device ops: when `schedule` fires for that op's call
    index, run `effect(op_name, fn, args, kwargs)` (raise to inject an
    error; block to inject a hang; call fn for a late real completion);
    otherwise dispatch normally.  Non-targeted ops (and non-firing calls)
    fall through to any previously installed hook, so injectors nest."""
    log = InjectionLog()
    prev = _watchdog_mod._DEVICE_OP_HOOK

    def hook(name, fn, args, kwargs):
        if name in ops:
            n = log._record(name)
            if schedule.fires(n):
                log._mark_fired(name)
                return effect(name, fn, args, kwargs)
        if prev is not None:
            return prev(name, fn, args, kwargs)
        return fn(*args, **kwargs)

    set_device_op_hook(hook)
    try:
        yield log
    finally:
        set_device_op_hook(prev)


def _raising(factory):
    def effect(op, fn, args, kwargs):
        raise factory(op)

    return effect


def xla_errors(*, ops=ENGINE_OPS, schedule: FaultSchedule = ALWAYS):
    """Engine invocations raise transient XLA-shaped runtime errors."""
    return device_fault(_raising(transient_error), ops=ops, schedule=schedule)


def device_oom(*, ops=ENGINE_OPS, schedule: FaultSchedule = ALWAYS):
    """Engine invocations raise RESOURCE_EXHAUSTED (device OOM)."""
    return device_fault(_raising(oom_error), ops=ops, schedule=schedule)


def compile_failures(*, ops=ENGINE_OPS, schedule: FaultSchedule = ALWAYS):
    """Engine invocations raise XLA compilation failures."""
    return device_fault(_raising(compile_error), ops=ops, schedule=schedule)


@contextlib.contextmanager
def device_slowdown(
    factor: float, *, ops=ENGINE_OPS, schedule: FaultSchedule = ALWAYS
):
    """Sustained device SLOWNESS: every targeted engine op completes for
    real, then stalls until its wall clock has been scaled by `factor`
    (>= 1.0) — thermal throttling, a contended tunnel, a neighbour's
    burst.  Hangs and crashes were injectable before; this is the shape
    overload soaks need: the device keeps answering, just too slowly to
    hold the fleet's deadlines, so shedding/brownout must engage rather
    than the breaker.

    Per-op accounting rides the yielded InjectionLog (calls/fired per op
    name) like every injector here, and the hook nests/restores through
    `device_fault` — an inner injector still sees non-targeted calls.
    """
    if factor < 1.0:
        raise ValueError(f"device_slowdown factor must be >= 1.0, got {factor}")

    def effect(op, fn, args, kwargs):
        t0 = time.monotonic()
        result = fn(*args, **kwargs)
        wall = time.monotonic() - t0
        time.sleep(wall * (factor - 1.0))
        return result

    with device_fault(effect, ops=ops, schedule=schedule) as log:
        yield log


@contextlib.contextmanager
def device_wedged(*, ops=ALL_DEVICE_OPS, schedule: FaultSchedule = ALWAYS):
    """The observed MULTICHIP_r05 failure: every device op — engine runs
    AND the recovery probe — hangs until the context exits ("the fault
    clears").  Abandoned supervisor threads unblock at exit and complete
    against the real device, so nothing leaks into the next test."""
    release = threading.Event()

    def effect(op, fn, args, kwargs):
        # block until "the fault clears" (context exit), then return a
        # nothing-result WITHOUT running the real op: the supervisor
        # already abandoned this call, and re-running real device work on
        # an orphaned thread would race interpreter teardown
        release.wait()
        return None

    with device_fault(effect, ops=ops, schedule=schedule) as log:
        try:
            yield log
        finally:
            release.set()


@contextlib.contextmanager
def device_loss(
    device_index: int,
    *,
    ops=ENGINE_OPS,
    schedule: FaultSchedule = ALWAYS,
    probe_ops=(DEVICE_PROBE_OP,),
):
    """Chip `device_index` DIES: from the scheduled call index on, every
    targeted dispatch that involves that device raises a DEVICE_LOST-shaped
    backend error.  Loss is LATCHED — once the schedule fires, the chip is
    permanently gone, so its per-device attribution probes (`probe_ops`)
    fail too regardless of schedule, while every other chip's probe passes:
    exactly the asymmetry the mesh classifier attributes on.  Dispatches
    not involving the chip (and all ops before the latch) fall through,
    nest-safe with per-op accounting like `device_slowdown`."""
    lost = threading.Event()

    def effect(op, fn, args, kwargs):
        raise device_lost_error(op, device_index)

    def involved(args) -> bool:
        ids = _dispatch_device_ids(args)
        return device_index in (ids if ids is not None else (0,))

    log = InjectionLog()
    prev = _watchdog_mod._DEVICE_OP_HOOK

    def hook(name, fn, args, kwargs):
        if name in probe_ops and lost.is_set() and involved(args):
            log._record(name)
            log._mark_fired(name)
            raise device_lost_error(name, device_index)
        if name in ops and involved(args):
            n = log._record(name)
            if schedule.fires(n):
                log._mark_fired(name)
                lost.set()
                return effect(name, fn, args, kwargs)
        if prev is not None:
            return prev(name, fn, args, kwargs)
        return fn(*args, **kwargs)

    set_device_op_hook(hook)
    try:
        yield log
    finally:
        set_device_op_hook(prev)


@contextlib.contextmanager
def collective_stall(
    *,
    device_index: int | None = None,
    ops=ENGINE_OPS,
    schedule: FaultSchedule = ALWAYS,
):
    """Hang ONLY multi-device dispatches: a targeted op whose receiver
    spans >1 device blocks until the context exits, single-device work
    keeps completing — the collective-wedge shape, distinct from
    `device_wedged` (everything hangs).  With `device_index` set, that
    chip's per-device attribution probe ALSO hangs once a stall has
    fired (latched), so the supervisor's fan-out pins the stall on it
    (COLLECTIVE_STALL with suspects) instead of reporting a bare HANG.
    Blocked threads release at exit; per-op accounting rides the log."""
    release = threading.Event()
    stalled = threading.Event()
    log = InjectionLog()
    prev = _watchdog_mod._DEVICE_OP_HOOK

    def hook(name, fn, args, kwargs):
        ids = _dispatch_device_ids(args)
        if (
            name == DEVICE_PROBE_OP
            and device_index is not None
            and stalled.is_set()
            and ids == (device_index,)
        ):
            log._record(name)
            log._mark_fired(name)
            release.wait()
            return None
        if name in ops and ids is not None and len(ids) > 1:
            n = log._record(name)
            if schedule.fires(n):
                log._mark_fired(name)
                stalled.set()
                # abandoned by the supervisor; completing real work on an
                # orphaned thread would race interpreter teardown
                release.wait()
                return None
        if prev is not None:
            return prev(name, fn, args, kwargs)
        return fn(*args, **kwargs)

    set_device_op_hook(hook)
    try:
        yield log
    finally:
        release.set()
        set_device_op_hook(prev)


# ----------------------------------------------------------------------
# arbitrary-method injection (admin backends, Kafka wire client, ...)
# ----------------------------------------------------------------------


@contextlib.contextmanager
def method_fault(target, name: str, effect, *, schedule: FaultSchedule = ALWAYS):
    """Patch `target.name` (object or class attribute): calls whose index
    fires per `schedule` run `effect(orig_bound, *args, **kwargs)`;
    others pass through.  effect receives the ORIGINAL callable so slow/
    wrapping effects can still do the real work."""
    log = InjectionLog()
    orig = getattr(target, name)
    # an instance patch must not leave a shadowing attribute behind when
    # the method originally lived on the class
    had_own = isinstance(target, type) or name in vars(target)

    def wrapper(*args, **kwargs):
        n = log._record(name)
        if schedule.fires(n):
            log._mark_fired(name)
            return effect(orig, *args, **kwargs)
        return orig(*args, **kwargs)

    setattr(target, name, wrapper)
    try:
        yield log
    finally:
        if had_own:
            setattr(target, name, orig)
        else:
            delattr(target, name)


def slow(delay_s: float):
    """Effect: the call succeeds, after delay_s (slow admin/broker)."""

    def effect(orig, *args, **kwargs):
        time.sleep(delay_s)
        return orig(*args, **kwargs)

    return effect


def dropping(result=None):
    """Effect: the call is swallowed — nothing happens on the backend
    (a controller that accepts and forgets, an election that never runs)."""

    def effect(orig, *args, **kwargs):
        return result

    return effect


def raising(exc_factory):
    """Effect: the call raises exc_factory() (e.g. ConnectionError for
    transient Kafka transport faults)."""

    def effect(orig, *args, **kwargs):
        raise exc_factory()

    return effect


def hanging(release: threading.Event):
    """Effect: the call blocks until `release` is set, then completes for
    real — a hung admin/broker response.  The caller owns the event (set
    it in test teardown, or use `hung_method` which does both)."""

    def effect(orig, *args, **kwargs):
        release.wait()
        return orig(*args, **kwargs)

    return effect


@contextlib.contextmanager
def hung_method(target, name: str, *, schedule: FaultSchedule = ALWAYS):
    """method_fault + hanging with the release tied to context exit."""
    release = threading.Event()
    with method_fault(target, name, hanging(release), schedule=schedule) as log:
        try:
            yield log
        finally:
            release.set()


def kafka_connection_errors(client, *, schedule: FaultSchedule = ALWAYS):
    """Transient transport faults: `client.broker_request` raises
    ConnectionError on scheduled calls (broker restart / dropped socket)."""
    return method_fault(
        client,
        "broker_request",
        raising(lambda: ConnectionError("injected: connection reset by peer")),
        schedule=schedule,
    )


# ----------------------------------------------------------------------
# crash/restart + stall injection (crash-safe executor tests)
# ----------------------------------------------------------------------


class SimulatedProcessCrash(RuntimeError):
    """Raised out of the executor's progress loop to model the process
    dying mid-execution (kill -9, OOM-kill, node loss)."""


@contextlib.contextmanager
def process_crash(admin, *, on: str = "tick", schedule: FaultSchedule = ALWAYS):
    """Model a HARD process crash mid-execution against `admin`.

    The scheduled call to `admin.on` raises SimulatedProcessCrash — and for
    the remainder of the context the dying process's outbound CLEANUP calls
    (`clear_replication_throttle`, `cancel_reassignments`) ALSO raise it,
    because a crashed process never reaches the cluster again: whatever
    `finally` blocks the interpreter still runs must not tidy up state —
    on the cluster OR in the journal — that a real kill -9 would have left
    behind (leaked throttles, in-flight reassignments, no trailing journal
    records).  The test catches the exception, abandons the "dead"
    executor, and constructs a fresh one over the same journal to exercise
    recovery.
    """
    crash = raising(lambda: SimulatedProcessCrash("injected crash"))
    with method_fault(admin, on, crash, schedule=schedule) as log, \
            method_fault(admin, "clear_replication_throttle", crash), \
            method_fault(admin, "cancel_reassignments", crash):
        yield log


@contextlib.contextmanager
def stalled_moves(admin, *keys):
    """Freeze the given reassignments on a SimulatedClusterAdmin (or any
    admin exposing stall/unstall): listed as in-progress forever, zero byte
    progress — the shape the stuck-move reaper enforces against."""
    admin.stall(*keys)
    try:
        yield
    finally:
        admin.unstall(*keys)


def truncate_file(path: str, *, keep_bytes: int | None = None, drop_bytes: int = 0):
    """Crash-truncate a journal: keep the first `keep_bytes` (or all minus
    `drop_bytes`) — models fsync racing the crash, including a torn final
    record."""
    import os

    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else max(0, size - drop_bytes)
    with open(path, "rb+") as f:
        f.truncate(keep)


# ----------------------------------------------------------------------
# fleet-HA injection: lease-store partitions + per-instance clock skew
# ----------------------------------------------------------------------

#: the LeaseStore contract surface the partition injector can sever
LEASE_OPS = ("acquire", "renew", "release", "read")


@contextlib.contextmanager
def lease_partition(store, *, ops=LEASE_OPS, schedule: FaultSchedule = ALWAYS,
                    mode: str = "fail"):
    """Partition an instance from its lease store: scheduled calls to the
    given LeaseStore methods either raise OSError (`mode="fail"` — the
    store is unreachable) or block until the context exits
    (`mode="hang"` — the classic stalled-writer shape: the instance
    neither renews nor learns it lost).  Call counts land in the yielded
    InjectionLog per method, like every other injector here.

    The store object is patched per INSTANCE, so a two-instance harness
    can partition one instance's view while the other keeps working —
    exactly the asymmetric partition that forces a takeover."""
    if mode not in ("fail", "hang"):
        raise ValueError(f"lease_partition mode {mode!r} not in (fail, hang)")
    log = InjectionLog()
    release = threading.Event()
    originals = {name: getattr(store, name) for name in ops}
    owned = {
        name: isinstance(store, type) or name in vars(store) for name in ops
    }

    def make_wrapper(name, orig):
        def wrapper(*args, **kwargs):
            n = log._record(name)
            if schedule.fires(n):
                log._mark_fired(name)
                if mode == "hang":
                    release.wait()
                    # the partition healed: the late call completes for
                    # real (its staleness is the lease layer's problem —
                    # that is the point)
                    return orig(*args, **kwargs)
                raise OSError(f"injected lease-store partition in {name}")
            return orig(*args, **kwargs)

        return wrapper

    for name, orig in originals.items():
        setattr(store, name, make_wrapper(name, orig))
    try:
        yield log
    finally:
        release.set()
        for name, orig in originals.items():
            if owned[name]:
                setattr(store, name, orig)
            else:
                delattr(store, name)


@contextlib.contextmanager
def clock_skew(target, offset_s: float):
    """Skew one instance's clock by `offset_s` seconds: patches the
    injectable `clock` attribute (LeaseManager and FileLeaseStore both
    carry one) so every read returns real+offset.  Yields an
    InjectionLog counting reads under "clock".  Skew within
    `fleet.ha.skew.slack.s` must be invisible; beyond it, the safety
    argument no longer covers the instance — chaos tests probe both
    sides of that line."""
    log = InjectionLog()
    orig = target.clock

    def skewed():
        log._record("clock")
        return orig() + offset_s

    target.clock = skewed
    try:
        yield log
    finally:
        target.clock = orig
