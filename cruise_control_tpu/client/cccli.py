"""cccli — command-line client for the REST API.

Reference: cruise-control-client/cruisecontrolclient/client/cccli.py:135-176
(one argparse subparser per endpoint), Endpoint.py (endpoint/parameter
object model), CCParameter/ (typed parameter validators), Responder.py /
Query.py (HTTP session + async 202 poll loop).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.parse
import urllib.request

from cruise_control_tpu.service.tasks import USER_TASK_ID_HEADER


# ----------------------------------------------------------------------
# typed parameter validators (reference CCParameter/*)
# ----------------------------------------------------------------------


def boolean_param(value: str) -> str:
    if value.lower() not in ("true", "false"):
        raise argparse.ArgumentTypeError(f"{value!r} is not true/false")
    return value.lower()

def csv_int_param(value: str) -> str:
    if not re.fullmatch(r"\d+(,\d+)*", value):
        raise argparse.ArgumentTypeError(f"{value!r} is not a comma-separated id list")
    return value


def positive_int_param(value: str) -> str:
    if not value.isdigit() or int(value) <= 0:
        raise argparse.ArgumentTypeError(f"{value!r} is not a positive integer")
    return value


def json_param(value: str) -> str:
    """Inline JSON, or @path to read it from a file (scenario documents
    are unwieldy on a command line)."""
    if value.startswith("@"):
        try:
            with open(value[1:]) as f:
                value = f.read()
        except OSError as e:
            raise argparse.ArgumentTypeError(f"cannot read {value[1:]!r}: {e}") from e
    try:
        json.loads(value)
    except json.JSONDecodeError as e:
        raise argparse.ArgumentTypeError(f"not valid JSON: {e}") from e
    return value


# ----------------------------------------------------------------------
# endpoint model (reference Endpoint.py)
# ----------------------------------------------------------------------


ENDPOINTS: dict[str, dict] = {
    # dest -> {method, endpoint, params: {flag: (param, type)}}
    "state": {"method": "GET", "endpoint": "state",
              "params": {"--substates": ("substates", str)}},
    "kafka_cluster_state": {"method": "GET", "endpoint": "kafka_cluster_state", "params": {}},
    "load": {"method": "GET", "endpoint": "load", "params": {}},
    "partition_load": {"method": "GET", "endpoint": "partition_load",
                       "params": {"--resource": ("resource", str),
                                  "--entries": ("entries", positive_int_param)}},
    "proposals": {"method": "GET", "endpoint": "proposals",
                  "params": {"--ignore-proposal-cache": ("ignore_proposal_cache", boolean_param)}},
    "user_tasks": {"method": "GET", "endpoint": "user_tasks",
                   "params": {"--user-task-ids": ("user_task_ids", str),
                              "--client-ids": ("client_ids", str),
                              "--endpoints": ("endpoints", str),
                              "--types": ("types", str),
                              "--fetch-completed-task": ("fetch_completed_task", boolean_param)}},
    "review_board": {"method": "GET", "endpoint": "review_board", "params": {}},
    "bootstrap": {"method": "GET", "endpoint": "bootstrap", "params": {}},
    "train": {"method": "GET", "endpoint": "train", "params": {}},
    "rebalance": {"method": "POST", "endpoint": "rebalance",
                  "params": {"--dryrun": ("dryrun", boolean_param),
                             "--goals": ("goals", str),
                             "--destination-broker-ids": ("destination_broker_ids", csv_int_param),
                             "--excluded-topics": ("excluded_topics", str),
                             "--rebalance-disk": ("rebalance_disk", boolean_param),
                             "--allow-capacity-estimation": ("allow_capacity_estimation", boolean_param),
                             "--exclude-recently-removed-brokers": ("exclude_recently_removed_brokers", boolean_param),
                             "--exclude-recently-demoted-brokers": ("exclude_recently_demoted_brokers", boolean_param),
                             "--replica-movement-strategies": ("replica_movement_strategies", str),
                             "--reason": ("reason", str),
                             "--review-id": ("review_id", positive_int_param)}},
    "add_broker": {"method": "POST", "endpoint": "add_broker",
                   "params": {"--brokers": ("brokerid", csv_int_param),
                              "--dryrun": ("dryrun", boolean_param)},
                   "required": ["--brokers"]},
    "remove_broker": {"method": "POST", "endpoint": "remove_broker",
                      "params": {"--brokers": ("brokerid", csv_int_param),
                                 "--dryrun": ("dryrun", boolean_param)},
                      "required": ["--brokers"]},
    "demote_broker": {"method": "POST", "endpoint": "demote_broker",
                      "params": {"--brokers": ("brokerid", csv_int_param),
                                 "--dryrun": ("dryrun", boolean_param)},
                      "required": ["--brokers"]},
    "fix_offline_replicas": {"method": "POST", "endpoint": "fix_offline_replicas",
                             "params": {"--dryrun": ("dryrun", boolean_param)}},
    "stop_proposal_execution": {"method": "POST", "endpoint": "stop_proposal_execution",
                                "params": {"--force": ("force_stop", boolean_param)}},
    "pause_sampling": {"method": "POST", "endpoint": "pause_sampling",
                       "params": {"--reason": ("reason", str)}},
    "resume_sampling": {"method": "POST", "endpoint": "resume_sampling", "params": {}},
    "topic_configuration": {"method": "POST", "endpoint": "topic_configuration",
                            "params": {"--topic": ("topic", str),
                                       "--replication-factor": ("replication_factor", positive_int_param),
                                       "--dryrun": ("dryrun", boolean_param)},
                            "required": ["--topic", "--replication-factor"]},
    "admin": {"method": "POST", "endpoint": "admin",
              "params": {"--enable-self-healing-for": ("enable_self_healing_for", str),
                         "--disable-self-healing-for": ("disable_self_healing_for", str),
                         "--drop-recently-removed-brokers": ("drop_recently_removed_brokers", csv_int_param),
                         "--drop-recently-demoted-brokers": ("drop_recently_demoted_brokers", csv_int_param),
                         # mid-execution concurrency control (reference
                         # AdminParameters ChangeExecutionConcurrency)
                         "--concurrent-partition-movements-per-broker":
                             ("concurrent_partition_movements_per_broker", positive_int_param),
                         "--concurrent-intra-broker-partition-movements":
                             ("concurrent_intra_broker_partition_movements", positive_int_param),
                         "--concurrent-leader-movements":
                             ("concurrent_leader_movements", positive_int_param),
                         "--execution-progress-check-interval-ms":
                             ("execution_progress_check_interval_ms", positive_int_param)}},
    "review": {"method": "POST", "endpoint": "review",
               "params": {"--approve": ("approve", csv_int_param),
                          "--discard": ("discard", csv_int_param),
                          "--reason": ("reason", str)}},
    # scenario planner (read-only what-if analysis)
    "simulate": {"method": "POST", "endpoint": "simulate",
                 "params": {"--scenarios": ("scenarios", json_param),
                            "--optimize": ("optimize", boolean_param),
                            "--allow-capacity-estimation":
                                ("allow_capacity_estimation", boolean_param),
                            "--reason": ("reason", str),
                            "--review-id": ("review_id", positive_int_param)},
                 "required": ["--scenarios"]},
    "rightsize": {"method": "GET", "endpoint": "rightsize",
                  "params": {"--horizon-ms": ("horizon_ms", positive_int_param),
                             "--min-brokers": ("min_brokers", positive_int_param),
                             "--max-broker-factor": ("max_broker_factor", str),
                             "--allow-capacity-estimation":
                                 ("allow_capacity_estimation", boolean_param)}},
    # observability: flight-recorder replay + Prometheus exposition.
    # `cccli trace` lists recent root traces; `cccli trace --id <traceId>`
    # (the _traceId of any async response, or a TraceId from user_tasks)
    # replays the span tree; `cccli trace --blackbox true` additionally
    # embeds the on-disk black-box dispatch spool (tail + in-flight
    # dispatches — the durable twin of the in-memory store).  `cccli
    # metrics` prints the exposition text verbatim (NOT JSON) — pipe it
    # to promtool or grep; `--format openmetrics` adds trace-id
    # exemplars on histogram buckets.
    "trace": {"method": "GET", "endpoint": "trace",
              "params": {"--id": ("id", str),
                         "--limit": ("limit", positive_int_param),
                         "--blackbox": ("blackbox", boolean_param)}},
    "metrics": {"method": "GET", "endpoint": "metrics",
                "params": {"--format": ("format", str)}},
    # SLO registry: burn rates, compliance and breach episodes per
    # cluster (`cccli slo`; pair with the global --cluster flag to
    # filter one cluster of a fleet)
    "slo": {"method": "GET", "endpoint": "slo", "params": {}},
    # decision ledger (analyzer/ledger.py).  `cccli explain --trace-id
    # <id>` (the _traceId of any async response) or `--proposal <id>`
    # replays one decision→outcome→calibration episode as a structured
    # explanation; `cccli ledger` prints the raw joined episode stream
    # newest-first.  Both are raw-JSON passthrough and route to one
    # cluster of a fleet with the global -c/--cluster flag, exactly like
    # `trace`/`slo`.
    "explain": {"method": "GET", "endpoint": "explain",
                "params": {"--trace-id": ("trace_id", str),
                           "--proposal": ("proposal", str)}},
    "ledger": {"method": "GET", "endpoint": "ledger",
               "params": {"--limit": ("limit", positive_int_param)}},
    # fleet controller: whole-instance rollup (`cccli fleet`); pair the
    # other subcommands with the global --cluster flag to target one
    # cluster of a fleet (e.g. `cccli --cluster east rebalance`)
    "fleet": {"method": "GET", "endpoint": "fleet",
              "params": {"--score": ("score", boolean_param)}},
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cccli", description="cruise-control-tpu command line client"
    )
    p.add_argument("-a", "--socket-address", default="http://127.0.0.1:9090",
                   help="host:port of the cruise-control server")
    p.add_argument("--prefix", default="/kafkacruisecontrol")
    p.add_argument("-u", "--user", default=None, metavar="USER:PASSWORD",
                   help="basic-auth credentials (reference BasicSecurityProvider)")
    p.add_argument("--token", default=None,
                   help="JWT bearer token (reference JwtSecurityProvider)")
    p.add_argument("--insecure", action="store_true",
                   help="skip TLS certificate verification (self-signed servers)")
    p.add_argument("-c", "--cluster", default=None,
                   help="fleet cluster id the request targets (fleet "
                        "deployments; rides every endpoint as cluster=)")
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--json-indent", type=int, default=2)
    sub = p.add_subparsers(dest="dest", required=True,
                           metavar="{" + ",".join(sorted(ENDPOINTS)) + "}")
    for dest, spec in ENDPOINTS.items():
        sp = sub.add_parser(dest)
        required = set(spec.get("required", ()))
        for flag, (param, typ) in spec["params"].items():
            sp.add_argument(flag, dest=param, type=typ, required=flag in required)
    return p


class Client:
    """HTTP session with the async 202 poll loop (reference Responder.py)."""

    def __init__(self, base: str, prefix: str, *, poll_interval=1.0, timeout=600.0,
                 user: str | None = None, token: str | None = None,
                 insecure: bool = False):
        if not base.startswith("http"):
            base = "http://" + base
        self.base = base.rstrip("/") + prefix
        self.poll_interval = poll_interval
        self.timeout = timeout
        self._auth: dict[str, str] = {}
        if token:
            self._auth["Authorization"] = f"Bearer {token}"
        elif user:
            import base64

            self._auth["Authorization"] = (
                "Basic " + base64.b64encode(user.encode()).decode()
            )
        self._ssl_ctx = None
        if insecure:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl_ctx.check_hostname = False
            self._ssl_ctx.verify_mode = ssl.CERT_NONE

    def request(self, method: str, endpoint: str, params: dict) -> dict:
        query = urllib.parse.urlencode({k: v for k, v in params.items() if v is not None})
        url = f"{self.base}/{endpoint}" + (f"?{query}" if query else "")
        headers: dict[str, str] = dict(self._auth)
        deadline = time.time() + self.timeout
        while True:
            req = urllib.request.Request(url, method=method, headers=headers)
            with urllib.request.urlopen(req, timeout=60, context=self._ssl_ctx) as resp:
                body = resp.read()
                ctype = resp.headers.get("Content-Type", "")
                if not ctype.startswith("application/json"):
                    # raw-text endpoint (/metrics Prometheus exposition):
                    # pass the body through verbatim
                    return body.decode()
                payload = json.loads(body)
                if resp.status != 202:
                    return payload
                tid = resp.headers.get(USER_TASK_ID_HEADER) or payload.get("_userTaskId")
                headers[USER_TASK_ID_HEADER] = tid
            if time.time() > deadline:
                raise TimeoutError(f"operation still running; resume with {tid}")
            for step in payload.get("progress", []):
                print(
                    f"  [{step['completionPercentage']:5.1f}%] {step['step']}",
                    file=sys.stderr,
                )
            time.sleep(self.poll_interval)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    spec = ENDPOINTS[args.dest]
    params = {
        param: getattr(args, param, None)
        for _, (param, _t) in spec["params"].items()
    }
    if args.cluster:
        params["cluster"] = args.cluster
    client = Client(args.socket_address, args.prefix,
                    poll_interval=args.poll_interval, timeout=args.timeout,
                    user=args.user, token=args.token, insecure=args.insecure)
    try:
        result = client.request(spec["method"], spec["endpoint"], params)
    except urllib.error.HTTPError as e:
        print(json.dumps(json.loads(e.read() or b"{}"), indent=args.json_indent))
        return 1
    if isinstance(result, str):
        print(result, end="" if result.endswith("\n") else "\n")
    else:
        print(json.dumps(result, indent=args.json_indent))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
