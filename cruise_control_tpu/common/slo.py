"""Declarative SLO registry with multi-window burn-rate alerting.

The service grew real service-level objectives one PR at a time — a
per-cluster proposal-freshness SLO the device scheduler derives deadlines
from (`fleet.scheduler.freshness.slo.s`), a cold-start-to-first-proposal
budget (PR 10's restart SLO), a sub-second streaming publish target
(ROADMAP item 4) and the urgent queue-wait bound — but each was only a
gate in `bench.py`.  This module makes them continuously evaluated,
observable objects: a registry of `SloSpec`s fed good/bad events (or
sampled by a probe), computing ERROR-BUDGET BURN RATES over a fast and a
slow window (the multiwindow-multi-burn-rate pattern from the SRE
workbook: the fast window catches a new fire quickly, the slow window
keeps one noisy sample from paging), and raising one alert-only
`SLO_BURN` anomaly per breach episode through the detector/notifier —
the same episode discipline as `FLEET_OVERLOAD`.

Burn rate: over a window, `burn = bad_fraction / error_budget` where
`error_budget = 1 - objective`.  Burn 1.0 consumes the budget exactly at
the sustainable rate; the registry alerts when BOTH windows' burn
reaches `burn_threshold` — a sustained breach, not a blip.

Surfaces: `GET /slo` (per-SLO burn rates, compliance, episode state),
the `/fleet` per-cluster rollup, and Prometheus gauges via the labeled
`slo.burn-rate` / `slo.compliance` collectors on the owning registry's
sensor catalog.

Event storage is time-bucketed (fixed `_BUCKETS` buckets spanning the
slow window), so a high-rate SLO costs O(1) memory and burn evaluation
is O(buckets), never O(events).  All clocks are injectable — the
episode tests drive hours of breach in milliseconds.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)

#: time buckets spanning the slow window (fast-window reads use the
#: suffix); 60 keeps fast-window resolution at slow/60 — with the
#: default 1 h slow window, one bucket per minute
_BUCKETS = 60


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One service-level objective.

    `objective` is the target good fraction (0.99 = 1% error budget).
    `probe` (optional) is sampled on every `tick()`: True = good sample,
    False = bad, None = no data right now (skipped — a service with no
    published proposal yet is not breaching its freshness SLO).  Without
    a probe the SLO is event-fed via `SloRegistry.record`."""

    name: str
    description: str
    objective: float
    probe: Callable[[], bool | None] | None = None
    #: the measurable the objective bounds (shown in /slo so an operator
    #: knows what "good" means without reading code)
    target: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), got "
                f"{self.objective} (1.0 leaves a zero error budget — every "
                f"bad event would be an infinite burn)"
            )


class _Windowed:
    """Good/bad counts in a ring of time buckets; O(1) memory."""

    def __init__(self, span_s: float):
        self.span_s = float(span_s)
        self.width_s = self.span_s / _BUCKETS
        #: bucket index -> [bucket_epoch, good, bad]
        self._ring: list[list] = [[-1, 0, 0] for _ in range(_BUCKETS)]

    def add(self, now: float, good: bool, n: int = 1) -> None:
        epoch = int(now / self.width_s)
        slot = self._ring[epoch % _BUCKETS]
        if slot[0] != epoch:
            slot[0], slot[1], slot[2] = epoch, 0, 0
        slot[1 if good else 2] += n

    def counts(self, now: float, window_s: float) -> tuple[int, int]:
        """(good, bad) over the trailing `window_s`."""
        cur = int(now / self.width_s)
        first = int((now - window_s) / self.width_s)
        good = bad = 0
        for slot in self._ring:
            if first <= slot[0] <= cur:
                good += slot[1]
                bad += slot[2]
        return good, bad


class SloState:
    """One registered SLO's live accounting (registry-internal)."""

    def __init__(self, spec: SloSpec, fast_s: float, slow_s: float):
        self.spec = spec
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.window = _Windowed(slow_s)
        self.alerting = False
        self.episodes = 0
        self.last_change: float | None = None

    def burn(self, now: float, window_s: float) -> float:
        good, bad = self.window.counts(now, window_s)
        total = good + bad
        if total == 0:
            return 0.0
        budget = 1.0 - self.spec.objective
        return (bad / total) / budget

    def compliance(self, now: float) -> float | None:
        good, bad = self.window.counts(now, self.slow_s)
        total = good + bad
        if total == 0:
            return None
        return good / total

    def state_json(self, now: float) -> dict:
        fast, slow = self.burn(now, self.fast_s), self.burn(now, self.slow_s)
        comp = self.compliance(now)
        good, bad = self.window.counts(now, self.slow_s)
        return {
            "name": self.spec.name,
            "description": self.spec.description,
            "objective": self.spec.objective,
            "target": self.spec.target,
            "fastWindowS": self.fast_s,
            "slowWindowS": self.slow_s,
            "fastBurnRate": round(fast, 4),
            "slowBurnRate": round(slow, 4),
            "compliance": (None if comp is None else round(comp, 6)),
            "samples": good + bad,
            "badSamples": bad,
            "alerting": self.alerting,
            "episodes": self.episodes,
        }


class SloRegistry:
    """Per-cluster SLO evaluator; the facade builds one from `slo.*` keys
    and wires its anomaly sink to the cluster's detector.

    Thread-safe: producers (`record`) are the controller/scheduler/facade
    threads; `tick` runs on the evaluation thread AND on every /slo
    scrape (a scrape must never show stale burn rates because the ticker
    is between intervals)."""

    def __init__(
        self,
        *,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        burn_threshold: float = 10.0,
        sensors=None,
        clock=time.monotonic,
        anomaly_sink=None,
        cluster_id: str = "",
    ):
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s <= slow_window_s, got "
                f"{fast_window_s}/{slow_window_s}"
            )
        if burn_threshold < 1.0:
            raise ValueError(
                f"burn_threshold must be >= 1.0 (1.0 is the sustainable "
                f"burn), got {burn_threshold}"
            )
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.burn_threshold = burn_threshold
        self.clock = clock
        self.cluster_id = cluster_id
        #: detector.add_anomaly (set by the facade once the detector
        #: exists); SLO_BURN rides it alert-only
        self.anomaly_sink = anomaly_sink
        self.sensors = sensors
        self._lock = threading.Lock()
        self._slos: dict[str, SloState] = {}
        if sensors is not None:
            sensors.collector("slo.burn-rate", self._burn_collector)
            sensors.collector("slo.compliance", self._compliance_collector)

    # -- registration / feeding ----------------------------------------

    def register(self, spec: SloSpec) -> None:
        with self._lock:
            if spec.name in self._slos:
                raise ValueError(f"SLO {spec.name!r} already registered")
            self._slos[spec.name] = SloState(
                spec, self.fast_window_s, self.slow_window_s
            )

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._slos)

    def record(self, name: str, good: bool, n: int = 1) -> None:
        """Feed one good/bad observation (event-fed SLOs: a publish
        landing inside/outside its latency target, an urgent grant
        meeting/missing its wait bound).  Unknown names are ignored — a
        producer must not crash because its SLO is not configured here."""
        with self._lock:
            st = self._slos.get(name)
            if st is None:
                return
            st.window.add(self.clock(), good, n)
        if self.sensors is not None and not good:
            self.sensors.counter("slo.bad-samples").inc(n)

    # -- evaluation -----------------------------------------------------

    def tick(self) -> list[dict]:
        """Sample every probe, evaluate burn rates, fire/clear episodes;
        returns the post-evaluation state (the /slo body)."""
        now = self.clock()
        fired: list[SloState] = []
        with self._lock:
            states = list(self._slos.values())
        for st in states:
            if st.spec.probe is not None:
                try:
                    verdict = st.spec.probe()
                except Exception:  # noqa: BLE001 — a broken probe is no data
                    verdict = None
                if verdict is not None:
                    with self._lock:
                        st.window.add(now, bool(verdict))
        out = []
        with self._lock:
            for st in states:
                fast = st.burn(now, st.fast_s)
                slow = st.burn(now, st.slow_s)
                breaching = (
                    fast >= self.burn_threshold and slow >= self.burn_threshold
                )
                if breaching and not st.alerting:
                    # episode start: alert EXACTLY once until recovery
                    st.alerting = True
                    st.episodes += 1
                    st.last_change = now
                    fired.append(st)
                elif not breaching and st.alerting and (
                    fast < self.burn_threshold
                ):
                    # episode end: the fast window has genuinely
                    # recovered (the slow window may stay hot for its
                    # whole span — that is history, not a new fire)
                    st.alerting = False
                    st.last_change = now
                out.append(st.state_json(now))
        if self.sensors is not None:
            self.sensors.counter("slo.evaluations").inc()
            for st in fired:
                self.sensors.counter("slo.alerts").inc()
        for st in fired:
            self._fire(st, now)
        return out

    def _fire(self, st: SloState, now: float) -> None:
        sink = self.anomaly_sink
        log.warning(
            "SLO %s burning: fast %.1fx / slow %.1fx over budget "
            "(objective %.4g, episode %d)",
            st.spec.name, st.burn(now, st.fast_s), st.burn(now, st.slow_s),
            st.spec.objective, st.episodes,
        )
        if sink is None:
            return
        try:
            from cruise_control_tpu.detector.anomalies import SloBurn

            sink(SloBurn(
                slo=st.spec.name,
                cluster_id=self.cluster_id,
                objective=st.spec.objective,
                fast_burn_rate=round(st.burn(now, st.fast_s), 3),
                slow_burn_rate=round(st.burn(now, st.slow_s), 3),
                episode=st.episodes,
            ))
        except Exception:  # noqa: BLE001 — alerting must not break evaluation
            log.warning("SLO_BURN anomaly delivery failed", exc_info=True)

    # -- surfaces -------------------------------------------------------

    def _burn_collector(self) -> list:
        now = self.clock()
        with self._lock:
            return [
                ({"slo": st.spec.name, "window": w},
                 st.burn(now, s))
                for st in self._slos.values()
                for w, s in (("fast", st.fast_s), ("slow", st.slow_s))
            ]

    def _compliance_collector(self) -> list:
        now = self.clock()
        with self._lock:
            out = []
            for st in self._slos.values():
                comp = st.compliance(now)
                if comp is not None:
                    out.append(({"slo": st.spec.name}, comp))
            return out

    def state_json(self) -> dict:
        """The `GET /slo` body for this cluster (evaluated fresh)."""
        return {
            "burnThreshold": self.burn_threshold,
            "slos": self.tick(),
        }

    def summary_json(self) -> dict:
        """Cheap per-SLO burn/episode summary (the /fleet rollup) — NO
        probe sampling or episode evaluation: rollups must stay cheap,
        the ticker and /slo scrapes keep the rates fresh."""
        now = self.clock()
        with self._lock:
            return {
                st.spec.name: {
                    "fastBurnRate": round(st.burn(now, st.fast_s), 4),
                    "slowBurnRate": round(st.burn(now, st.slow_s), 4),
                    "alerting": st.alerting,
                    "episodes": st.episodes,
                }
                for st in self._slos.values()
            }


class SloTicker:
    """Tiny evaluation loop: one daemon thread ticking a set of
    registries (one per cluster facade) on a fixed cadence.  The /slo
    endpoint also ticks on scrape; this thread exists so burn episodes
    fire (and reach the notifier) with nobody watching."""

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = interval_s
        self._registries: list[SloRegistry] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def add(self, registry: SloRegistry) -> None:
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)

    def remove(self, registry: SloRegistry) -> None:
        """Detach one registry (its facade is shutting down); the loop
        thread stops once nobody is left to tick — in a fleet, N facades
        share ONE core-owned ticker, and the last one out turns off the
        light."""
        with self._lock:
            try:
                self._registries.remove(registry)
            except ValueError:
                pass
            empty = not self._registries
        if empty:
            self.stop()

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="slo-ticker"
            )
            self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                regs = list(self._registries)
            for reg in regs:
                try:
                    reg.tick()
                except Exception:  # noqa: BLE001 — the loop must keep ticking
                    log.warning("SLO tick failed", exc_info=True)
