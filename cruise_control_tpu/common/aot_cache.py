"""AOT export cache: skip Python tracing/lowering on warm service starts.

The persistent XLA compilation cache (compilation_cache.py) removes the
*compile* cost of a warm start, but jax.jit still re-traces and re-lowers
every engine program in each fresh process — ~6s of pure Python/StableHLO
work at north-star scale (scripts/profile_warmup.py).  This module
serializes the EXPORTED program (jax.export) to disk once per
(function, shape bucket, config, code version); later processes
deserialize StableHLO in milliseconds and go straight to the XLA cache.

Plays the role the reference gets from the JVM's always-warm process
model: its GoalOptimizer never pays a per-process compile because it
never restarts the compiler (analyzer/GoalOptimizer.java:124-175
amortizes via the proposal precompute loop instead).

Usage: `enable_aot_cache(dir)` at startup (bench.py, service main);
`AotCache.current()` returns the active cache or None.  Engine wraps its
jitted functions through `wrap()`, which transparently falls back to the
plain jit path on any export/deserialize failure.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading

import jax

log = logging.getLogger(__name__)

_active: "AotCache | None" = None
_registered: set[type] = set()
_reg_lock = threading.Lock()


def register_for_export(*classes) -> None:
    """Idempotently register custom pytree dataclasses for jax.export
    serialization (auxdata is pickled — metadata fields like ClusterShape
    are plain picklable dataclasses)."""
    from jax import export

    with _reg_lock:
        for cls in classes:
            if cls in _registered:
                continue
            export.register_pytree_node_serialization(
                cls,
                serialized_name=f"{cls.__module__}.{cls.__qualname__}",
                serialize_auxdata=pickle.dumps,
                deserialize_auxdata=lambda b: pickle.loads(bytes(b)),
            )
            _registered.add(cls)


def enable_aot_cache(directory: str | None) -> "AotCache | None":
    """Activate the process-wide AOT cache (None/'' disables)."""
    global _active
    if not directory:
        _active = None
        return None
    _active = AotCache(os.path.expanduser(directory))
    return _active


class AotCache:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @staticmethod
    def current() -> "AotCache | None":
        return _active

    def path_for(self, name: str, fingerprint: str) -> str:
        return os.path.join(self.directory, f"{name}-{fingerprint}.jaxexp")

    def wrap(self, jit_fn, name: str, fingerprint: str):
        return _AotFn(self, jit_fn, name, fingerprint)


def fingerprint_of(*parts) -> str:
    """Stable hex key over arbitrary repr()-able parts + jax version +
    backend platform (an export for tpu must not be loaded on cpu)."""
    h = hashlib.sha256()
    h.update(jax.__version__.encode())
    h.update(jax.default_backend().encode())
    for p in parts:
        h.update(repr(p).encode())
    return h.hexdigest()[:20]


def source_fingerprint(module) -> str:
    """Hash of a module's source — code changes invalidate saved programs."""
    import inspect

    try:
        return hashlib.sha256(inspect.getsource(module).encode()).hexdigest()[:12]
    except OSError:
        return "nosource"


class _AotFn:
    """Callable wrapping a jitted function with disk-backed AOT export.

    First call in a process: load the serialized export if present
    (deserialize is ~ms; XLA compile then hits the persistent cache), else
    export once (ONE trace+lower, same cost the jit path would pay),
    persist it, and call the exported program.  Any failure logs once and
    falls back to the plain jit path permanently for this instance.
    """

    def __init__(self, cache: AotCache, jit_fn, name: str, fingerprint: str):
        self._cache = cache
        self._jit = jit_fn
        self._name = name
        self._path = cache.path_for(name, fingerprint)
        self._call = None
        self._lock = threading.Lock()

    def _ensure(self, args, kwargs):
        if self._call is not None:
            return
        with self._lock:
            if self._call is not None:
                return
            from jax import export

            if os.path.exists(self._path):
                with open(self._path, "rb") as f:
                    self._call = export.deserialize(bytearray(f.read())).call
                return
            exp = export.export(self._jit)(*args, **kwargs)
            blob = exp.serialize()
            tmp = f"{self._path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._path)
            self._call = exp.call

    def __call__(self, *args, **kwargs):
        if self._call is None:
            try:
                self._ensure(args, kwargs)
            except Exception as e:  # noqa: BLE001 — AOT is an optimization,
                # never a correctness dependency: any export/deserialize
                # failure reverts to the ordinary jit path
                log.warning("aot cache disabled for %s: %r", self._name, e)
                self._call = self._jit
        return self._call(*args, **kwargs)

    # introspection passthroughs used by profiling scripts
    def __getattr__(self, item):
        return getattr(self._jit, item)
