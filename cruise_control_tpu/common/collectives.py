"""Context-gated MODEL_AXIS reductions for the sharded-model engine.

The goal chain and `compute_aggregates` reduce over the replica /
partition axes.  When the flattened model is *sharded* over MODEL_AXIS
(parallel/model_shard.py) those arrays are shard-local slices, so every
such reduction must finish with a `psum` over the model axis to recover
the global value.  When the model is replicated (plain engine, the
replicated mesh mode) the very same code must lower to the very same
HLO — the repo's byte-parity pins compare those programs bit-for-bit.

Rather than thread an `axis_name` argument through every goal
signature, the active model axis rides in a contextvar that is read at
**trace time**: the engine brackets its `chain.evaluate` /
`compute_aggregates` call sites with `model_axis_scope(axis)` *inside*
the traced function, so the set/reset pair is synchronous within
whichever thread (foreground or warm-pool background compile) is
tracing.  With no active scope every helper is the identity
composition — `gsum(x) == x.sum()` produces the identical jaxpr — so
the unsharded path is untouched by construction.
"""

from __future__ import annotations

import contextlib
import contextvars

import jax
import jax.numpy as jnp

#: the MODEL_AXIS name active for the current trace, or None (replicated)
_MODEL_AXIS: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "cruise_model_axis", default=None
)


def model_axis() -> str | None:
    """The mesh axis name reductions must psum over, or None."""
    return _MODEL_AXIS.get()


@contextlib.contextmanager
def model_axis_scope(axis: str | None):
    """Trace-time bracket marking replica/partition arrays as sharded
    over `axis`.  `axis=None` is a no-op scope (replicated model)."""
    tok = _MODEL_AXIS.set(axis)
    try:
        yield
    finally:
        _MODEL_AXIS.reset(tok)


def _psum(x, axis: str):
    # jax.lax.psum rejects bool; route through int32 (exact: exactly one
    # shard contributes a possibly-nonzero value per element).
    if x.dtype == jnp.bool_:
        return jax.lax.psum(x.astype(jnp.int32), axis).astype(jnp.bool_)
    return jax.lax.psum(x, axis)


def gsum(x):
    """Global `x.sum()` over a (possibly model-sharded) replica/partition
    array: shard-local sum + psum.  Identity with `.sum()` when no model
    axis is active."""
    s = x.sum()
    axis = _MODEL_AXIS.get()
    return s if axis is None else _psum(s, axis)


def gsegment_sum(data, segment_ids, num_segments: int):
    """Global `jax.ops.segment_sum` whose *segment ids* are global (e.g.
    broker ids) but whose *data* rows are model-sharded: each shard
    seg-sums its local rows, then the per-segment partials psum to the
    global result.  Exact for ints; for floats the partial-sum order
    differs from single-device (see parity-test quantization note)."""
    part = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    axis = _MODEL_AXIS.get()
    return part if axis is None else _psum(part, axis)


def gscatter_rows(full):
    """Reduce-scatter a `[rows, ...]` partial over the model axis and keep
    only this shard's `rows / n` slice (used for the partition-indexed
    `part_rack_count`, which stays sharded in the carry).  Identity when
    no model axis is active.  `rows` must divide by the axis size."""
    axis = _MODEL_AXIS.get()
    if axis is None:
        return full
    return jax.lax.psum_scatter(full, axis, scatter_dimension=0, tiled=True)
