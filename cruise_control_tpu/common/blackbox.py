"""Black-box telemetry: a crash/hang-durable on-disk dispatch spool.

The flight recorder (common/trace.py) lives in process memory, so the one
failure that matters most — a process hung inside an XLA program and
killed by the driver (MULTICHIP_r05: bare rc=124, one JAX platform
warning) — leaves no trace at all.  This module is the aircraft-style
black box for device work: every device dispatch writes a line-JSONL
record to an on-disk spool BEFORE the call can block, so a hang, a
kill -9 or an OOM-kill leaves a readable trail ending at the exact
in-flight dispatch ("engine slice 7 of bucket R4096 in flight for 93 s
under a BACKGROUND grant"), not a bare return code.

Spool mechanics (deliberately journal-shaped, executor/journal.py):

  * one append-only JSONL file per process (`spool-<pid>.jsonl` inside
    the configured directory — the journal/compile-cache mount, the
    service's one durable surface);
  * every record is `write()`+`flush()`ed synchronously before the
    dispatch proceeds: the bytes reach the KERNEL, so process death of
    any flavor (kill -9, abort, driver kill) cannot lose them.  fsync is
    BATCHED (`blackbox.fsync.batch.records`) like the executor journal —
    full durability against machine power loss costs an fsync per batch,
    not per dispatch;
  * a fixed-size ring: past `blackbox.spool.max.records` the active file
    rotates to `<name>.1` (one previous generation kept, like the lease
    audit trail) so the spool can run forever in bounded space;
  * readers (`read_spool`) tolerate a torn final line — the crash
    happened mid-write; everything before it is trusted.

Record grammar — one JSON object per line:

    {"t": <kind>, "ph": "B"|"E"|"I", "seq": n, "ms": wall_ms,
     "mono": monotonic_s, "pid": pid, "thread": name, ...context}

`ph` is the phase: "B"egin is written before a dispatch blocks, "E"nd
after it returns (ok/error/hang verdict), "I"nstant for point events
(scheduler grants).  A "B" with no matching "E" is an IN-FLIGHT dispatch
— `in_flight_from_records` pairs them up, which is how a post-mortem
(or `__graft_entry__.py`'s dryrun timeout verdict) names the dispatch a
dead process was stuck in.

Recording sites (each records what it knows; `blackbox_context` threads
cross-layer context — bucket, config fingerprint, work class, queue
wait — down to the leaf records):

  * `common/device_watchdog.py` `DeviceSupervisor._bounded` — kind
    "supervised": op + budget, End carries the hang/error verdict;
  * the `device_op` seam (same module) — kind "device-op": every engine
    dispatch (run/sharded/grid/portfolio/probe), inside the worker, so a
    hang leaves it permanently in flight;
  * `analyzer/engine.py` `_run_segmented` — kind "engine-slice": one
    Begin per wall-bounded slice with the slice index and round range
    (the blocking-sync boundary), so a hung segmented anneal names its
    slice;
  * `fleet/scheduler.py` grants — kind "sched-grant" instants with work
    class, queue wait and deadline verdict;
  * `controller/streaming.py` cycles — kind "controller-cycle" around
    each window roll.

Default-on when a durable directory can be derived
(`config.blackbox_dir()`); the disabled path is one predicate check per
dispatch and is pinned byte-identical (tests/test_blackbox.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time

#: how many trailing records a diagnostic embed keeps (the dryrun
#: timeout verdict, /trace?blackbox=true) — enough to see the approach
#: to the hang, small enough to ride a JSON record
DEFAULT_TAIL_RECORDS = 40


# ----------------------------------------------------------------------
# cross-layer dispatch context
# ----------------------------------------------------------------------

_CONTEXT: contextvars.ContextVar = contextvars.ContextVar(
    "blackbox_context", default=None
)


@contextlib.contextmanager
def blackbox_context(**fields):
    """Merge `fields` into every record the enclosed code emits.

    The optimizer stamps bucket/config-fingerprint/parallel-mode here,
    the device scheduler stamps work class + queue wait — so the leaf
    "engine-slice"/"device-op" records carry the whole story without any
    layer knowing the others.  A contextvar, so it survives the
    DeviceSupervisor's copied-context worker hop exactly like the
    segmented-execution seam."""
    cur = _CONTEXT.get() or {}
    token = _CONTEXT.set({**cur, **fields})
    try:
        yield
    finally:
        _CONTEXT.reset(token)


def current_context() -> dict:
    return dict(_CONTEXT.get() or {})


# ----------------------------------------------------------------------
# recorder
# ----------------------------------------------------------------------


class BlackBoxRecorder:
    """Crash-durable dispatch recorder over one JSONL ring spool.

    Thread-safe; `enabled` is False until `configure(path)` — every
    recording site guards on it, so an unconfigured recorder costs one
    attribute read per dispatch and writes nothing (the pinned disabled
    path)."""

    def __init__(self, *, clock=time.monotonic, wall=time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self._wall = wall
        self._f = None
        self.path: str | None = None
        self.enabled = False
        self.max_records = 2048
        self.fsync_batch = 32
        self._seq = 0
        self._written = 0
        self._active_records = 0
        self._since_fsync = 0
        self.write_errors = 0
        #: in-process view of open dispatches: seq -> begin record
        self._open: dict[int, dict] = {}

    # -- lifecycle ------------------------------------------------------

    def configure(
        self,
        path: str | None,
        *,
        max_records: int = 2048,
        fsync_batch: int = 32,
    ) -> None:
        """Point the recorder at a spool file (None disables + closes).

        Idempotent on the same path — N fleet facades over one core all
        configure the same process-wide recorder.  An unwritable spool
        location (read-only mount, permission denial) leaves the
        recorder DISABLED with a warning: default-on telemetry must
        never prevent the service it observes from booting."""
        with self._lock:
            if path == self.path and (self._f is not None or path is None):
                self.max_records = max_records
                self.fsync_batch = fsync_batch
                return
            self._close_locked()
            self.path = path
            self.enabled = path is not None
            self.max_records = max_records
            self.fsync_batch = fsync_batch
            self._active_records = 0
            self._since_fsync = 0
            self._open.clear()
            if path is not None:
                try:
                    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                    # append: a restart shares the ring with its
                    # predecessor's records until rotation ages them out
                    self._f = open(path, "a", encoding="utf-8")
                    self._prune_dead_spools_locked(path)
                except OSError:
                    import logging

                    self.write_errors += 1
                    self.enabled = False
                    self.path = None
                    logging.getLogger(__name__).warning(
                        "black-box spool %s is unwritable; recorder "
                        "disabled", path, exc_info=True,
                    )

    @staticmethod
    def _prune_dead_spools_locked(path: str) -> None:
        """Delete sibling spool files of pids that no longer exist — the
        per-file ring bounds ONE process's disk, this bounds the
        directory across restarts ('bounded disk forever' must hold on a
        service restarted daily under a new pid).  Best-effort: a live
        post-mortem reader racing the prune just re-lists."""
        spool_dir = os.path.dirname(path) or "."
        try:
            names = os.listdir(spool_dir)
        except OSError:
            return
        for name in names:
            if not (name.startswith("spool-") and ".jsonl" in name):
                continue
            full = os.path.join(spool_dir, name)
            if full == path or full == path + ".1":
                continue
            try:
                pid = int(name[len("spool-"):].split(".jsonl")[0])
            except ValueError:
                continue
            try:
                os.kill(pid, 0)  # liveness probe, signal 0 sends nothing
            except ProcessLookupError:
                try:
                    os.unlink(full)
                except OSError:
                    pass
            except OSError:
                pass  # e.g. EPERM: pid exists under another uid — keep

    def close(self) -> None:
        with self._lock:
            self._close_locked()
            self.enabled = False
            self.path = None

    def _close_locked(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    # -- writing --------------------------------------------------------

    def _emit_locked(self, rec: dict, *, durable: bool = False) -> None:
        f = self._f
        if f is None:
            return
        try:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            # flush ALWAYS: the bytes must reach the kernel before the
            # dispatch can block — surviving process death is the whole
            # point.  fsync (power-loss durability) is batched.
            f.flush()
            self._written += 1
            self._active_records += 1
            self._since_fsync += 1
            if durable or self._since_fsync >= self.fsync_batch:
                os.fsync(f.fileno())
                self._since_fsync = 0
            if self._active_records >= self.max_records:
                self._rotate_locked()
        except (OSError, ValueError):
            # a full/yanked disk must degrade the telemetry, never the
            # dispatch it observes
            self.write_errors += 1

    def _rotate_locked(self) -> None:
        self._close_locked()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            self.write_errors += 1
        try:
            self._f = open(self.path, "w", encoding="utf-8")
        except OSError:
            self.write_errors += 1
        self._active_records = 0
        # re-emit still-OPEN Begin records into the new generation: a
        # long-hung dispatch must survive any number of rotations driven
        # by healthy traffic, or the post-mortem would be empty for
        # precisely the long-hang case the spool exists for (readers
        # dedup by (pid, seq), so the copy is harmless once the original
        # generation ages out)
        if self._f is not None and self._open:
            try:
                for rec in self._open.values():
                    self._f.write(
                        json.dumps(rec, separators=(",", ":")) + "\n"
                    )
                    self._active_records += 1
                    self._written += 1
                self._f.flush()
            except (OSError, ValueError):
                self.write_errors += 1

    def _base(self, kind: str, ph: str, seq: int) -> dict:
        return {
            "t": kind,
            "ph": ph,
            "seq": seq,
            "ms": int(self._wall() * 1000),
            "mono": round(self._clock(), 6),
            "pid": os.getpid(),
            "thread": threading.current_thread().name,
        }

    def begin(self, kind: str, **fields) -> int:
        """Write the Begin record of one dispatch — BEFORE it can block —
        and return its seq for the matching `end`.  0 when disabled."""
        if not self.enabled:
            return 0
        ctx = _CONTEXT.get()
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = self._base(kind, "B", seq)
            if ctx:
                rec.update(ctx)
            rec.update(fields)
            self._emit_locked(rec)
            self._open[seq] = rec
        return seq

    def end(self, seq: int, *, ok: bool = True, **fields) -> None:
        if not self.enabled or seq == 0:
            return
        with self._lock:
            opened = self._open.pop(seq, None)
            rec = self._base(opened["t"] if opened else "?", "E", seq)
            rec["ok"] = bool(ok)
            if opened is not None:
                rec["wall_s"] = round(self._clock() - opened["mono"], 6)
            rec.update(fields)
            self._emit_locked(rec, durable=not ok)

    def event(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ctx = _CONTEXT.get()
        with self._lock:
            self._seq += 1
            rec = self._base(kind, "I", self._seq)
            if ctx:
                rec.update(ctx)
            rec.update(fields)
            self._emit_locked(rec)

    @contextlib.contextmanager
    def record(self, kind: str, **fields):
        """begin/end pair around one dispatch; an exception lands in the
        End record (ok=False) and propagates — only a dispatch that never
        returns (hang, process death) leaves the Begin in flight."""
        seq = self.begin(kind, **fields)
        try:
            yield seq
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            self.end(seq, ok=False, error=repr(e))
            raise
        else:
            self.end(seq)

    # -- reading --------------------------------------------------------

    def in_flight(self) -> list[dict]:
        """Open dispatches of THIS process, oldest first, with live age."""
        with self._lock:
            open_recs = [dict(r) for r in self._open.values()]
            now = self._clock()
        for r in open_recs:
            r["in_flight_s"] = round(now - r["mono"], 3)
        return sorted(open_recs, key=lambda r: r["seq"])

    def tail(self, n: int = DEFAULT_TAIL_RECORDS) -> list[dict]:
        """Last n records re-read from disk (both ring generations)."""
        if self.path is None:
            return []
        return read_spool(self.path, last_n=n)

    def state_json(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "path": self.path,
                "recordsWritten": self._written,
                "activeRecords": self._active_records,
                "maxRecords": self.max_records,
                "writeErrors": self.write_errors,
                "openDispatches": len(self._open),
            }


#: process-wide recorder every recording site consults — configured by
#: the service facade (AnalyzerCore) from `blackbox.*` config keys, or by
#: the dryrun child from BLACKBOX_SPOOL_DIR; disabled (one predicate per
#: dispatch, zero writes) until then
RECORDER = BlackBoxRecorder()


# ----------------------------------------------------------------------
# cross-process reading (post-mortem / parent-of-child)
# ----------------------------------------------------------------------


def _read_file(path: str) -> list[dict]:
    records: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    # torn tail: the writer died mid-line — everything
                    # before it is trusted, nothing after it exists
                    break
    except OSError:
        return records
    return records


def read_spool(path: str, *, last_n: int | None = None) -> list[dict]:
    """Parse a spool file — or every `spool-*.jsonl` under a directory —
    oldest record first, tolerating a torn final line.  For a file, the
    previous ring generation (`<path>.1`) is read first so the tail spans
    a rotation."""
    records: list[dict] = []
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith("spool-") and ".jsonl" in n
        )
        # read .1 generations before their active twin
        for name in sorted(names, key=lambda n: (n.replace(".1", ""), n.endswith(".1") is False)):
            records.extend(_read_file(os.path.join(path, name)))
        records.sort(key=lambda r: (r.get("pid", 0), r.get("seq", 0)))
    else:
        if os.path.exists(path + ".1"):
            records.extend(_read_file(path + ".1"))
        records.extend(_read_file(path))
    if last_n is not None and len(records) > last_n:
        records = records[-last_n:]
    return records


def in_flight_from_records(
    records: list[dict], *, now_ms: int | None = None
) -> list[dict]:
    """Begin records with no matching End — the dispatches a (possibly
    dead) process was inside when the spool went quiet.  Pairs by
    (pid, seq); `in_flight_s` is measured against the spool's LAST
    record on the writer's own monotonic clock, and `wall_age_s`
    (when `now_ms` is given) against the READER's wall clock — the
    dead child's monotonic clock died with it, but parent and child
    share the machine's wall time."""
    opens: dict[tuple, dict] = {}
    last_mono_by_pid: dict[int, float] = {}
    for r in records:
        key = (r.get("pid"), r.get("seq"))
        ph = r.get("ph")
        if ph == "B":
            opens[key] = r
        elif ph == "E":
            opens.pop(key, None)
        if "mono" in r:
            pid = r.get("pid")
            last_mono_by_pid[pid] = max(
                last_mono_by_pid.get(pid, 0.0), r["mono"]
            )
    out = []
    for r in opens.values():
        r = dict(r)
        last = last_mono_by_pid.get(r.get("pid"), r.get("mono", 0.0))
        r["in_flight_s"] = round(max(0.0, last - r.get("mono", last)), 3)
        if now_ms is not None and "ms" in r:
            r["wall_age_s"] = round(max(0.0, (now_ms - r["ms"]) / 1000.0), 3)
        out.append(r)
    return sorted(out, key=lambda r: (r.get("pid", 0), r.get("seq", 0)))


def spool_verdict(path: str, *, last_n: int = DEFAULT_TAIL_RECORDS) -> dict:
    """The structured post-mortem block diagnostic surfaces embed: the
    spool tail + the dispatches still in flight when it ends.  Mesh
    dispatches record their width (`mesh_shape`/`n_devices`, stamped by
    the mesh engine's `_blackbox_fields` through the device_op seam), and
    the verdict surfaces the widest one in flight as `mesh_in_flight` so
    a timeout kill names the mesh width, not just the op.  Never raises —
    an unreadable/absent spool is an empty verdict, because this runs
    inside failure paths."""
    try:
        records = read_spool(path, last_n=None)
    except Exception:  # noqa: BLE001 — diagnosis must not mask the failure
        records = []
    in_flight = in_flight_from_records(
        records, now_ms=int(time.time() * 1000)
    )
    verdict = {"records": records[-last_n:], "in_flight": in_flight}
    mesh = [r for r in in_flight if r.get("n_devices") or r.get("mesh_shape")]
    if mesh:
        widest = max(mesh, key=lambda r: int(r.get("n_devices") or 0))
        verdict["mesh_in_flight"] = {
            k: widest.get(k)
            for k in ("kind", "op", "mesh_shape", "n_devices", "in_flight_s")
            if widest.get(k) is not None
        }
    return verdict
