"""Device-dispatch accounting for the streaming control loop.

The fused steady-state cycle's contract (ROADMAP item 4) is O(1)
host<->device per window roll: ONE program dispatch plus ONE blocking
host extraction.  That contract is proved by counting, not asserted by
reading the code: every choke point that launches a device program or
forces a device->host sync on the controller's cycle path calls
`count_dispatch(tag)`, and `bench.py --streaming --smoke` wraps each
steady-state `run_once()` in a `dispatch_meter()` and gates on
`meter.total <= 2`.

The meter is a contextvar STACK, not a single slot: the controller keeps
its own per-cycle meter (the `controller.cycle-dispatches` gauge) while
the bench wraps it in an outer one — every active meter sees every
count.  No meter active costs one contextvar read per choke point.
"""

from __future__ import annotations

import contextlib
import contextvars

_METERS: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "cc-dispatch-meters", default=()
)


class DispatchMeter:
    """Per-tag dispatch counts observed while this meter was active."""

    def __init__(self):
        self.counts: dict[str, int] = {}

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def count(self, tag: str, n: int = 1) -> None:
        self.counts[tag] = self.counts.get(tag, 0) + n

    def __repr__(self) -> str:
        return f"DispatchMeter(total={self.total}, counts={self.counts})"


def count_dispatch(tag: str, n: int = 1) -> None:
    """Record `n` device dispatches/syncs against every active meter."""
    for m in _METERS.get():
        m.count(tag, n)


@contextlib.contextmanager
def dispatch_meter():
    """Activate a DispatchMeter for the enclosed block (nestable)."""
    m = DispatchMeter()
    token = _METERS.set(_METERS.get() + (m,))
    try:
        yield m
    finally:
        _METERS.reset(token)
