"""Accelerator liveness probe with a hard timeout.

The tunneled TPU can wedge (observed: every device op hangs indefinitely,
MULTICHIP_r05: bare rc=124 driver kill).  Any entry point that is about to
touch the backend — bench ladder, dryrun_multichip, ad-hoc scripts — runs
this gate first so a wedged runtime produces a diagnosable error record
within a bounded budget instead of an opaque process timeout.
"""

from __future__ import annotations

import threading


def device_watchdog(timeout_s: float = 180.0) -> str | None:
    """None when the accelerator answers a trivial op within the budget,
    else a diagnosis string (hang vs immediate failure).

    Runs the probe on a DAEMON thread so a hung runtime cannot block
    process exit either.  Waits on an event, not the thread: a probe that
    RAISES quickly (import error, PJRT client init failure) reports
    immediately with the real exception instead of burning the full budget
    and claiming a hang.
    """
    done = threading.Event()
    result: dict = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            jax.block_until_ready(jnp.arange(8).sum())
            result["ok"] = True
        except BaseException as e:  # noqa: BLE001 — diagnosis, not control flow
            result["error"] = f"device probe failed: {e!r}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True, name="device-watchdog")
    t.start()
    done.wait(timeout_s)
    if result.get("ok"):
        return None
    return result.get(
        "error", f"device unresponsive: trivial op did not complete in {timeout_s:.0f}s"
    )
