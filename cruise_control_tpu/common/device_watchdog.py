"""Supervised device runtime: watchdog probe, failure taxonomy, breaker.

The tunneled TPU can wedge (observed: every device op hangs indefinitely,
MULTICHIP_r05: bare rc=124 driver kill).  PR 1 added `device_watchdog` so
OFFLINE entry points (bench ladder, dryrun_multichip) fail diagnosably;
this module grows it into the supervision layer the SERVICE path runs
under — a wedged device must degrade the rebalancer, not hang
`proposals()` and every self-healing action behind it forever (the same
graceful-degradation stance the online rack-placement literature takes
toward solver failures, PAPERS.md arXiv:2501.12725 / 2504.00277):

  * `device_op`  — marker/seam every engine dispatch routes through; the
    deterministic fault harness (testing/faults.py) injects hangs and
    raised errors here instead of monkeypatching N engine classes.
  * `classify_failure` — maps an exception from a supervised call onto the
    failure taxonomy (HANG / COMPILE / OOM / TRANSIENT); application
    errors (bad request masks, invalid states) classify as None and
    propagate untouched.
  * `CircuitBreaker` — CLOSED -> (N classified failures) -> OPEN ->
    (half-open probe healthy) -> CLOSED.
  * `DeviceSupervisor` — bounded-budget call (daemon-thread deadline),
    jittered-backoff retry for transient classes, breaker bookkeeping,
    half-open probing via the trivial-op watchdog, and the sensor surface
    (`analyzer.supervisor.*`) the `/state` endpoint snapshots.

`GoalOptimizer` consults the supervisor around every engine invocation and
falls back to the CPU greedy path while the breaker is open
(analyzer/optimizer.py); the facade builds one supervisor per service from
the `tpu.supervisor.*` config keys.
"""

from __future__ import annotations

import enum
import random
import threading
import time

from cruise_control_tpu.common.blackbox import RECORDER as _BLACKBOX


def _trivial_device_op() -> None:
    """The watchdog's probe payload: one tiny reduction through the
    backend.  A module-level seam (wrapped by `device_op`) so the fault
    harness can wedge the probe exactly like the engine ops — a hung
    device hangs EVERY dispatch, including this one."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(jnp.arange(8).sum())


# ----------------------------------------------------------------------
# fault-injection seam
# ----------------------------------------------------------------------

#: (op_name, fn, args, kwargs) -> result.  The default just dispatches;
#: testing/faults.py swaps it to inject hangs / raised XLA errors / OOMs
#: keyed by op name and call count.  Kept deliberately tiny: one indirect
#: call per ENGINE INVOCATION (not per step), unmeasurable next to a run.
_DEVICE_OP_HOOK = None
_HOOK_LOCK = threading.Lock()


def set_device_op_hook(hook) -> None:
    """Install (or with None, remove) the device-op interception hook."""
    global _DEVICE_OP_HOOK
    with _HOOK_LOCK:
        _DEVICE_OP_HOOK = hook


_PAUSE_CLOCK_VAR = None


def _pause_clock_var():
    global _PAUSE_CLOCK_VAR
    if _PAUSE_CLOCK_VAR is None:
        import contextvars

        _PAUSE_CLOCK_VAR = contextvars.ContextVar(
            "device_op_pause_clock", default=None
        )
    return _PAUSE_CLOCK_VAR


class pause_clock_scope:
    """Scope a pause clock — `() -> float`, cumulative EXTERNALLY-imposed
    pause of the current dispatch, including one in progress — to the
    current context.  The device scheduler wraps each granted preemptible
    dispatch in one (fleet/scheduler.py), so only THAT dispatch's
    supervised calls extend their hang deadline by its pauses: a paused
    anneal is the scheduler doing its job, not a wedged device.  A
    contextvar so it rides the caller's context into `_bounded`'s worker
    copy; unset (the default) keeps the hang budget pure wall clock."""

    def __init__(self, fn):
        self._fn = fn
        self._token = None

    def __enter__(self):
        self._token = _pause_clock_var().set(self._fn)
        return self

    def __exit__(self, *exc):
        _pause_clock_var().reset(self._token)


def _current_pause_clock():
    return _pause_clock_var().get()


class CheckpointClock:
    """Cumulative seconds a dispatch has spent capturing fault-tolerance
    carry checkpoints (engine.SegmentContext snapshots).  Installed by the
    optimizer around supervised mesh calls via `checkpoint_clock_scope`;
    `DeviceSupervisor._bounded` adds it to the pause clock so host-side
    snapshot I/O extends the hang deadline instead of eating it — a run
    that checkpoints diligently must not look closer to wedged."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0.0

    def add(self, dt: float) -> None:
        with self._lock:
            self._total += max(0.0, dt)

    def seconds(self) -> float:
        with self._lock:
            return self._total


_CKPT_CLOCK_VAR = None


def _ckpt_clock_var():
    global _CKPT_CLOCK_VAR
    if _CKPT_CLOCK_VAR is None:
        import contextvars

        _CKPT_CLOCK_VAR = contextvars.ContextVar(
            "device_op_checkpoint_clock", default=None
        )
    return _CKPT_CLOCK_VAR


class checkpoint_clock_scope:
    """Scope a CheckpointClock to the current context — same contextvar
    ride as `pause_clock_scope`, so the enforcer thread and the copied
    worker context observe the SAME accumulator object."""

    def __init__(self, clock: CheckpointClock):
        self._clock = clock
        self._token = None

    def __enter__(self):
        self._token = _ckpt_clock_var().set(self._clock)
        return self._clock

    def __exit__(self, *exc):
        _ckpt_clock_var().reset(self._token)


def current_checkpoint_clock() -> CheckpointClock | None:
    return _ckpt_clock_var().get()


def device_op(name: str):
    """Mark a function/method as a device-dispatching entry point.

    Every supervised engine invocation (Engine.run, ShardedEngine.run,
    GridEngine.run, portfolio_run, the watchdog probe) carries this marker
    so fault injection targets ops BY NAME, uniformly, without knowing the
    class layout."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            hook = _DEVICE_OP_HOOK
            if _BLACKBOX.enabled:
                # black-box spool (common/blackbox.py): the Begin record
                # is on disk BEFORE anything that could block — including
                # the memory probe below, which queries the same runtime
                # that may be wedged (a hang inside it must still leave
                # this op in flight in the trail).  Best-effort
                # per-device memory (OOM post-mortems) rides the End
                # record instead.  One predicate read on the disabled
                # path.  A mesh-owning receiver (MeshEngine) annotates
                # its Begin records with mesh shape/width so a kill
                # verdict names the mesh in flight, not just the op.
                fields = {"op": name}
                if args:
                    extra = getattr(args[0], "_blackbox_fields", None)
                    if extra is not None:
                        try:
                            fields.update(extra())
                        except Exception:  # noqa: BLE001 — telemetry only
                            pass
                seq = _BLACKBOX.begin("device-op", **fields)
                try:
                    if hook is not None:
                        result = hook(name, fn, args, kwargs)
                    else:
                        result = fn(*args, **kwargs)
                except BaseException as e:  # noqa: BLE001 — recorded, re-raised
                    _BLACKBOX.end(seq, ok=False, error=repr(e))
                    raise
                mem = _memory_in_use()
                _BLACKBOX.end(
                    seq, **({"mem_bytes": mem} if mem is not None else {})
                )
                return result
            if hook is not None:
                return hook(name, fn, args, kwargs)
            return fn(*args, **kwargs)

        wrapper._device_op_name = name
        return wrapper

    return deco


def _memory_in_use() -> int | None:
    """Best-effort bytes-in-use across local devices for the black-box
    supervised record (None where the backend has no stats — host CPU,
    or an uninitialized/wedged runtime this probe must never touch
    dangerously)."""
    try:
        from cruise_control_tpu.common.profiling import _memory_stat

        v = _memory_stat("bytes_in_use")
        return int(v) if v else None
    except Exception:  # noqa: BLE001 — telemetry, never the dispatch
        return None


_probe_op = device_op("probe")(_trivial_device_op)


def device_watchdog(timeout_s: float = 180.0) -> str | None:
    """None when the accelerator answers a trivial op within the budget,
    else a diagnosis string (hang vs immediate failure).

    Runs the probe on a DAEMON thread so a hung runtime cannot block
    process exit either.  Waits on an event, not the thread: a probe that
    RAISES quickly (import error, PJRT client init failure) reports
    immediately with the real exception instead of burning the full budget
    and claiming a hang.
    """
    done = threading.Event()
    result: dict = {}

    def probe():
        try:
            _probe_op()
            result["ok"] = True
        except BaseException as e:  # noqa: BLE001 — diagnosis, not control flow
            result["error"] = f"device probe failed: {e!r}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True, name="device-watchdog")
    t.start()
    done.wait(timeout_s)
    if result.get("ok"):
        return None
    return result.get(
        "error", f"device unresponsive: trivial op did not complete in {timeout_s:.0f}s"
    )


def _per_device_probe(device) -> None:
    """One tiny single-device dispatch pinned to `device` — the unit of
    the mesh-attribution fan-out.  A module-level `device_op` seam
    ("device.probe", the device as args[0]) so the fault harness can wedge
    or kill probes for a SPECIFIC chip by device id."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(jax.device_put(jnp.arange(8), device).sum())


_device_probe_op = device_op("device.probe")(_per_device_probe)


def probe_devices(devices, timeout_s: float = 20.0) -> dict:
    """Probe each device CONCURRENTLY with its own liveness dispatch.

    Returns {device_id: None | diagnosis string} — None means the chip
    answered within the shared budget.  Each probe runs on its own daemon
    thread (a lost chip's probe may never return; it is abandoned like any
    hung supervised worker), so the whole fan-out costs one budget, not
    one per device.  This is how a hung MESH dispatch gets attributed to
    the specific chip: survivors answer, suspects do not.
    """
    events: dict[int, threading.Event] = {}
    results: dict[int, dict] = {}

    def probe_one(dev, did):
        try:
            _device_probe_op(dev)
            results[did]["ok"] = True
        except BaseException as e:  # noqa: BLE001 — diagnosis, not control flow
            results[did]["error"] = f"device {did} probe failed: {e!r}"
        finally:
            events[did].set()

    for dev in devices:
        did = int(getattr(dev, "id", dev if isinstance(dev, int) else 0))
        events[did] = threading.Event()
        results[did] = {}
        threading.Thread(
            target=probe_one,
            args=(dev, did),
            daemon=True,
            name=f"device-probe-{did}",
        ).start()
    deadline = time.monotonic() + timeout_s
    out: dict[int, str | None] = {}
    for did, ev in events.items():
        ev.wait(max(0.0, deadline - time.monotonic()))
        if results[did].get("ok"):
            out[did] = None
        else:
            out[did] = results[did].get(
                "error",
                f"device {did} unresponsive: probe did not complete in "
                f"{timeout_s:.0f}s",
            )
    return out


# ----------------------------------------------------------------------
# failure taxonomy
# ----------------------------------------------------------------------


class FailureClass(enum.Enum):
    """How a supervised device call failed; drives retry + breaker policy."""

    HANG = "hang"  # deadline exhausted; the dispatch never returned
    COMPILE = "compile"  # XLA compilation rejected the program
    OOM = "oom"  # RESOURCE_EXHAUSTED / out of device memory
    TRANSIENT = "transient"  # runtime-layer error expected to clear (retried)
    DEVICE_LOST = "device_lost"  # a specific chip evicted/coredumped mid-run
    COLLECTIVE_STALL = "collective_stall"  # multi-device dispatch hung on
    # a subset of its mesh (survivors answer probes, suspects do not)


#: failure classes that name specific chips — the optimizer treats these
#: as MESH failures (degrade width, per-width breaker) rather than
#: whole-backend failures
MESH_FAILURE_CLASSES = frozenset(
    {FailureClass.DEVICE_LOST, FailureClass.COLLECTIVE_STALL}
)


class DeviceHangError(TimeoutError):
    """A supervised call did not complete within its budget."""

    def __init__(self, op: str, timeout_s: float):
        super().__init__(
            f"device op {op!r} did not complete within {timeout_s:.1f}s"
        )
        self.op = op
        self.timeout_s = timeout_s


class DeviceLostError(RuntimeError):
    """The backend reported a device as gone (evicted, coredumped,
    disconnected).  `device_ids` names the chips when attribution
    succeeded; None when the backend only said 'a device'."""

    def __init__(self, msg: str, device_ids: tuple[int, ...] | None = None):
        super().__init__(msg)
        self.device_ids = tuple(device_ids) if device_ids else None


class CollectiveStallError(RuntimeError):
    """A multi-device dispatch hung while only a SUBSET of its mesh stopped
    answering per-device probes — the collective is wedged on the suspect
    chips, the survivors are healthy."""

    def __init__(self, msg: str, device_ids: tuple[int, ...] | None = None):
        super().__init__(msg)
        self.device_ids = tuple(device_ids) if device_ids else None


class DeviceDegradedError(RuntimeError):
    """A supervised call failed with a CLASSIFIED device failure (after any
    retries).  Carries the class + original cause so the optimizer can
    route to the degraded CPU path and report why; for mesh failure
    classes `device_ids` names the suspect chips so degrade-and-resume
    can rebuild the mesh around them."""

    def __init__(
        self,
        op: str,
        failure_class: FailureClass,
        cause: BaseException,
        device_ids: tuple[int, ...] | None = None,
    ):
        super().__init__(f"device op {op!r} failed ({failure_class.value}): {cause!r}")
        self.op = op
        self.failure_class = failure_class
        self.device_ids = tuple(device_ids) if device_ids else None
        self.__cause__ = cause


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory", "OOM")
_COMPILE_MARKERS = ("compilation", "Compilation", "UNIMPLEMENTED", "while compiling")
_RUNTIME_MARKERS = (
    "XLA", "xla", "jaxlib", "PJRT", "pjrt", "DEADLINE_EXCEEDED", "INTERNAL",
    "UNAVAILABLE", "ABORTED", "device",
)
#: backend phrasings for "this chip is gone" (PJRT / TPU driver / the
#: fault harness's lookalikes) — checked before the generic runtime
#: markers, which would otherwise swallow these into TRANSIENT retries
#: that can never succeed on a chip that no longer exists
_DEVICE_LOST_MARKERS = (
    "DEVICE_LOST", "device lost", "Device lost", "device is lost",
    "lost device", "device coredump", "device was removed",
)


def classify_failure(exc: BaseException) -> FailureClass | None:
    """Map an exception from a supervised call onto the failure taxonomy.

    None means "not a device failure": application errors (ValueError from
    input validation, bad request masks) must propagate to the caller
    untouched — counting them toward the breaker would let a malformed
    request degrade the service for everyone.

    Classification is structural (type) first, textual (well-known
    runtime-layer markers) second: jaxlib's XlaRuntimeError is a single
    type whose status code only appears in the message, and the fault
    harness injects lookalike RuntimeErrors with the same shape.
    """
    if isinstance(exc, DeviceHangError):
        return FailureClass.HANG
    if isinstance(exc, DeviceLostError):
        return FailureClass.DEVICE_LOST
    if isinstance(exc, CollectiveStallError):
        return FailureClass.COLLECTIVE_STALL
    if isinstance(exc, MemoryError):
        return FailureClass.OOM
    name = type(exc).__name__
    msg = str(exc)
    runtime_typed = "XlaRuntimeError" in name or "JaxRuntimeError" in name
    if not runtime_typed and not isinstance(exc, RuntimeError):
        return None
    if any(m in msg for m in _DEVICE_LOST_MARKERS):
        return FailureClass.DEVICE_LOST
    if any(m in msg for m in _OOM_MARKERS):
        return FailureClass.OOM
    if any(m in msg for m in _COMPILE_MARKERS):
        return FailureClass.COMPILE
    if runtime_typed or any(m in msg for m in _RUNTIME_MARKERS):
        return FailureClass.TRANSIENT
    # a plain RuntimeError with no runtime-layer markers: application code
    return None


def jittered_backoff_s(
    attempt: int,
    *,
    base_s: float,
    cap_s: float,
    rng: random.Random | None = None,
) -> float:
    """Full-jitter exponential backoff: uniform in (0, min(cap, base*2^n)].

    Shared by the supervisor's transient retries and the Kafka transport's
    reroute/reconnect retries; `rng` is injectable so tests pin the draw.
    """
    if attempt < 1:
        attempt = 1
    ceiling = min(cap_s, base_s * (2.0 ** (attempt - 1)))
    draw = (rng or random).random()
    # never 0: a zero sleep turns "backoff" into a hot retry loop
    return ceiling * max(draw, 0.05)


# ----------------------------------------------------------------------
# circuit breaker
# ----------------------------------------------------------------------


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Failure-count breaker with timed half-open probing.

    CLOSED counts consecutive operation-level failures; at
    `failure_threshold` it OPENs.  While OPEN, `probe_due()` turns true
    every `probe_interval_s`; the owner runs its health probe between
    `begin_probe()` and `probe_succeeded()`/`probe_failed()` (HALF_OPEN in
    between, so /state can show a probe in flight).  All transitions are
    lock-serialized — request threads and the precompute thread share one
    breaker."""

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        probe_interval_s: float = 30.0,
        clock=time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        self._lock = threading.Lock()
        self._clock = clock
        self.failure_threshold = failure_threshold
        self.probe_interval_s = probe_interval_s
        self._state = BreakerState.CLOSED
        self._consecutive = 0
        self._next_probe_at = 0.0
        self._opened_at: float | None = None
        #: transitions into OPEN so far — consumers detect "opened again"
        #: by epoch comparison instead of registering callbacks
        self.open_epoch = 0

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def record_failure(self) -> bool:
        """Count one operation-level classified failure; True exactly when
        this failure transitions the breaker to OPEN."""
        with self._lock:
            self._consecutive += 1
            if self._state is BreakerState.CLOSED and (
                self._consecutive >= self.failure_threshold
            ):
                self._open_locked()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state is BreakerState.CLOSED:
                self._consecutive = 0

    def _open_locked(self) -> None:
        self._state = BreakerState.OPEN
        self.open_epoch += 1
        self._opened_at = self._clock()
        self._next_probe_at = self._opened_at + self.probe_interval_s

    def probe_due(self) -> bool:
        with self._lock:
            return (
                self._state is BreakerState.OPEN
                and self._clock() >= self._next_probe_at
            )

    def begin_probe(self) -> bool:
        """OPEN + due -> HALF_OPEN; False when another thread won the race
        (it is running the probe — this caller just sees OPEN)."""
        with self._lock:
            if self._state is not BreakerState.OPEN:
                return False
            if self._clock() < self._next_probe_at:
                return False
            self._state = BreakerState.HALF_OPEN
            return True

    def probe_succeeded(self) -> None:
        with self._lock:
            self._state = BreakerState.CLOSED
            self._consecutive = 0
            self._opened_at = None

    def probe_failed(self) -> None:
        with self._lock:
            if self._state is BreakerState.HALF_OPEN:
                self._state = BreakerState.OPEN
            self._next_probe_at = self._clock() + self.probe_interval_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state.value,
                "consecutiveFailures": self._consecutive,
                "failureThreshold": self.failure_threshold,
                "openEpoch": self.open_epoch,
                "openForSeconds": (
                    round(self._clock() - self._opened_at, 1)
                    if self._opened_at is not None
                    else None
                ),
            }


# ----------------------------------------------------------------------
# supervisor
# ----------------------------------------------------------------------


class DeviceSupervisor:
    """Bounded, classified, breaker-guarded execution of device ops.

    One instance per service (the facade builds it from `tpu.supervisor.*`
    keys) shared by every optimizer the facade creates, so ad-hoc
    per-request optimizers and the precompute thread all feed the same
    breaker.  Thread-safe throughout.
    """

    def __init__(
        self,
        *,
        op_timeout_s: float = 300.0,
        max_retries: int = 2,
        retry_backoff_s: float = 0.25,
        retry_backoff_cap_s: float = 5.0,
        breaker_failure_threshold: int = 3,
        probe_interval_s: float = 30.0,
        probe_timeout_s: float = 20.0,
        sensors=None,
        probe=None,
        rng: random.Random | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
        tracer=None,
    ):
        """probe: () -> str | None (None = healthy) — defaults to the
        trivial-op watchdog under `probe_timeout_s`; injectable for tests.
        rng feeds the retry jitter; clock/sleep are injectable so breaker
        timing tests run without wall-clock waits.  tracer: flight
        recorder every supervised call opens a `device.<op>` span on
        (retries, classified failures and breaker transitions become span
        events); defaults to the process-wide common.trace.TRACER."""
        if op_timeout_s <= 0:
            raise ValueError(f"op_timeout_s must be > 0, got {op_timeout_s}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        self.op_timeout_s = op_timeout_s
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_cap_s = retry_backoff_cap_s
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failure_threshold,
            probe_interval_s=probe_interval_s,
            clock=clock,
        )
        self.probe_timeout_s = probe_timeout_s
        self._probe = probe or (lambda: device_watchdog(self.probe_timeout_s))
        self._probe_lock = threading.Lock()
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._lock = threading.Lock()
        self.sensors = sensors
        from cruise_control_tpu.common.trace import TRACER

        self.tracer = tracer if tracer is not None else TRACER
        self._failure_counts: dict[FailureClass, int] = {c: 0 for c in FailureClass}
        #: latest per-device probe verdicts from mesh attribution fan-outs
        self._device_health: dict[int, dict] = {}
        self.last_failure: dict | None = None
        self.num_retries = 0
        self.num_probes = 0
        self.num_probe_failures = 0
        if sensors is not None:
            # 0 closed / 0.5 probing / 1 open — scrapeable from /state
            sensors.gauge(
                "analyzer.supervisor.breaker-state",
                lambda: {"closed": 0.0, "half_open": 0.5, "open": 1.0}[
                    self.breaker.state.value
                ],
            )

    # -- classification-side bookkeeping --------------------------------

    def _count(self, cls: FailureClass, op: str, exc: BaseException) -> None:
        with self._lock:
            self._failure_counts[cls] += 1
            self.last_failure = {
                "op": op,
                "class": cls.value,
                "error": repr(exc),
                "ms": int(time.time() * 1000),
            }
        if self.sensors is not None:
            self.sensors.counter(f"analyzer.supervisor.failures.{cls.value}").inc()

    # -- bounded call ---------------------------------------------------

    def _bounded(self, fn, op: str, timeout_s: float):
        """Run fn on a daemon thread with a hard deadline.

        The deadline fires DeviceHangError on the caller; the worker (and
        whatever device dispatch it is stuck in) is abandoned — a wedged
        runtime cannot be interrupted, only outlived.  Any engine it holds
        pinned stays exempt from hard buffer release (optimizer pin
        semantics), so an eventual late completion cannot touch freed
        memory."""
        import contextvars

        done = threading.Event()
        box: dict = {}
        # the worker runs with the CALLER'S context copied in: the device
        # scheduler's ambient grants (segmented-execution seam, held-slot
        # reentrancy) are contextvars and must survive this thread hop —
        # a fresh context would silently run a preemptible dispatch
        # unsegmented (tracer parentage rides along too, harmlessly)
        ctx = contextvars.copy_context()

        def worker():
            try:
                box["result"] = ctx.run(fn)
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=worker, daemon=True, name=f"supervised-{op}"
        )
        # black-box Begin BEFORE the worker starts: the supervised call's
        # budget and op land on disk ahead of any chance to block, and the
        # ABANDONMENT verdict below (the one outcome the in-worker
        # device-op record can never write — its thread is the thing that
        # hung) closes the pair.  Deliberately NO runtime introspection on
        # this thread: querying a wedged runtime can itself hang, and this
        # thread is the one enforcing the deadline.
        bb_seq = _BLACKBOX.begin(
            "supervised", op=op, timeout_s=round(timeout_s, 3)
        )
        t.start()
        # deadline extended by scheduler-imposed pause: a segmented
        # dispatch parked at a preemption checkpoint while URGENT work
        # runs is healthy — billing that wait here would turn sustained
        # urgent load into spurious DeviceHangError breaker failures.
        # Host-side carry-checkpoint capture (mesh fault tolerance) is
        # excluded the same way: its CheckpointClock composes into the
        # effective pause, so snapshot I/O never eats the hang budget.
        pause = _current_pause_clock()
        ckpt = current_checkpoint_clock()
        if ckpt is not None:
            prev = pause
            pause = (
                ckpt.seconds
                if prev is None
                else (lambda p=prev, c=ckpt.seconds: p() + c())
            )
        try:
            if pause is None:
                if not done.wait(timeout_s):
                    raise DeviceHangError(op, timeout_s)
            else:
                base = pause()
                deadline = time.monotonic() + timeout_s
                while True:
                    remaining = deadline + max(0.0, pause() - base) - time.monotonic()
                    if remaining <= 0:
                        raise DeviceHangError(op, timeout_s)
                    if done.wait(min(remaining, 0.5)):
                        break
        except DeviceHangError:
            _BLACKBOX.end(bb_seq, ok=False, hang=True, abandoned=True)
            raise
        if "error" in box:
            _BLACKBOX.end(bb_seq, ok=False, error=repr(box["error"]))
            raise box["error"]
        _BLACKBOX.end(bb_seq)
        return box.get("result")

    def call(
        self,
        fn,
        *,
        op: str = "optimize",
        timeout_s: float | None = None,
        breaker: CircuitBreaker | None = None,
        mesh_devices=None,
    ):
        """Run fn under the supervision contract.

        Success resets the breaker's consecutive count.  Classified
        failures: TRANSIENT retries up to `max_retries` with full-jitter
        backoff; exhausted/unretryable failures count one operation-level
        failure toward the breaker and raise DeviceDegradedError.
        Unclassified exceptions propagate unchanged and touch nothing.

        `breaker` substitutes a caller-owned breaker (the optimizer's
        per-mesh-width breakers) for the supervisor's single-device one,
        so a mesh failure degrades the MESH ladder without opening the
        single-device breaker.  `mesh_devices` (the dispatch's mesh, >1
        device) arms attribution: a HANG or unattributed device loss
        triggers a per-device probe fan-out that names the suspect chips,
        upgrading HANG to COLLECTIVE_STALL when only a subset stalled.
        """
        budget = timeout_s if timeout_s is not None else self.op_timeout_s
        brk = breaker if breaker is not None else self.breaker
        with self.tracer.span(
            f"device.{op}", component="device", timeout_s=budget
        ) as sp:
            attempt = 0
            while True:
                try:
                    result = self._bounded(fn, op, budget)
                except BaseException as e:  # noqa: BLE001 — classified below
                    cls = classify_failure(e)
                    if cls is None:
                        raise
                    device_ids = getattr(e, "device_ids", None)
                    if (
                        mesh_devices is not None
                        and len(mesh_devices) > 1
                        and cls in (FailureClass.HANG, FailureClass.DEVICE_LOST)
                    ):
                        cls, device_ids = self._attribute_mesh_failure(
                            op, cls, device_ids, mesh_devices, sp
                        )
                    self._count(cls, op, e)
                    sp.event("failure", failure_class=cls.value, error=repr(e))
                    if cls is FailureClass.TRANSIENT and attempt < self.max_retries:
                        attempt += 1
                        with self._lock:
                            self.num_retries += 1
                        if self.sensors is not None:
                            self.sensors.counter("analyzer.supervisor.retries").inc()
                        backoff = jittered_backoff_s(
                            attempt,
                            base_s=self.retry_backoff_s,
                            cap_s=self.retry_backoff_cap_s,
                            rng=self._rng,
                        )
                        sp.event("retry", attempt=attempt, backoff_s=round(backoff, 4))
                        self._sleep(backoff)
                        continue
                    if brk.record_failure():
                        # a breaker flip is THE degradation moment — make
                        # it a first-class trace event, not just a counter
                        sp.event("breaker-opened", open_epoch=brk.open_epoch)
                        if self.sensors is not None:
                            self.sensors.counter(
                                "analyzer.supervisor.breaker-opened"
                            ).inc()
                    sp.set(attempts=attempt + 1, failure_class=cls.value)
                    raise DeviceDegradedError(op, cls, e, device_ids) from e
                brk.record_success()
                sp.set(attempts=attempt + 1)
                return result

    # -- mesh failure attribution ---------------------------------------

    def _attribute_mesh_failure(self, op, cls, device_ids, mesh_devices, sp):
        """Per-device probe fan-out after a mesh dispatch failed.

        Returns the (possibly upgraded) failure class plus the suspect
        device ids.  HANG with a strict subset of the mesh unresponsive
        becomes COLLECTIVE_STALL (the collective wedged on those chips);
        all-healthy or all-dead stays HANG (nothing to exclude — the
        whole backend is suspect).  Results land in the per-device health
        registry (/state) and the black-box spool, so a kill names the
        chip, not just the slice."""
        try:
            results = probe_devices(mesh_devices, self.probe_timeout_s)
        except BaseException as e:  # noqa: BLE001 — attribution must not mask
            sp.event("mesh-probe-error", error=repr(e))
            return cls, device_ids
        suspects = tuple(sorted(d for d, diag in results.items() if diag))
        healthy = tuple(sorted(d for d, diag in results.items() if not diag))
        self.note_device_health(results)
        sp.event(
            "mesh-probe", suspects=list(suspects), healthy=list(healthy)
        )
        _BLACKBOX.event(
            "mesh-probe",
            op=op,
            failure_class=cls.value,
            suspects=list(suspects),
            healthy=list(healthy),
        )
        if self.sensors is not None and suspects:
            self.sensors.counter("analyzer.mesh-ft.device-lost").inc(
                len(suspects)
            )
        if cls is FailureClass.HANG and suspects and healthy:
            return FailureClass.COLLECTIVE_STALL, suspects
        if cls is FailureClass.DEVICE_LOST and suspects:
            return cls, suspects
        return cls, device_ids or (suspects or None)

    def note_device_health(self, results: dict) -> None:
        """Record per-device probe outcomes ({id: None | diagnosis})."""
        now_ms = int(time.time() * 1000)
        with self._lock:
            for did, diag in results.items():
                self._device_health[int(did)] = {
                    "healthy": diag is None,
                    "diagnosis": diag,
                    "ms": now_ms,
                }

    def device_health(self) -> dict:
        """Latest per-device probe verdicts, {id: {healthy, diagnosis, ms}}."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._device_health.items())}

    # -- availability / half-open probing -------------------------------

    @property
    def is_degraded(self) -> bool:
        return self.breaker.state is not BreakerState.CLOSED

    def available(self) -> bool:
        """True when the device path should be attempted.

        While the breaker is OPEN this is where recovery happens: once per
        `probe_interval_s` ONE caller runs the trivial-op watchdog
        (HALF_OPEN during the probe); a healthy probe closes the breaker
        and the call proceeds on the device, a failed one re-arms the
        probe timer and the caller stays degraded.  Lazy probing keeps the
        supervisor threadless — the service's own traffic (requests + the
        precompute loop) provides the cadence."""
        if self.breaker.state is BreakerState.CLOSED:
            return True
        if not self._probe_lock.acquire(blocking=False):
            return False  # another thread is probing right now
        try:
            if not self.breaker.begin_probe():
                return False
            with self._lock:
                self.num_probes += 1
            if self.sensors is not None:
                self.sensors.counter("analyzer.supervisor.probes").inc()
            # a recovery probe is its own root span: it runs on whatever
            # request thread happened to poll availability, and must not
            # attach the breaker's recovery story to that request's trace
            with self.tracer.span(
                "device.probe", component="device", root=True
            ) as sp:
                try:
                    diagnosis = self._probe()
                except BaseException as e:  # noqa: BLE001 — a raising probe is a failed probe
                    diagnosis = repr(e)
                if diagnosis is None:
                    self.breaker.probe_succeeded()
                    sp.event("breaker-closed", open_epoch=self.breaker.open_epoch)
                    sp.set(healthy=True)
                    if self.sensors is not None:
                        self.sensors.counter(
                            "analyzer.supervisor.probe-successes"
                        ).inc()
                    return True
                self.breaker.probe_failed()
                sp.set(healthy=False, diagnosis=diagnosis)
                with self._lock:
                    self.num_probe_failures += 1
                    self.last_failure = {
                        "op": "probe",
                        "class": FailureClass.HANG.value,
                        "error": diagnosis,
                        "ms": int(time.time() * 1000),
                    }
                if self.sensors is not None:
                    self.sensors.counter("analyzer.supervisor.probe-failures").inc()
                return False
        finally:
            self._probe_lock.release()

    @property
    def open_epoch(self) -> int:
        return self.breaker.open_epoch

    def state_json(self) -> dict:
        """The /state `AnalyzerState.supervisor` block."""
        with self._lock:
            counts = {c.value: n for c, n in self._failure_counts.items()}
            last = dict(self.last_failure) if self.last_failure else None
            retries, probes, probe_failures = (
                self.num_retries, self.num_probes, self.num_probe_failures,
            )
            health = {
                str(k): dict(v)
                for k, v in sorted(self._device_health.items())
            }
        out = self.breaker.snapshot()
        out["breaker"] = out.pop("state")
        out.update(
            opTimeoutSeconds=self.op_timeout_s,
            failureCounts=counts,
            lastFailure=last,
            numRetries=retries,
            numProbes=probes,
            numProbeFailures=probe_failures,
        )
        if health:
            out["deviceHealth"] = health
        return out
