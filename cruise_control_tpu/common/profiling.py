"""Device profiling hooks: memory/buffer gauges + opt-in jax.profiler dump.

Two observability gaps the trace layer alone does not close:

  * **Where is the HBM going?**  Engine caches, shape-bucket padding and
    scenario batches all hold device buffers; `register_device_gauges`
    publishes per-backend memory-in-use / limit / live-buffer-count gauges
    (aggregates under fixed sensor names, per-device detail as a labeled
    collector) so `/metrics` answers it continuously.
  * **What is the device DOING during a slow run?**  The span layer times
    stages; `profiler_trace` (config `tpu.profiler.*`) wraps one engine
    run in a `jax.profiler.trace` dump — the XLA-level view (op timeline,
    fusion, transfers) an operator attaches TensorBoard/XProf to.  Opt-in:
    profiler dumps cost real time and disk, so the default is off.

Everything here degrades to no-ops on backends without the introspection
APIs (CPU `memory_stats()` returns None; Gauge callbacks that raise read
as NaN), so the gauges are safe to register unconditionally.
"""

from __future__ import annotations

import contextlib


def _memory_stat(field: str) -> float:
    """Sum a memory_stats field across local devices; 0.0 where a backend
    exposes no stats (host CPU) — the per-device collector distinguishes."""
    import jax

    total = 0.0
    for d in jax.local_devices():
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if stats:
            total += float(stats.get(field, 0) or 0)
    return total


def _live_buffer_count() -> float:
    import jax

    return float(len(jax.live_arrays()))


def _per_device_memory() -> list[tuple[dict, float]]:
    import jax

    out = []
    for d in jax.local_devices():
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if stats:
            out.append(
                (
                    {"device": str(d.id), "platform": d.platform},
                    float(stats.get("bytes_in_use", 0) or 0),
                )
            )
    return out


def per_device_live_bytes() -> dict:
    """Live bytes RESIDENT per device right now, keyed by device id.

    Prefers the backend allocator's ``bytes_in_use`` (real HBM, includes
    XLA scratch); backends without ``memory_stats`` (host CPU, including
    the virtual ``--xla_force_host_platform_device_count`` mesh the bench
    and tests run on) fall back to summing each live array's addressable
    shard bytes onto the shard's device — exactly the model/carry
    footprint the sharded-model mode claims to cut, minus scratch."""
    import jax

    out: dict = {}
    stats_seen = False
    for d in jax.local_devices():
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if stats and stats.get("bytes_in_use") is not None:
            stats_seen = True
            out[d.id] = float(stats.get("bytes_in_use", 0) or 0)
    if stats_seen:
        return out
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                out[shard.device.id] = out.get(shard.device.id, 0.0) + float(
                    shard.data.nbytes
                )
        except Exception:  # noqa: BLE001 — deleted/donated buffers mid-walk
            continue
    return out


class PeakLiveBytesTracker:
    """Max-over-time per-(bucket, device) live-bytes attribution.

    `record(bucket)` samples `per_device_live_bytes` and maxes each
    device's reading into that shape bucket's cell; `values()` is the
    labeled-collector callback shape the sensor registry expects.  The
    optimizer records after every engine run, so the bench's "per-device
    HBM headroom at the north-star shape" claim is a scraped
    `/metrics` series (`tpu.device.peak-live-bytes-by-bucket`), not a
    one-off print."""

    def __init__(self):
        self._peaks: dict = {}

    def record(self, bucket: str) -> None:
        try:
            sample = per_device_live_bytes()
        except Exception:  # noqa: BLE001 — observability never fails a run
            return
        for dev, val in sample.items():
            key = (str(bucket), str(dev))
            if val > self._peaks.get(key, 0.0):
                self._peaks[key] = val

    def values(self) -> list:
        return [
            ({"bucket": b, "device": d}, v) for (b, d), v in sorted(self._peaks.items())
        ]


def register_device_gauges(sensors) -> "PeakLiveBytesTracker":
    """Install the device-memory/buffer sensor surface on a registry.

    Names are fixed (documented in docs/sensors.md; the drift test walks
    them); per-device breakdown rides collector LABELS, never dynamic
    sensor names.  Returns the peak tracker so the optimizer can feed it
    per-bucket samples."""
    sensors.gauge("tpu.device.memory-in-use-bytes", lambda: _memory_stat("bytes_in_use"))
    sensors.gauge("tpu.device.memory-limit-bytes", lambda: _memory_stat("bytes_limit"))
    sensors.gauge("tpu.device.live-buffers", _live_buffer_count)
    sensors.collector("tpu.device.memory-by-device", _per_device_memory)
    tracker = PeakLiveBytesTracker()
    sensors.collector("tpu.device.peak-live-bytes-by-bucket", tracker.values)
    return tracker


@contextlib.contextmanager
def profiler_trace(dump_dir: str | None):
    """Wrap a block in a jax.profiler trace dump when `dump_dir` is set
    (config tpu.profiler.enabled + tpu.profiler.dump.dir); no-op otherwise.

    A profiler that fails to start (unsupported backend, unwritable dir)
    must never fail the optimization it was meant to observe — the error
    is swallowed and the block runs unprofiled."""
    if not dump_dir:
        yield
        return
    import jax

    try:
        ctx = jax.profiler.trace(dump_dir)
        ctx.__enter__()
    except Exception:  # noqa: BLE001 — profiling is best-effort
        yield
        return
    try:
        yield
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception:  # noqa: BLE001
            pass
