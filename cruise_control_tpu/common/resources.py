"""Resource taxonomy for the cluster workload model.

The four balanced/capacity-checked resources, in the same canonical order the
reference uses (reference: common/Resource.java:19-26).  The order is load-
bearing: every `[..., NUM_RESOURCES]` array axis in the framework is indexed
by these constants.

Epsilon semantics mirror reference common/Resource.java:28-35: utilization
comparisons tolerate `max(epsilon_abs, EPSILON_PERCENT * (a + b))` — float
accumulation over hundreds of thousands of replicas must not flip balance
decisions.
"""

from __future__ import annotations

import enum

import numpy as np

NUM_RESOURCES = 4

# Relative epsilon applied to the sum of the two compared values
# (reference: common/Resource.java:32).
EPSILON_PERCENT = 0.0008


class Resource(enum.IntEnum):
    """Balanced resources; int value is the array axis index."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        # CPU and network are host-level resources (a host's brokers share
        # NICs/cores); disk is broker-level (reference: common/Resource.java:19-26).
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return True  # all four are tracked per broker

    @property
    def epsilon_abs(self) -> float:
        # Absolute epsilon floor per resource (reference: common/Resource.java:19-26
        # passes a per-resource epsilon into the enum ctor).
        return _EPSILON_ABS[int(self)]

    def epsilon(self, value1: float, value2: float) -> float:
        """Comparison tolerance for two utilization values.

        Mirrors reference common/Resource.java:92-94.
        """
        return max(self.epsilon_abs, EPSILON_PERCENT * (value1 + value2))


# Per-resource absolute epsilon floors, indexed by Resource value.
_EPSILON_ABS = np.array([1e-5, 1e-5, 1e-5, 1e-5], dtype=np.float64)

# Convenience: names in canonical order, e.g. for reports / JSON responses.
RESOURCE_NAMES = tuple(r.name for r in sorted(Resource, key=int))


def epsilon_array(values1, values2):
    """Vectorized epsilon for arrays shaped [..., NUM_RESOURCES]."""
    import jax.numpy as jnp

    eps_abs = jnp.asarray(_EPSILON_ABS, dtype=values1.dtype)
    return jnp.maximum(eps_abs, EPSILON_PERCENT * (values1 + values2))
