"""Observability sensors: counters, gauges, timers, meters in one registry.

TPU-native analog of the reference's Dropwizard MetricRegistry published
under the `kafka.cruisecontrol` JMX domain (reference
KafkaCruiseControlApp.java:39-41; sensor catalog docs/wiki/User
Guide/Sensors.md:1-17).  There is no JVM/JMX here: sensors are plain
thread-safe Python objects snapshotted into the `/state` JSON (substate
`sensors`), which is how a TPU-side service is actually scraped.

Headline sensors (same semantics as the reference catalog):
  * analyzer.proposal-computation-timer  (GoalOptimizer.java:116,155)
  * monitor.cluster-model-creation-timer (LoadMonitor.java:100,510)
  * executor.execution-started / -stopped, per-mode gauges
    (Executor.java:118-125,257)
  * anomaly-detector per-type rates + mean-time-between-anomalies
    (detector/AnomalyMetrics.java:1, MeanTimeBetweenAnomaliesMs.java:1)
  * analyzer.supervisor.* — supervised optimizer runtime: breaker-state
    gauge (0 closed / 0.5 half-open / 1 open), per-class device failure
    counters (hang/compile/oom/transient), retry + probe counters; plus
    analyzer.degraded-proposals for CPU-greedy-served results (no
    reference analog — the reference has no accelerator to lose; see
    docs/sensors.md "Ops note: degraded-mode gauges")
  * executor.recovery.* — crash-safe execution: journal reconciliation
    counters (executions-recovered, tasks-{completed,readopted,
    resubmitted}, throttles-swept, reservations-restored)
  * executor.reaper.stuck-task / .rollback — stuck-move reaper actions
  * executor.adaptive.{backoff,recovery} counters +
    executor.adaptive.inter-broker-cap gauge — load-aware adaptive
    concurrency (reference ConcurrencyAdjuster)
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable


class Counter:
    """Monotonic event count."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        # read under the lock: a bare int read is atomic in CPython today,
        # but `inc` is a read-modify-write and the exposition scrape reads
        # concurrently with every component thread — take the lock so the
        # monotonic-counter contract holds by construction, not by
        # interpreter accident
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self.count}


class Gauge:
    """Point-in-time value; either set explicitly or computed by a callback."""

    def __init__(self, fn: Callable[[], float] | None = None) -> None:
        self._fn = fn
        self._value: float = 0.0

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Timer:
    """Duration statistics with a bounded sample window for percentiles."""

    def __init__(self, window: int = 256) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = 0.0
        self._samples: deque[float] = deque(maxlen=window)

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._min = min(self._min, seconds)
            self._max = max(self._max, seconds)
            self._samples.append(seconds)

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    @property
    def count(self) -> int:
        # same locked-reader contract as Counter.count: `update` writes
        # count/total/min/max as a group, so a reader must not interleave
        with self._lock:
            return self._count

    def total_seconds(self) -> float:
        with self._lock:
            return self._total

    def quantiles(self) -> dict[float, float]:
        """{quantile: seconds} over the bounded sample window — the
        Prometheus summary exposition's source (empty before any update)."""
        with self._lock:
            if not self._samples:
                return {}
            ordered = sorted(self._samples)

            def pct(p: float) -> float:
                return ordered[min(len(ordered) - 1, int(p * len(ordered)))]

            return {0.5: pct(0.50), 0.95: pct(0.95), 0.99: pct(0.99)}

    def snapshot(self) -> dict:
        with self._lock:
            if not self._count:
                return {"type": "timer", "count": 0}
            ordered = sorted(self._samples)

            def pct(p: float) -> float:
                return ordered[min(len(ordered) - 1, int(p * len(ordered)))]

            return {
                "type": "timer",
                "count": self._count,
                "meanMs": 1e3 * self._total / self._count,
                "minMs": 1e3 * self._min,
                "maxMs": 1e3 * self._max,
                "p50Ms": 1e3 * pct(0.50),
                "p95Ms": 1e3 * pct(0.95),
                "p99Ms": 1e3 * pct(0.99),
            }


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.update(time.monotonic() - self._t0)


class Meter:
    """Event rate + mean inter-arrival time (the MTBA sensor's shape:
    reference detector/MeanTimeBetweenAnomaliesMs.java).

    Inter-arrival math rides an injected MONOTONIC clock (default
    time.monotonic): a backwards NTP step must not produce a negative
    mean-time-between or an absurd rate spike.  Wall-clock stamps are kept
    separately, for display only (`lastEventMs` in the snapshot)."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        *,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self._wall = wall
        self._count = 0
        self._first: float | None = None
        self._last: float | None = None
        self._last_wall_ms: int | None = None

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = self._clock()
            self._count += n
            if self._first is None:
                self._first = now
            self._last = now
            self._last_wall_ms = int(self._wall() * 1000)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def mean_time_between_ms(self) -> float:
        """Mean time between events; inf until two events were seen."""
        with self._lock:
            if self._count < 2 or self._first is None or self._last is None:
                return float("inf")
            span = self._last - self._first
            return 1e3 * span / (self._count - 1)

    def rate_per_hour(self) -> float:
        with self._lock:
            # a single event carries no rate information; a tiny span right
            # after it would report an absurd spike (same count>=2 guard as
            # mean_time_between_ms)
            if self._count < 2 or self._first is None:
                return 0.0
            span = max(self._clock() - self._first, 1.0)
            return 3600.0 * self._count / span

    def snapshot(self) -> dict:
        mtb = self.mean_time_between_ms()
        return {
            "type": "meter",
            "count": self.count,
            "ratePerHour": self.rate_per_hour(),
            "meanTimeBetweenMs": (None if mtb == float("inf") else mtb),
            "lastEventMs": self._last_event_wall_ms(),
        }

    def _last_event_wall_ms(self) -> int | None:
        with self._lock:
            return self._last_wall_ms


#: default Histogram boundaries: latency-shaped seconds buckets spanning
#: the service's realistic range (5ms model builds to 5-minute compiles)
DEFAULT_HISTOGRAM_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)


class Histogram:
    """Fixed-boundary histogram with exportable cumulative buckets — the
    sensor type the Prometheus exposition needs (a Timer's bounded sample
    window yields quantiles, but quantiles cannot be aggregated across
    instances; buckets can).

    `observe` optionally takes an EXEMPLAR — a small label dict (by
    convention `{"trace_id": ...}`) naming one concrete observation that
    landed in that bucket.  The OpenMetrics exposition renders the latest
    exemplar per bucket, which is how a p99 outlier on a latency panel
    links straight to its `/trace` replay."""

    def __init__(self, buckets=DEFAULT_HISTOGRAM_BUCKETS) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket boundary")
        if len(set(bounds)) != len(bounds):
            raise ValueError(f"duplicate histogram boundaries: {bounds}")
        self._lock = threading.Lock()
        self.bounds = bounds
        # per-bucket (non-cumulative) counts; last slot is the +Inf bucket
        self._counts = [0] * (len(bounds) + 1)
        # latest exemplar per bucket: (value, labels, wall_ts) or None
        self._exemplars: list = [None] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        import bisect

        i = bisect.bisect_left(self.bounds, float(value))
        with self._lock:
            self._counts[i] += 1
            self._sum += float(value)
            self._count += 1
            if exemplar:
                self._exemplars[i] = (float(value), dict(exemplar), time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def cumulative(self) -> tuple[list[tuple[float, int]], float, int]:
        """([(upper_bound, cumulative_count)...incl +Inf], sum, count) —
        the exposition's `_bucket{le=...}` series, precomputed atomically."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum = []
        running = 0
        for bound, c in zip(self.bounds, counts):
            running += c
            cum.append((bound, running))
        cum.append((float("inf"), running + counts[-1]))
        return cum, total, n

    def exemplars(self) -> list:
        """[(upper_bound, value, labels, wall_ts)] for buckets holding an
        exemplar, ordered like `cumulative()`'s ladder (+Inf last)."""
        with self._lock:
            ex = list(self._exemplars)
        bounds = list(self.bounds) + [float("inf")]
        return [
            (bounds[i], v, labels, ts)
            for i, e in enumerate(ex)
            if e is not None
            for (v, labels, ts) in (e,)
        ]

    def quantile(self, q: float) -> float:
        """Prometheus-style `histogram_quantile`: linear interpolation
        within the bucket the q-th observation falls in (the +Inf bucket
        answers its lower bound — the largest finite boundary).  NaN
        before any observation."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        cum, _total, n = self.cumulative()
        if n == 0:
            return float("nan")
        rank = q * n
        prev_bound, prev_count = 0.0, 0
        for bound, c in cum:
            if c >= rank:
                if bound == float("inf"):
                    return prev_bound  # unbounded bucket: report its floor
                if c == prev_count:
                    return bound
                frac = (rank - prev_count) / (c - prev_count)
                return prev_bound + (bound - prev_bound) * frac
            prev_bound, prev_count = (bound if bound != float("inf") else prev_bound), c
        return prev_bound

    def snapshot(self) -> dict:
        cum, total, n = self.cumulative()
        return {
            "type": "histogram",
            "count": n,
            "sum": round(total, 6),
            "buckets": [
                {"le": ("+Inf" if b == float("inf") else b), "count": c}
                for b, c in cum
            ],
        }


class Collector:
    """Labeled multi-value callback gauge: `fn() -> [(labels, value), ...]`
    with labels a {name: str} dict.  The JSON snapshot and the Prometheus
    exposition both read it at scrape time; per-device memory and
    per-bucket compile attribution ride this instead of minting one sensor
    NAME per device/bucket (names are a documented, drift-tested catalog —
    label values are data)."""

    def __init__(self, fn: Callable[[], list]) -> None:
        self._fn = fn

    def values(self) -> list[tuple[dict, float]]:
        try:
            return [(dict(labels), float(v)) for labels, v in self._fn()]
        except Exception:  # noqa: BLE001 — a failing callback yields no series
            return []

    def snapshot(self) -> dict:
        return {
            "type": "collector",
            "values": [
                {"labels": labels, "value": v} for labels, v in self.values()
            ],
        }


class SensorRegistry:
    """Named sensor catalog; `snapshot()` renders the /state JSON block.

    base_labels: label set stamped onto EVERY sample this registry emits
    in the Prometheus exposition (common/exposition.py) — the fleet
    controller gives each cluster its own registry labeled
    `{cluster: <id>}` so two clusters registering the same sensor family
    stay distinct series instead of last-writer-wins colliding on one
    name.  The JSON snapshot is unlabeled (each registry is already
    scoped to one cluster's /state)."""

    def __init__(self, base_labels: dict[str, str] | None = None) -> None:
        self._lock = threading.Lock()
        self._sensors: dict[str, object] = {}
        self.base_labels: dict[str, str] = dict(base_labels or {})

    def _get(self, name: str, factory):
        with self._lock:
            s = self._sensors.get(name)
            if s is None:
                s = factory()
                self._sensors[name] = s
            return s

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn: Callable[[], float] | None = None) -> Gauge:
        g = self._get(name, lambda: Gauge(fn))
        if fn is not None:
            g._fn = fn
        return g

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._get(
            name,
            (lambda: Histogram(buckets)) if buckets is not None else Histogram,
        )

    def collector(self, name: str, fn: Callable[[], list] | None = None) -> Collector:
        c = self._get(name, lambda: Collector(fn or (lambda: [])))
        if fn is not None:
            c._fn = fn  # re-registration rebinds, like gauge callbacks
        return c

    def get(self, name: str):
        """The sensor registered under `name`, or None — WITHOUT
        creating one (readers like the /fleet rollup must not mint a
        default-boundary histogram the real producer would then be
        stuck with)."""
        with self._lock:
            return self._sensors.get(name)

    def items(self) -> list[tuple[str, object]]:
        """Stable (name, sensor) listing — the exposition iterates this."""
        with self._lock:
            return sorted(self._sensors.items())

    def snapshot(self) -> dict:
        return {name: s.snapshot() for name, s in self.items()}


#: process-wide default registry (components accept an override for tests)
REGISTRY = SensorRegistry()
