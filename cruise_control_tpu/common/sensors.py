"""Observability sensors: counters, gauges, timers, meters in one registry.

TPU-native analog of the reference's Dropwizard MetricRegistry published
under the `kafka.cruisecontrol` JMX domain (reference
KafkaCruiseControlApp.java:39-41; sensor catalog docs/wiki/User
Guide/Sensors.md:1-17).  There is no JVM/JMX here: sensors are plain
thread-safe Python objects snapshotted into the `/state` JSON (substate
`sensors`), which is how a TPU-side service is actually scraped.

Headline sensors (same semantics as the reference catalog):
  * analyzer.proposal-computation-timer  (GoalOptimizer.java:116,155)
  * monitor.cluster-model-creation-timer (LoadMonitor.java:100,510)
  * executor.execution-started / -stopped, per-mode gauges
    (Executor.java:118-125,257)
  * anomaly-detector per-type rates + mean-time-between-anomalies
    (detector/AnomalyMetrics.java:1, MeanTimeBetweenAnomaliesMs.java:1)
  * analyzer.supervisor.* — supervised optimizer runtime: breaker-state
    gauge (0 closed / 0.5 half-open / 1 open), per-class device failure
    counters (hang/compile/oom/transient), retry + probe counters; plus
    analyzer.degraded-proposals for CPU-greedy-served results (no
    reference analog — the reference has no accelerator to lose; see
    docs/sensors.md "Ops note: degraded-mode gauges")
  * executor.recovery.* — crash-safe execution: journal reconciliation
    counters (executions-recovered, tasks-{completed,readopted,
    resubmitted}, throttles-swept, reservations-restored)
  * executor.reaper.stuck-task / .rollback — stuck-move reaper actions
  * executor.adaptive.{backoff,recovery} counters +
    executor.adaptive.inter-broker-cap gauge — load-aware adaptive
    concurrency (reference ConcurrencyAdjuster)
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable


class Counter:
    """Monotonic event count."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self._count}


class Gauge:
    """Point-in-time value; either set explicitly or computed by a callback."""

    def __init__(self, fn: Callable[[], float] | None = None) -> None:
        self._fn = fn
        self._value: float = 0.0

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Timer:
    """Duration statistics with a bounded sample window for percentiles."""

    def __init__(self, window: int = 256) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = 0.0
        self._samples: deque[float] = deque(maxlen=window)

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._min = min(self._min, seconds)
            self._max = max(self._max, seconds)
            self._samples.append(seconds)

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> dict:
        with self._lock:
            if not self._count:
                return {"type": "timer", "count": 0}
            ordered = sorted(self._samples)

            def pct(p: float) -> float:
                return ordered[min(len(ordered) - 1, int(p * len(ordered)))]

            return {
                "type": "timer",
                "count": self._count,
                "meanMs": 1e3 * self._total / self._count,
                "minMs": 1e3 * self._min,
                "maxMs": 1e3 * self._max,
                "p50Ms": 1e3 * pct(0.50),
                "p95Ms": 1e3 * pct(0.95),
                "p99Ms": 1e3 * pct(0.99),
            }


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.update(time.monotonic() - self._t0)


class Meter:
    """Event rate + mean inter-arrival time (the MTBA sensor's shape:
    reference detector/MeanTimeBetweenAnomaliesMs.java)."""

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self._count = 0
        self._first: float | None = None
        self._last: float | None = None

    def mark(self, n: int = 1) -> None:
        with self._lock:
            now = self._clock()
            self._count += n
            if self._first is None:
                self._first = now
            self._last = now

    @property
    def count(self) -> int:
        return self._count

    def mean_time_between_ms(self) -> float:
        """Mean time between events; inf until two events were seen."""
        with self._lock:
            if self._count < 2 or self._first is None or self._last is None:
                return float("inf")
            span = self._last - self._first
            return 1e3 * span / (self._count - 1)

    def rate_per_hour(self) -> float:
        with self._lock:
            # a single event carries no rate information; a tiny span right
            # after it would report an absurd spike (same count>=2 guard as
            # mean_time_between_ms)
            if self._count < 2 or self._first is None:
                return 0.0
            span = max(self._clock() - self._first, 1.0)
            return 3600.0 * self._count / span

    def snapshot(self) -> dict:
        mtb = self.mean_time_between_ms()
        return {
            "type": "meter",
            "count": self._count,
            "ratePerHour": self.rate_per_hour(),
            "meanTimeBetweenMs": (None if mtb == float("inf") else mtb),
        }


class SensorRegistry:
    """Named sensor catalog; `snapshot()` renders the /state JSON block."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sensors: dict[str, object] = {}

    def _get(self, name: str, factory):
        with self._lock:
            s = self._sensors.get(name)
            if s is None:
                s = factory()
                self._sensors[name] = s
            return s

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn: Callable[[], float] | None = None) -> Gauge:
        g = self._get(name, lambda: Gauge(fn))
        if fn is not None:
            g._fn = fn
        return g

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._sensors.items())
        return {name: s.snapshot() for name, s in sorted(items)}


#: process-wide default registry (components accept an override for tests)
REGISTRY = SensorRegistry()
