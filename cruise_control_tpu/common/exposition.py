"""Prometheus text exposition for the SensorRegistry (+ a lint parser).

`GET /metrics` renders the whole sensor catalog in the Prometheus text
format (version 0.0.4) so the service is scrapeable by any standard
collector instead of only via the `/state` JSON blob:

  * Counter   -> `counter`, sample `<name>_total` (monotonic)
  * Gauge     -> `gauge`
  * Timer     -> `summary` in SECONDS: `<name>_seconds{quantile=...}` over
                 the bounded sample window + `_sum`/`_count` (totals exact,
                 quantiles windowed — same caveat as the JSON snapshot)
  * Meter     -> `<name>_total` counter + `<name>_rate_per_hour` gauge
  * Histogram -> `histogram`: cumulative `_bucket{le=...}` + `_sum`/`_count`
  * Collector -> `gauge` with one labeled sample per (labels, value) entry

Sensor names are dotted-kebab (`analyzer.engine-cache-hits`); Prometheus
names must match `[a-zA-Z_:][a-zA-Z0-9_:]*`, so `metric_name` sanitizes
every non-conforming rune to `_` under a configurable namespace prefix
(`metrics.prometheus.namespace`).  Sanitization can collide two catalog
names onto one metric family — `prometheus_text` raises rather than emit a
duplicate family, because a silently merged counter lies to every alert
built on it.

`parse_exposition` is the deliberately small strict parser behind the
scripts/check.sh lint gate and the tests: TYPE-before-samples, one TYPE
per family, counter naming + non-negativity, label syntax/escaping, and
histogram bucket monotonicity (with the `+Inf` bucket == `_count`).
"""

from __future__ import annotations

import math
import re

from cruise_control_tpu.common.sensors import (
    Collector,
    Counter,
    Gauge,
    Histogram,
    Meter,
    SensorRegistry,
    Timer,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def metric_name(name: str, *, namespace: str = "cruisecontrol") -> str:
    """Sanitize a sensor catalog name into a Prometheus metric name."""
    base = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    out = f"{namespace}_{base}" if namespace else base
    if not _NAME_OK.match(out):
        # a namespace starting with a digit, or an empty namespace with a
        # digit-leading sensor name
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
    return format(float(v), ".10g")


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        if not _LABEL_NAME_OK.match(str(k)):
            raise ValueError(f"invalid Prometheus label name {k!r}")
        parts.append(f'{k}="{_escape_label(labels[k])}"')
    return "{" + ",".join(parts) + "}"


def prometheus_text(registry: SensorRegistry, *, namespace: str = "cruisecontrol") -> str:
    """Render the registry in the exposition format; ends with a newline."""
    lines: list[str] = []
    seen_families: dict[str, str] = {}  # family -> source sensor name

    def family(sensor_name: str, suffix: str, ptype: str) -> str:
        fam = metric_name(sensor_name, namespace=namespace) + suffix
        prior = seen_families.get(fam)
        if prior is not None and prior != sensor_name:
            raise ValueError(
                f"sensor names {prior!r} and {sensor_name!r} sanitize to the "
                f"same Prometheus family {fam!r}; rename one"
            )
        if prior is None:
            seen_families[fam] = sensor_name
            lines.append(f"# HELP {fam} sensor {sensor_name}")
            lines.append(f"# TYPE {fam} {ptype}")
        return fam

    for name, sensor in registry.items():
        if isinstance(sensor, Counter):
            fam = family(name, "_total", "counter")
            lines.append(f"{fam} {_fmt(sensor.count)}")
        elif isinstance(sensor, Gauge):
            fam = family(name, "", "gauge")
            lines.append(f"{fam} {_fmt(sensor.value)}")
        elif isinstance(sensor, Timer):
            fam = family(name, "_seconds", "summary")
            for q, v in sorted(sensor.quantiles().items()):
                lines.append(f'{fam}{{quantile="{_fmt(q)}"}} {_fmt(v)}')
            lines.append(f"{fam}_sum {_fmt(sensor.total_seconds())}")
            lines.append(f"{fam}_count {_fmt(sensor.count)}")
        elif isinstance(sensor, Meter):
            fam = family(name, "_total", "counter")
            lines.append(f"{fam} {_fmt(sensor.count)}")
            rfam = family(name + ".rate-per-hour", "", "gauge")
            lines.append(f"{rfam} {_fmt(sensor.rate_per_hour())}")
        elif isinstance(sensor, Histogram):
            fam = family(name, "", "histogram")
            cum, total, n = sensor.cumulative()
            for bound, c in cum:
                le = "+Inf" if bound == float("inf") else _fmt(bound)
                lines.append(f'{fam}_bucket{{le="{le}"}} {_fmt(c)}')
            lines.append(f"{fam}_sum {_fmt(total)}")
            lines.append(f"{fam}_count {_fmt(n)}")
        elif isinstance(sensor, Collector):
            fam = family(name, "", "gauge")
            for labels, v in sensor.values():
                lines.append(f"{fam}{_labels(labels)} {_fmt(v)}")
        # unknown sensor types are skipped: the exposition only promises
        # the documented catalog
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# minimal strict parser (the exposition lint gate)
# ----------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$"
)
_LABEL_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<value>(?:[^"\\]|\\["\\n])*)"\s*(?:,|$)'
)
_SUMMARY_HISTOGRAM_SUFFIXES = ("_sum", "_count", "_bucket")


class ExpositionError(ValueError):
    """A lint violation in a /metrics body, with the offending line."""


def _parse_labels(raw: str) -> dict:
    labels: dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ExpositionError(f"malformed label block {raw!r}")
        name = m.group("name")
        if name in labels:
            raise ExpositionError(f"duplicate label {name!r} in {raw!r}")
        labels[name] = (
            m.group("value")
            .replace("\\n", "\n")
            .replace('\\"', '"')
            .replace("\\\\", "\\")
        )
        pos = m.end()
    return labels


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse + lint an exposition body.

    Returns {family: {"type": str, "samples": [(name, labels, value)]}}.
    Raises ExpositionError on: samples without a preceding TYPE, repeated
    TYPE lines, bad sample/label syntax, unparseable values, counters not
    ending in `_total` or going negative, and histograms whose cumulative
    buckets decrease or whose `+Inf` bucket disagrees with `_count`.
    """
    families: dict[str, dict] = {}

    def family_of(sample_name: str) -> str | None:
        if sample_name in families:
            return sample_name
        for suffix in _SUMMARY_HISTOGRAM_SUFFIXES:
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if base in families and families[base]["type"] in (
                    "summary", "histogram",
                ):
                    return base
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ExpositionError(f"line {lineno}: malformed TYPE line {line!r}")
            _, _, fam, ptype = parts
            if ptype not in ("counter", "gauge", "summary", "histogram", "untyped"):
                raise ExpositionError(f"line {lineno}: unknown type {ptype!r}")
            if fam in families:
                raise ExpositionError(f"line {lineno}: duplicate TYPE for {fam!r}")
            if ptype == "counter" and not fam.endswith("_total"):
                raise ExpositionError(
                    f"line {lineno}: counter family {fam!r} must end in _total"
                )
            families[fam] = {"type": ptype, "samples": []}
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionError(f"line {lineno}: malformed sample line {line!r}")
        name = m.group("name")
        fam = family_of(name)
        if fam is None:
            raise ExpositionError(
                f"line {lineno}: sample {name!r} has no preceding TYPE line"
            )
        labels = _parse_labels(m.group("labels")) if m.group("labels") else {}
        raw_v = m.group("value")
        try:
            value = float(raw_v)
        except ValueError as e:
            raise ExpositionError(
                f"line {lineno}: unparseable value {raw_v!r}"
            ) from e
        if families[fam]["type"] == "counter" and name == fam and value < 0:
            raise ExpositionError(
                f"line {lineno}: counter {fam!r} is negative ({value})"
            )
        families[fam]["samples"].append((name, labels, value))

    # histogram structural lint: buckets cumulative + +Inf == _count
    for fam, info in families.items():
        if info["type"] != "histogram":
            continue
        buckets = [
            (labels.get("le"), v)
            for name, labels, v in info["samples"]
            if name == fam + "_bucket"
        ]
        if not buckets:
            raise ExpositionError(f"histogram {fam!r} emitted no buckets")
        if buckets[-1][0] != "+Inf":
            raise ExpositionError(f"histogram {fam!r} missing the +Inf bucket")
        prev = -1.0
        for le, v in buckets:
            if v < prev:
                raise ExpositionError(
                    f"histogram {fam!r} bucket le={le} decreases ({v} < {prev})"
                )
            prev = v
        counts = [
            v for name, _, v in info["samples"] if name == fam + "_count"
        ]
        if counts and counts[0] != buckets[-1][1]:
            raise ExpositionError(
                f"histogram {fam!r}: +Inf bucket {buckets[-1][1]} != _count {counts[0]}"
            )
    return families
