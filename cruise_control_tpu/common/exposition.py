"""Prometheus text exposition for the SensorRegistry (+ a lint parser).

`GET /metrics` renders the whole sensor catalog in the Prometheus text
format (version 0.0.4) so the service is scrapeable by any standard
collector instead of only via the `/state` JSON blob:

  * Counter   -> `counter`, sample `<name>_total` (monotonic)
  * Gauge     -> `gauge`
  * Timer     -> `summary` in SECONDS: `<name>_seconds{quantile=...}` over
                 the bounded sample window + `_sum`/`_count` (totals exact,
                 quantiles windowed — same caveat as the JSON snapshot)
  * Meter     -> `<name>_total` counter + `<name>_rate_per_hour` gauge
  * Histogram -> `histogram`: cumulative `_bucket{le=...}` + `_sum`/`_count`
  * Collector -> `gauge` with one labeled sample per (labels, value) entry

Sensor names are dotted-kebab (`analyzer.engine-cache-hits`); Prometheus
names must match `[a-zA-Z_:][a-zA-Z0-9_:]*`, so `metric_name` sanitizes
every non-conforming rune to `_` under a configurable namespace prefix
(`metrics.prometheus.namespace`).  Sanitization can collide two catalog
names onto one metric family — `prometheus_text` raises rather than emit a
duplicate family, because a silently merged counter lies to every alert
built on it.

`parse_exposition` is the deliberately small strict parser behind the
scripts/check.sh lint gate and the tests: TYPE-before-samples, one TYPE
per family, counter naming + non-negativity, label syntax/escaping, and
histogram bucket monotonicity (with the `+Inf` bucket == `_count`).

OpenMetrics flavor (`prometheus_text(..., openmetrics=True)` — served
when `GET /metrics` is asked for `application/openmetrics-text` or
`?format=openmetrics`): the same family structure plus EXEMPLARS on
histogram `_bucket` samples (` # {trace_id="..."} <value> <ts>`) and the
terminating `# EOF` line.  Exemplars are how a latency panel's p99
outlier links straight to its `/trace` replay — each Histogram sensor
keeps the latest exemplar per bucket (common/sensors.py).  The lint
parser accepts and validates the exemplar syntax on `_bucket`/`_total`
samples and rejects it anywhere else.
"""

from __future__ import annotations

import math
import re

from cruise_control_tpu.common.sensors import (
    Collector,
    Counter,
    Gauge,
    Histogram,
    Meter,
    SensorRegistry,
    Timer,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def metric_name(name: str, *, namespace: str = "cruisecontrol") -> str:
    """Sanitize a sensor catalog name into a Prometheus metric name."""
    base = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    out = f"{namespace}_{base}" if namespace else base
    if not _NAME_OK.match(out):
        # a namespace starting with a digit, or an empty namespace with a
        # digit-leading sensor name
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
    return format(float(v), ".10g")


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        if not _LABEL_NAME_OK.match(str(k)):
            raise ValueError(f"invalid Prometheus label name {k!r}")
        parts.append(f'{k}="{_escape_label(labels[k])}"')
    return "{" + ",".join(parts) + "}"


def prometheus_text(
    registry, *, namespace: str = "cruisecontrol", openmetrics: bool = False
) -> str:
    """Render one registry — or a sequence of them — in the exposition
    format; ends with a newline.

    Multi-registry rendering is the fleet controller's `/metrics` path:
    each cluster owns a registry whose `base_labels` (e.g.
    `{cluster: "east"}`) are stamped onto every sample, and the shared
    core's registry rides unlabeled beside them.  All samples of one
    family are emitted as one group (the format requires it) regardless
    of which registry contributed them, with ONE TYPE line per family.

    `openmetrics=True` additionally renders each Histogram bucket's
    latest exemplar (` # {trace_id=...} value ts`) and terminates the
    body with `# EOF`; the default 0.0.4 text stays byte-identical to
    before exemplars existed (scrapers that never asked for OpenMetrics
    must never see its syntax)."""
    registries = (
        [registry] if isinstance(registry, SensorRegistry) else list(registry)
    )
    #: family -> {"sensor": source name, "type": ptype, "lines": [...]}
    families: dict[str, dict] = {}
    order: list[str] = []

    def family(sensor_name: str, suffix: str, ptype: str) -> tuple[str, list]:
        fam = metric_name(sensor_name, namespace=namespace) + suffix
        info = families.get(fam)
        if info is None:
            info = families[fam] = {
                "sensor": sensor_name, "type": ptype, "lines": [],
            }
            order.append(fam)
        elif info["sensor"] != sensor_name:
            raise ValueError(
                f"sensor names {info['sensor']!r} and {sensor_name!r} "
                f"sanitize to the same Prometheus family {fam!r}; rename one"
            )
        return fam, info["lines"]

    for reg in registries:
        base = dict(getattr(reg, "base_labels", None) or {})
        blk = _labels(base)
        for name, sensor in reg.items():
            if isinstance(sensor, Counter):
                fam, out = family(name, "_total", "counter")
                out.append(f"{fam}{blk} {_fmt(sensor.count)}")
            elif isinstance(sensor, Gauge):
                fam, out = family(name, "", "gauge")
                out.append(f"{fam}{blk} {_fmt(sensor.value)}")
            elif isinstance(sensor, Timer):
                fam, out = family(name, "_seconds", "summary")
                for q, v in sorted(sensor.quantiles().items()):
                    out.append(
                        f"{fam}{_labels({**base, 'quantile': _fmt(q)})} {_fmt(v)}"
                    )
                out.append(f"{fam}_sum{blk} {_fmt(sensor.total_seconds())}")
                out.append(f"{fam}_count{blk} {_fmt(sensor.count)}")
            elif isinstance(sensor, Meter):
                fam, out = family(name, "_total", "counter")
                out.append(f"{fam}{blk} {_fmt(sensor.count)}")
                rfam, rout = family(name + ".rate-per-hour", "", "gauge")
                rout.append(f"{rfam}{blk} {_fmt(sensor.rate_per_hour())}")
            elif isinstance(sensor, Histogram):
                fam, out = family(name, "", "histogram")
                cum, total, n = sensor.cumulative()
                exemplars = (
                    {b: (v, lab, ts) for b, v, lab, ts in sensor.exemplars()}
                    if openmetrics
                    else {}
                )
                for bound, c in cum:
                    le = "+Inf" if bound == float("inf") else _fmt(bound)
                    line = f"{fam}_bucket{_labels({**base, 'le': le})} {_fmt(c)}"
                    ex = exemplars.get(bound)
                    if ex is not None:
                        v, lab, ts = ex
                        line += (
                            f" # {_labels(lab) or '{}'} {_fmt(v)} {_fmt(ts)}"
                        )
                    out.append(line)
                out.append(f"{fam}_sum{blk} {_fmt(total)}")
                out.append(f"{fam}_count{blk} {_fmt(n)}")
            elif isinstance(sensor, Collector):
                fam, out = family(name, "", "gauge")
                for labels, v in sensor.values():
                    # base labels win a key clash: the registry's scope is
                    # authoritative over what a callback claims
                    out.append(f"{fam}{_labels({**labels, **base})} {_fmt(v)}")
            # unknown sensor types are skipped: the exposition only
            # promises the documented catalog
    lines: list[str] = []
    for fam in order:
        info = families[fam]
        lines.append(f"# HELP {fam} sensor {info['sensor']}")
        lines.append(f"# TYPE {fam} {info['type']}")
        lines.extend(info["lines"])
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# minimal strict parser (the exposition lint gate)
# ----------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*?)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?"
    # OpenMetrics exemplar: ` # {labels} value [timestamp]` — rendered
    # only on histogram buckets; linted wherever it appears
    r"(?:\s+#\s+\{(?P<exlabels>.*?)\}\s+(?P<exvalue>[^\s]+)"
    r"(?:\s+(?P<exts>[^\s]+))?)?$"
)
_LABEL_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"(?P<value>(?:[^"\\]|\\["\\n])*)"\s*(?:,|$)'
)
_SUMMARY_HISTOGRAM_SUFFIXES = ("_sum", "_count", "_bucket")


class ExpositionError(ValueError):
    """A lint violation in a /metrics body, with the offending line."""


def _parse_labels(raw: str) -> dict:
    labels: dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ExpositionError(f"malformed label block {raw!r}")
        name = m.group("name")
        if name in labels:
            raise ExpositionError(f"duplicate label {name!r} in {raw!r}")
        labels[name] = (
            m.group("value")
            .replace("\\n", "\n")
            .replace('\\"', '"')
            .replace("\\\\", "\\")
        )
        pos = m.end()
    return labels


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse + lint an exposition body.

    Returns {family: {"type": str, "samples": [(name, labels, value)]}}.
    Raises ExpositionError on: samples without a preceding TYPE, repeated
    TYPE lines, bad sample/label syntax, unparseable values, counters not
    ending in `_total` or going negative, and histograms whose cumulative
    buckets decrease or whose `+Inf` bucket disagrees with `_count`.
    """
    families: dict[str, dict] = {}

    def family_of(sample_name: str) -> str | None:
        if sample_name in families:
            return sample_name
        for suffix in _SUMMARY_HISTOGRAM_SUFFIXES:
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if base in families and families[base]["type"] in (
                    "summary", "histogram",
                ):
                    return base
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ExpositionError(f"line {lineno}: malformed TYPE line {line!r}")
            _, _, fam, ptype = parts
            if ptype not in ("counter", "gauge", "summary", "histogram", "untyped"):
                raise ExpositionError(f"line {lineno}: unknown type {ptype!r}")
            if fam in families:
                raise ExpositionError(f"line {lineno}: duplicate TYPE for {fam!r}")
            if ptype == "counter" and not fam.endswith("_total"):
                raise ExpositionError(
                    f"line {lineno}: counter family {fam!r} must end in _total"
                )
            families[fam] = {"type": ptype, "samples": []}
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionError(f"line {lineno}: malformed sample line {line!r}")
        name = m.group("name")
        fam = family_of(name)
        if fam is None:
            raise ExpositionError(
                f"line {lineno}: sample {name!r} has no preceding TYPE line"
            )
        labels = _parse_labels(m.group("labels")) if m.group("labels") else {}
        raw_v = m.group("value")
        try:
            value = float(raw_v)
        except ValueError as e:
            raise ExpositionError(
                f"line {lineno}: unparseable value {raw_v!r}"
            ) from e
        if families[fam]["type"] == "counter" and name == fam and value < 0:
            raise ExpositionError(
                f"line {lineno}: counter {fam!r} is negative ({value})"
            )
        if m.group("exvalue") is not None:
            # exemplar lint: allowed only where OpenMetrics allows them
            # (histogram buckets, counters), with valid label syntax and
            # a parseable value
            if not (name.endswith("_bucket") or name.endswith("_total")):
                raise ExpositionError(
                    f"line {lineno}: exemplar on non-bucket/counter "
                    f"sample {name!r}"
                )
            if m.group("exlabels"):
                _parse_labels(m.group("exlabels"))
            try:
                float(m.group("exvalue"))
            except ValueError as e:
                raise ExpositionError(
                    f"line {lineno}: unparseable exemplar value "
                    f"{m.group('exvalue')!r}"
                ) from e
        families[fam]["samples"].append((name, labels, value))

    # histogram structural lint: buckets cumulative + +Inf == _count.
    # Grouped by the non-`le` label set: a labeled exposition (the fleet's
    # per-cluster series) interleaves independent bucket ladders in one
    # family, and each ladder must hold the invariants on its own.
    for fam, info in families.items():
        if info["type"] != "histogram":
            continue
        ladders: dict[tuple, list] = {}
        for name, labels, v in info["samples"]:
            if name != fam + "_bucket":
                continue
            key = tuple(sorted((k, x) for k, x in labels.items() if k != "le"))
            ladders.setdefault(key, []).append((labels.get("le"), v))
        if not ladders:
            raise ExpositionError(f"histogram {fam!r} emitted no buckets")
        counts_by_key = {
            tuple(sorted(labels.items())): v
            for name, labels, v in info["samples"]
            if name == fam + "_count"
        }
        for key, buckets in ladders.items():
            if buckets[-1][0] != "+Inf":
                raise ExpositionError(
                    f"histogram {fam!r}{dict(key)} missing the +Inf bucket"
                )
            prev = -1.0
            for le, v in buckets:
                if v < prev:
                    raise ExpositionError(
                        f"histogram {fam!r}{dict(key)} bucket le={le} "
                        f"decreases ({v} < {prev})"
                    )
                prev = v
            count = counts_by_key.get(key)
            if count is not None and count != buckets[-1][1]:
                raise ExpositionError(
                    f"histogram {fam!r}{dict(key)}: +Inf bucket "
                    f"{buckets[-1][1]} != _count {count}"
                )
    return families
