from cruise_control_tpu.common.resources import (
    NUM_RESOURCES,
    RESOURCE_NAMES,
    Resource,
    epsilon_array,
)

__all__ = ["NUM_RESOURCES", "RESOURCE_NAMES", "Resource", "epsilon_array"]
