"""Flight recorder: lightweight spans over the whole proposal pipeline.

One rebalance crosses five subsystems (monitor model build -> analyzer
optimize -> device supervisor op -> executor task lifecycle, with the
detector and planner running their own flows beside it), and until now the
only correlation between them was log archaeology: per-run device timings
live in `OptimizerResult.history`, executor transitions in the journal,
supervisor retries in counters.  The flight recorder stitches them into
one trace — every service operation gets a trace ID, every stage becomes a
span (monotonic clocks, parent links, attributes, bounded events), and
`GET /trace?id=...` replays the tree after the fact.

Design constraints, in order:

  * **Near-zero overhead.**  Tracing is ON by default and sits on the hot
    proposal path, so a span is a plain Python object, IDs come from one
    `uuid4`, and the store is a bounded per-component ring buffer
    (`deque(maxlen=...)`) — no I/O, no serialization, no background
    thread.  The `bench.py --trace-overhead` gate (scripts/check.sh) pins
    the cost under 2% of a smoke proposal run.
  * **Crash-tolerant by construction.**  Spans are published to the ring
    at START (end stamp None while running), so a trace polled mid-flight
    shows its live frontier, and a span abandoned by a hung device thread
    still appears (unfinished) instead of vanishing.
  * **Context propagation without plumbing.**  The active span rides a
    `contextvars.ContextVar`, so nested stages parent automatically within
    a thread; cross-thread handoffs (the user-task pool, the executor
    recovery thread, detector loop) pass an explicit `trace_id`/`root`.

There is no OpenTelemetry dependency on purpose: the container is
hermetic, and the span model here is deliberately the minimal subset that
serves `/trace`, the bench stage summaries, and the learned-warm-start
telemetry of ROADMAP item 3.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from collections import deque

#: the active span of the current logical context (one per thread/task)
_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "cc_current_span", default=None
)


def current_trace_id() -> str:
    """Trace id of the ambient span ("" outside any span) — lets
    non-span producers (the decision ledger's records) stamp the trace
    they ran under without threading ids through every call."""
    sp = _CURRENT.get()
    return sp.trace_id if sp is not None else ""


class Span:
    """One timed stage of a trace.  Mutable until `finish()`; thread-safe
    enough for its uses (attributes/events are appended under the owning
    tracer's lock only when contention is possible — in practice one span
    is written by one thread, the executor's observer hook included)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "component",
        "start_mono", "end_mono", "start_ms", "attributes", "events",
        "error", "_max_events", "events_dropped",
    )

    def __init__(
        self,
        name: str,
        *,
        component: str,
        trace_id: str,
        parent_id: str | None,
        max_events: int = 256,
    ):
        self.name = name
        self.component = component
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start_mono = time.monotonic()
        self.end_mono: float | None = None
        self.start_ms = int(time.time() * 1000)  # wall, display only
        self.attributes: dict = {}
        self.events: list[dict] = []
        self.error: str | None = None
        self._max_events = max_events
        self.events_dropped = 0

    # -- recording ------------------------------------------------------

    def set(self, **attrs) -> "Span":
        """Attach attributes (engine_cache_hit, device_s, bucket, ...)."""
        self.attributes.update(attrs)
        return self

    def event(self, name: str, **attrs) -> None:
        """Append a point-in-time event (task transition, retry, breaker
        flip).  Bounded: past `max_events` the event is counted, not kept —
        a 100k-task execution must not hold 100k dicts per span."""
        if len(self.events) >= self._max_events:
            self.events_dropped += 1
            return
        ev = {"name": name, "offset_s": round(time.monotonic() - self.start_mono, 6)}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def finish(self, error: str | None = None) -> None:
        if self.end_mono is None:
            self.end_mono = time.monotonic()
        if error is not None:
            self.error = error

    # -- reading --------------------------------------------------------

    @property
    def duration_s(self) -> float | None:
        if self.end_mono is None:
            return None
        return self.end_mono - self.start_mono

    def to_json(self) -> dict:
        d = self.duration_s
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "component": self.component,
            "startMs": self.start_ms,
            "startOffsetMonoS": self.start_mono,  # orders spans in a trace
            "durationMs": (None if d is None else round(d * 1e3, 3)),
            "inFlight": self.end_mono is None,
            "attributes": dict(self.attributes),
            "events": list(self.events),
        }
        if self.events_dropped:
            out["eventsDropped"] = self.events_dropped
        if self.error is not None:
            out["error"] = self.error
        return out


class _NoopSpan:
    """Inert span handed out while tracing is disabled — callers never
    branch on the enabled flag."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    events_dropped = 0

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        pass

    def finish(self, error=None):
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory + bounded per-component ring store + trace index.

    Retention is per COMPONENT (config `trace.retention.spans.per.
    component`): a chatty component (device ops under retries) evicts its
    own history, never the executor's.  A trace expires naturally when its
    spans age out of every ring — there is no separate trace GC."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        retention_per_component: int = 512,
        max_events_per_span: int = 512,
    ):
        if retention_per_component < 1:
            raise ValueError(
                f"retention_per_component must be >= 1, got {retention_per_component}"
            )
        if max_events_per_span < 1:
            raise ValueError(
                f"max_events_per_span must be >= 1, got {max_events_per_span}"
            )
        self.enabled = enabled
        self.retention_per_component = retention_per_component
        self.max_events_per_span = max_events_per_span
        self._lock = threading.Lock()
        self._rings: dict[str, deque[Span]] = {}

    # -- span lifecycle -------------------------------------------------

    def new_trace_id(self) -> str:
        return uuid.uuid4().hex

    def start_span(
        self,
        name: str,
        *,
        component: str = "service",
        trace_id: str | None = None,
        parent: Span | None = None,
        root: bool = False,
    ) -> Span:
        """Create + publish a span (visible in the store immediately, end
        stamp pending).  Parentage: explicit `parent` wins; otherwise the
        context-active span unless `root=True` (detector loop, recovery
        thread — flows that must not attach to whatever request context
        the thread inherited)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None and not root:
            parent = _CURRENT.get()
        if isinstance(parent, _NoopSpan):
            parent = None
        if trace_id is None or trace_id == "":
            trace_id = parent.trace_id if parent is not None else self.new_trace_id()
        span = Span(
            name,
            component=component,
            trace_id=trace_id,
            parent_id=parent.span_id if parent is not None else None,
            max_events=self.max_events_per_span,
        )
        with self._lock:
            ring = self._rings.get(component)
            if ring is None:
                ring = deque(maxlen=self.retention_per_component)
                self._rings[component] = ring
            ring.append(span)
        return span

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        component: str = "service",
        trace_id: str | None = None,
        parent: Span | None = None,
        root: bool = False,
        **attrs,
    ):
        """Start, activate (context parent for nested spans), finish."""
        sp = self.start_span(
            name, component=component, trace_id=trace_id, parent=parent, root=root
        )
        if attrs:
            sp.set(**attrs)
        if sp is NOOP_SPAN:
            yield sp
            return
        token = _CURRENT.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.finish(error=repr(e))
            raise
        else:
            sp.finish()
        finally:
            _CURRENT.reset(token)

    def current(self) -> Span | None:
        sp = _CURRENT.get()
        return None if isinstance(sp, _NoopSpan) else sp

    def event(self, name: str, **attrs) -> None:
        """Attach an event to the context-active span; silently dropped
        with no active span (a library running outside any traced flow)."""
        sp = _CURRENT.get()
        if sp is not None:
            sp.event(name, **attrs)

    # -- reading --------------------------------------------------------

    def _all_spans(self) -> list[Span]:
        with self._lock:
            return [s for ring in self._rings.values() for s in ring]

    def trace(self, trace_id: str) -> list[Span]:
        """Every retained span of one trace, oldest first."""
        spans = [s for s in self._all_spans() if s.trace_id == trace_id]
        spans.sort(key=lambda s: s.start_mono)
        return spans

    def trace_tree(self, trace_id: str) -> list[dict]:
        """The trace as a forest of nested span dicts (children under
        `children`).  A span whose parent already aged out of its ring
        surfaces as an extra root rather than disappearing."""
        spans = self.trace(trace_id)
        nodes = {s.span_id: {**s.to_json(), "children": []} for s in spans}
        roots = []
        for s in spans:
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            if parent is not None:
                parent["children"].append(node)
            else:
                roots.append(node)
        return roots

    def recent_traces(self, limit: int = 50) -> list[dict]:
        """Newest-first index of retained ROOT spans — what an operator
        lists before picking a trace ID to replay."""
        roots = [s for s in self._all_spans() if s.parent_id is None]
        roots.sort(key=lambda s: s.start_mono, reverse=True)
        return [
            {
                "traceId": s.trace_id,
                "name": s.name,
                "component": s.component,
                "startMs": s.start_ms,
                "durationMs": (
                    None if s.duration_s is None else round(s.duration_s * 1e3, 3)
                ),
                "inFlight": s.end_mono is None,
                "error": s.error,
            }
            for s in roots[: max(1, limit)]
        ]

    def summarize(self, trace_id: str | None = None) -> dict:
        """Per-stage rollup {span name: {count, totalMs, maxMs, errors}} —
        the bench embeds this next to its wall-clock numbers so the perf
        trajectory records WHERE the time went, not just totals."""
        spans = self.trace(trace_id) if trace_id else self._all_spans()
        out: dict[str, dict] = {}
        for s in spans:
            d = s.duration_s
            if d is None:
                continue
            row = out.setdefault(
                s.name,
                {"component": s.component, "count": 0, "totalMs": 0.0,
                 "maxMs": 0.0, "errors": 0},
            )
            row["count"] += 1
            row["totalMs"] = round(row["totalMs"] + d * 1e3, 3)
            row["maxMs"] = round(max(row["maxMs"], d * 1e3), 3)
            if s.error is not None:
                row["errors"] += 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()

    def scoped(self, scope: str) -> "Tracer":
        """Cluster-scoped view for the fleet controller: spans record into
        the SAME store (trace ids and GET /trace replay stay fleet-global)
        under a namespaced component (`<scope>:monitor`), so every cluster
        gets its own per-component retention rings — one cluster's chatty
        executor can never evict another cluster's history."""
        if not scope:
            return self
        return _ScopedTracer(self, scope)


class _ScopedTracer:
    """Component-namespacing proxy over a shared Tracer (Tracer.scoped)."""

    def __init__(self, base: Tracer, scope: str):
        self._base = base
        self.scope = scope

    @property
    def enabled(self) -> bool:
        return self._base.enabled

    def _component(self, component: str) -> str:
        return f"{self.scope}:{component}"

    def new_trace_id(self) -> str:
        return self._base.new_trace_id()

    def start_span(self, name, *, component="service", **kwargs):
        return self._base.start_span(
            name, component=self._component(component), **kwargs
        )

    def span(self, name, *, component="service", **kwargs):
        return self._base.span(
            name, component=self._component(component), **kwargs
        )

    def current(self):
        return self._base.current()

    def event(self, name, **attrs) -> None:
        self._base.event(name, **attrs)

    def trace(self, trace_id):
        return self._base.trace(trace_id)

    def trace_tree(self, trace_id):
        return self._base.trace_tree(trace_id)

    def recent_traces(self, limit: int = 50):
        return self._base.recent_traces(limit)

    def summarize(self, trace_id=None):
        return self._base.summarize(trace_id)

    def scoped(self, scope: str):
        return self._base.scoped(scope)

    def clear(self) -> None:
        self._base.clear()


#: process-wide default tracer (components accept an override; the facade
#: builds a per-service instance from the trace.* config keys).  Enabled
#: by default — the whole point is that a production incident has a trace
#: waiting, not a knob that was off.
TRACER = Tracer()
