"""Persistent XLA compilation cache.

The engine's statics-as-arguments design already avoids recompiles WITHIN a
process (analyzer/engine.py module docstring), but a service restart used to
pay the full ~70s warm-up again (BENCH_r01 warmup_s).  JAX's persistent
compilation cache writes compiled executables to disk keyed by HLO
fingerprint, so a restarted service (same shapes, same jax/XLA version)
reloads them in milliseconds.

Reference analog: none — a JVM has no compile step to amortize; this is a
TPU-framework concern (the proposal-precompute thread
GoalOptimizer.java:124-175 amortizes model generations, not compilation).
"""

from __future__ import annotations

import os

_enabled = False


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Idempotently point JAX at a durable on-disk compilation cache.

    Returns the directory used, or None when disabled (empty dir given or
    an old jax without the feature).
    """
    global _enabled
    if not cache_dir:
        return None
    cache_dir = os.path.expanduser(cache_dir)
    if _enabled:
        return cache_dir
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # persist even sub-second compiles: a cold process pays dozens of
        # 0.1-0.5s "tiny" compiles (zero-fills, reductions) that add whole
        # seconds to warmup; disk hits are ~ms
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled = True
        return cache_dir
    except Exception:  # pragma: no cover — very old jax
        return None
