"""Persistent XLA compilation cache.

The engine's statics-as-arguments design already avoids recompiles WITHIN a
process (analyzer/engine.py module docstring), but a service restart used to
pay the full ~70s warm-up again (BENCH_r01 warmup_s).  JAX's persistent
compilation cache writes compiled executables to disk keyed by HLO
fingerprint, so a restarted service (same shapes, same jax/XLA version)
reloads them in milliseconds.

Boot observability (config tpu.compile.cache.dir): enabling the cache
records its on-disk entry inventory; `boot_report()` later diffs against
it so the service can log, after the first proposal pass, how many
executables were loaded warm from disk (hits) vs compiled fresh (misses)
— the number ROADMAP item 2's restart SLO is built on.

Reference analog: none — a JVM has no compile step to amortize; this is a
TPU-framework concern (the proposal-precompute thread
GoalOptimizer.java:124-175 amortizes model generations, not compilation).
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger(__name__)

_enabled = False
#: entry names present on disk when the cache was enabled (boot inventory)
_boot_entries: set[str] | None = None
_cache_dir: str | None = None

#: per-bucket engine-program trace accounting since process start:
#: bucket key -> {"fresh": python-traced-and-compiled, "aot": loaded from
#: a serialized jax.export artifact (no Python trace)}.  The restart-SLO
#: gate (bench.py --coldstart) asserts "fresh" stays ZERO for every
#: manifest-listed bucket on a warm-disk restart.
_trace_lock = threading.Lock()
_engine_traces: dict[str, dict[str, int]] = {}


def record_engine_trace(bucket: str, *, source: str) -> None:
    """Count one fused-engine-program acquisition for `bucket`.

    source: "fresh" (Python trace + compile — the cost AOT exists to
    kill) or "aot" (deserialized artifact; compile may still be an XLA
    disk-cache hit).  Counted independently of the persistent cache
    being enabled so tests can assert the fallback ladder."""
    with _trace_lock:
        row = _engine_traces.setdefault(bucket, {"fresh": 0, "aot": 0})
        row[source] = row.get(source, 0) + 1


def engine_trace_counts() -> dict[str, dict[str, int]]:
    with _trace_lock:
        return {k: dict(v) for k, v in _engine_traces.items()}


def reset_engine_trace_counts() -> None:
    """Test seam only — boot accounting is per-process in production."""
    with _trace_lock:
        _engine_traces.clear()


def _scan(cache_dir: str) -> tuple[set[str], int]:
    """(entry names, total bytes) currently on disk; tolerant of races.

    Prunes the `prewarm` and `blackbox` subdirectories: the boot-prewarm
    manifest + AOT artifacts (analyzer/prewarm.py) and the black-box
    dispatch spool (common/blackbox.py) live INSIDE the cache dir by
    default so they share its mount/durability, and their writes must
    not read as XLA compile-cache hits/misses in boot_report()."""
    entries: set[str] = set()
    total = 0
    try:
        for root, _dirs, files in os.walk(cache_dir):
            _dirs[:] = [d for d in _dirs if d not in ("prewarm", "blackbox")]
            for fn in files:
                path = os.path.join(root, fn)
                entries.add(os.path.relpath(path, cache_dir))
                try:
                    total += os.path.getsize(path)
                except OSError:
                    pass
    except OSError:
        pass
    return entries, total


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Idempotently point JAX at a durable on-disk compilation cache.

    Returns the directory used, or None when disabled (empty dir given or
    an old jax without the feature).  Logs the boot inventory — how many
    cached executables a restart can reload instead of re-tracing.
    """
    global _enabled, _boot_entries, _cache_dir
    if not cache_dir:
        return None
    cache_dir = os.path.expanduser(cache_dir)
    if _enabled:
        return cache_dir
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # persist even sub-second compiles: a cold process pays dozens of
        # 0.1-0.5s "tiny" compiles (zero-fills, reductions) that add whole
        # seconds to warmup; disk hits are ~ms
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled = True
        _cache_dir = cache_dir
        _boot_entries, total = _scan(cache_dir)
        log.info(
            "persistent XLA compile cache at %s: %d cached executables "
            "(%.1f MB) available warm at boot",
            cache_dir, len(_boot_entries), total / 1e6,
        )
        return cache_dir
    except Exception:  # pragma: no cover — very old jax
        return None


def boot_report() -> dict | None:
    """Hit/miss view since boot: entries present at enable time (warm,
    reloadable = hits for re-traced programs) vs entries written since
    (fresh compiles = misses).  None when the cache is disabled."""
    if not _enabled or _cache_dir is None or _boot_entries is None:
        return None
    now, total = _scan(_cache_dir)
    return {
        "dir": _cache_dir,
        "entriesAtBoot": len(_boot_entries),
        "newCompiles": len(now - _boot_entries),
        "entries": len(now),
        "bytes": total,
        # fresh-trace vs AOT-load split per engine bucket: the number the
        # --coldstart SLO gate reads (zero "fresh" for manifest buckets
        # on a manifest+AOT restart)
        "engineTraces": engine_trace_counts(),
    }
