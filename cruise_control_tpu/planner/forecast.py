"""Per-topic load forecasting from the monitor's windowed history.

Fits a trend per (topic, resource) over the aggregator's completed
windows (WindowedMetricSampleAggregator.history_snapshot) and emits
future `Scenario`s whose topicLoadFactors scale today's model to the
projected load at a horizon.  Two fitters:

  linear  ordinary least squares over the valid windows — robust default
          for the handful of windows the monitor keeps
  holt    Holt's linear (double) exponential smoothing — weights recent
          windows harder, tracks level shifts faster

Forecast scenarios feed the same batched evaluator every other
hypothetical does: "traffic next week" is just one more Scenario in the
batch, and the rightsizer composes its broker-count sweeps on top of it.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES
from cruise_control_tpu.planner.scenario import Scenario


@dataclasses.dataclass(frozen=True)
class TopicTrend:
    """Fitted per-resource trend of one topic's total load.

    level: projected value at the NEWEST observed window; slope: change
    per window step.  Both are [4] per-resource vectors over the model's
    consumed metrics (CPU, NW_IN, NW_OUT, DISK)."""

    topic: str
    level: np.ndarray  # f32[4]
    slope: np.ndarray  # f32[4]
    windows_observed: int

    def factors_at(self, horizon_windows: float, *, max_factor: float = 10.0) -> tuple:
        """Per-resource multiplicative factors projecting `level` forward
        `horizon_windows` window steps, clamped to [0, max_factor] (a fit
        on a few noisy windows must not 1000x a topic)."""
        base = np.maximum(self.level, 1e-9)
        pred = self.level + self.slope * horizon_windows
        f = np.clip(pred / base, 0.0, max_factor)
        # untrended / unobserved resources stay at 1.0 (a zero-load
        # resource projected to zero is "no change", not "erase it")
        f = np.where(self.level <= 0.0, 1.0, f)
        return tuple(float(x) for x in f)


def fit_linear(y: np.ndarray, valid: np.ndarray) -> tuple[float, float]:
    """OLS (level at the newest point, slope per step) over valid points.

    y is oldest -> newest.  Fewer than 2 valid points degenerate to a
    flat trend at the observed mean."""
    idx = np.nonzero(valid)[0]
    if idx.size == 0:
        return 0.0, 0.0
    if idx.size == 1:
        return float(y[idx[0]]), 0.0
    x = idx.astype(np.float64)
    yy = y[idx].astype(np.float64)
    slope, intercept = np.polyfit(x, yy, 1)
    newest = y.size - 1
    return float(intercept + slope * newest), float(slope)


def fit_holt(
    y: np.ndarray, valid: np.ndarray, *, alpha: float = 0.5, beta: float = 0.3
) -> tuple[float, float]:
    """Holt's linear exponential smoothing over valid points (oldest ->
    newest); gaps are skipped (the smoothing state carries across)."""
    idx = np.nonzero(valid)[0]
    if idx.size == 0:
        return 0.0, 0.0
    if idx.size == 1:
        return float(y[idx[0]]), 0.0
    level = float(y[idx[0]])
    trend = float(y[idx[1]] - y[idx[0]])
    prev = idx[0]
    for i in idx[1:]:
        steps = int(i - prev)
        forecast = level + trend * steps
        obs = float(y[i])
        new_level = alpha * obs + (1 - alpha) * forecast
        new_trend = beta * (new_level - level) / steps + (1 - beta) * trend
        level, trend = new_level, new_trend
        prev = i
    # roll the smoothed state forward to the newest window
    tail = int((y.size - 1) - prev)
    return level + trend * tail, trend


_FITTERS = {"linear": fit_linear, "holt": fit_holt}


class LoadForecaster:
    """Fits TopicTrends from a WindowedHistory and emits future Scenarios."""

    def __init__(
        self,
        *,
        method: str = "linear",
        min_windows: int = 3,
        max_factor: float = 10.0,
    ):
        if method not in _FITTERS:
            raise ValueError(f"unknown forecast method {method!r} (linear | holt)")
        self.method = method
        self.min_windows = min_windows
        self.max_factor = max_factor

    def fit(self, history, metric_def, topic_names: dict | None = None) -> list[TopicTrend]:
        """Per-topic trends from an aggregator WindowedHistory.

        Entities must be PartitionEntity-shaped (topic, partition) — the
        partition aggregator's layout; per-topic totals are the sum over
        the topic's partitions per window.  topic_names maps topic id ->
        display name (catalog.topic_names_by_id()); absent ids keep their
        numeric spelling so the scenario can resolve them without a
        catalog."""
        cols = [
            metric_def.metric_id("CPU_USAGE"),
            metric_def.metric_id("LEADER_BYTES_IN"),
            metric_def.metric_id("LEADER_BYTES_OUT"),
            metric_def.metric_id("DISK_USAGE"),
        ]
        E = len(history.entities)
        if E == 0:
            return []
        tids = np.fromiter(
            (int(getattr(e, "topic")) for e in history.entities), np.int64, count=E
        )
        uniq = np.unique(tids)
        # oldest -> newest for the fitters (history is newest-first)
        values = history.values[:, ::-1][:, :, cols]  # [E, W, 4]
        complete = history.complete[:, ::-1]  # [E, W]
        W = values.shape[1]
        trends = []
        for t in uniq:
            rows = tids == t
            # a window observes the topic when every partition reported a
            # complete cell — summing a half-sampled window would read as
            # a traffic drop and poison the slope
            vmask = complete[rows].all(axis=0)  # [W]
            if int(vmask.sum()) < self.min_windows:
                continue
            totals = values[rows].sum(axis=0)  # [W, 4]
            level = np.zeros(NUM_RESOURCES, np.float64)
            slope = np.zeros(NUM_RESOURCES, np.float64)
            fit = _FITTERS[self.method]
            for r in range(NUM_RESOURCES):
                level[r], slope[r] = fit(totals[:, r], vmask)
            name = (topic_names or {}).get(int(t), str(int(t)))
            trends.append(
                TopicTrend(
                    topic=name,
                    level=np.maximum(level, 0.0),
                    slope=slope,
                    windows_observed=int(vmask.sum()),
                )
            )
        return trends

    def scenario_at(
        self, trends: list[TopicTrend], horizon_ms: int, window_ms: int, *,
        name: str | None = None,
    ) -> Scenario:
        """One Scenario scaling each trended topic to its projected load
        `horizon_ms` from now."""
        steps = horizon_ms / max(window_ms, 1)
        factors = {
            tr.topic: tr.factors_at(steps, max_factor=self.max_factor)
            for tr in trends
        }
        return Scenario(
            name=name or f"forecast+{horizon_ms}ms",
            topic_load_factors=factors,
        )

    def scenarios(
        self, history, metric_def, horizons_ms, *, topic_names: dict | None = None
    ) -> list[Scenario]:
        trends = self.fit(history, metric_def, topic_names)
        return [
            self.scenario_at(trends, int(h), history.window_ms)
            for h in horizons_ms
        ]
