"""Rightsizing: the minimum broker count satisfying every hard goal.

Cruise Control's `ProvisionStatus` (UNDER_PROVISIONED / RIGHT_SIZED /
OVER_PROVISIONED) answers "is this cluster the right size" for the
current topology only.  Here the question is asked as a what-if sweep:
each candidate broker count becomes a Scenario (drop the highest-id
brokers, or add median-profile brokers), every candidate is screened in
ONE batched goal-score evaluation, and a monotone binary search runs the
full anneal on the shortlist to confirm that a rebalance at that size
actually satisfies every hard goal.  Candidates share one planned shape,
so the anneal reuses a single compiled engine across the whole search.

Monotonicity is the search's load-bearing assumption: if n brokers can
satisfy the hard goals, n+1 can (the optimizer may simply not use the
extra broker).  That is what turns a sweep into O(log n) anneals.
"""

from __future__ import annotations

import dataclasses
import enum
import time

import numpy as np

from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.planner.scenario import BrokerAdd, Scenario


class ProvisionStatus(enum.Enum):
    """Reference: analyzer ProvisionStatus semantics."""

    RIGHT_SIZED = "RIGHT_SIZED"
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclasses.dataclass(frozen=True)
class CandidateResult:
    brokers: int
    feasible: bool  # hard goals satisfiable (post-anneal) at this count
    violated_hard_goals: tuple
    objective_after: float | None
    num_moves: int | None
    degraded: bool = False  # verdict came from the CPU fallback path

    def to_json(self) -> dict:
        return {
            "brokers": self.brokers,
            "feasible": self.feasible,
            "violatedHardGoals": list(self.violated_hard_goals),
            "objectiveAfter": self.objective_after,
            "numMoves": self.num_moves,
        }


class Rightsizer:
    """Monotone broker-count search over the batched scenario evaluator."""

    def __init__(
        self,
        evaluator,
        *,
        min_brokers: int = 1,
        max_broker_factor: float = 2.0,
        bucket=None,
        sensors=None,
    ):
        """evaluator: analyzer.scenario_eval.ScenarioEvaluator with an
        optimizer attached (the anneal is what makes a verdict honest —
        pre-move violations only prove a rebalance is NEEDED, not that
        one is impossible).  bucket: the CONFIGURED ShapeBucketPolicy —
        candidate shapes that outgrow the base padding must land in the
        same buckets the engine cache serves, or every grown candidate
        pays a fresh compile and the O(log n) search degrades."""
        self.evaluator = evaluator
        self.min_brokers = min_brokers
        self.max_broker_factor = max_broker_factor
        self.bucket = bucket
        self.sensors = sensors

    # ------------------------------------------------------------------

    def _scenario_for_count(
        self, state: ClusterState, n: int, current: int, base: Scenario | None
    ) -> Scenario:
        """The what-if that makes the cluster n brokers big.  Shrinks drop
        the highest-id ALIVE brokers (the conventional decommission order);
        grows add median-profile brokers round-robin over racks."""
        if n < current:
            alive = np.nonzero(
                np.asarray(state.broker_valid) & np.asarray(state.broker_alive)
            )[0]
            sc = Scenario(
                name=f"brokers={n}",
                remove_brokers=tuple(int(b) for b in alive[n:]),
            )
        elif n > current:
            sc = Scenario(
                name=f"brokers={n}", add_brokers=(BrokerAdd(count=n - current),)
            )
        else:
            sc = Scenario(name=f"brokers={n}")
        return sc if base is None else base.compose(sc, name=sc.name)

    def _floor(self, state: ClusterState, current: int) -> int:
        """No candidate below max replication factor (a partition cannot
        place two replicas on one broker — such counts are structurally
        infeasible, not merely unbalanced) or the configured minimum."""
        part = np.asarray(state.replica_partition)[np.asarray(state.replica_valid)]
        max_rf = int(np.bincount(part).max()) if part.size else 1
        return max(self.min_brokers, max_rf, 1)

    def _feasible(self, state, catalog, scenario) -> CandidateResult:
        """Post-anneal hard-goal verdict for one candidate.  No memo on
        purpose: the binary search never revisits a count, and a cache
        that can never hit only suggests reuse that does not exist."""
        outcome = self.evaluator.evaluate(
            state, [scenario], catalog, optimize=True, bucket=self.bucket
        )[0]
        fix = outcome.fix or {}
        hard_names = [
            g.name for g in self.evaluator.chain.goals if g.hard
        ]
        violated_hard = tuple(
            v for v in fix.get("violatedGoalsAfter", []) if v in hard_names
        )
        return CandidateResult(
            brokers=outcome.brokers_alive,
            feasible=bool(fix.get("hardGoalsSatisfiedAfter", False)),
            violated_hard_goals=violated_hard,
            objective_after=fix.get("objectiveAfter"),
            num_moves=fix.get("numReplicaMovements"),
            degraded=outcome.degraded or bool(fix.get("degraded")),
        )

    # ------------------------------------------------------------------

    def rightsize(
        self,
        state: ClusterState,
        catalog=None,
        *,
        load_scenario: Scenario | None = None,
        max_anneals: int = 16,
        screen_limit: int = 16,
    ) -> dict:
        """Minimum brokers satisfying all hard goals at current (and, via
        `load_scenario`, forecast) load.

        Phase 1 screens a bounded grid of candidate counts in ONE batched
        goal-score program (the pre-move violation curve, reported for
        operators); phase 2 binary-searches the integer range for the
        feasibility boundary with full anneals (engine compiled once,
        rebound per candidate — O(log n) anneals even at 2600 brokers).
        `max_anneals` bounds the search wall clock; an unfinished search
        reports UNDECIDED rather than guessing.
        """
        t0 = time.monotonic()
        alive = np.asarray(state.broker_valid) & np.asarray(state.broker_alive)
        current = int(alive.sum())
        lo = self._floor(state, current)
        hi = max(current, int(np.ceil(current * self.max_broker_factor)))
        # screening grid: every count when small, else evenly spread with
        # lo/current/hi always present
        span = hi - lo + 1
        if span <= screen_limit:
            grid = list(range(lo, hi + 1))
        else:
            grid = sorted(
                {lo, current, hi}
                | {int(x) for x in np.linspace(lo, hi, screen_limit - 2)}
            )
        scenarios = [
            self._scenario_for_count(state, n, current, load_scenario)
            for n in grid
        ]
        # phase 1: one batched evaluation of every screened candidate's
        # PRE-move violations — the curve an operator reads to see how
        # stressed each size starts out
        pre = self.evaluator.evaluate(
            state, scenarios, catalog, optimize=False, bucket=self.bucket
        )
        degraded = any(o.degraded for o in pre)
        pre_by_count = {
            n: {"objective": o.objective, "violatedGoals": o.violated_goals}
            for n, o in zip(grid, pre)
        }

        # phase 2: monotone binary search on post-anneal feasibility over
        # the FULL integer range (not just the grid)
        anneals = 0
        verdicts: dict[int, CandidateResult] = {}

        def check(n: int) -> bool:
            nonlocal anneals, degraded
            sc = self._scenario_for_count(state, n, current, load_scenario)
            res = self._feasible(state, catalog, sc)
            verdicts[n] = res
            degraded = degraded or res.degraded
            anneals += 1
            return res.feasible

        min_feasible: int | None = None
        upper_bound: int | None = None
        undecided = False
        # check(hi) always runs (max_anneals >= 1).  An INFEASIBLE ceiling
        # is a completed proof, not an exhausted search: by monotonicity no
        # smaller count can work either -> decided UNDER_PROVISIONED.
        if check(hi):
            lo_n, hi_n = lo, hi  # hi_n always feasible
            while lo_n < hi_n and anneals < max_anneals:
                mid = (lo_n + hi_n) // 2
                if check(mid):
                    hi_n = mid
                else:
                    lo_n = mid + 1
            if lo_n < hi_n:
                # budget ran out mid-bracket: hi_n only bounds the true
                # minimum from ABOVE — reporting it as "the minimum" could
                # flip an OVER_PROVISIONED cluster to UNDER.  Say so.
                undecided = True
                upper_bound = hi_n
            else:
                min_feasible = hi_n

        if undecided:
            status = ProvisionStatus.UNDECIDED
        elif min_feasible is None:
            # even the largest candidate cannot satisfy the hard goals
            status = ProvisionStatus.UNDER_PROVISIONED
        elif min_feasible > current:
            status = ProvisionStatus.UNDER_PROVISIONED
        elif min_feasible < current:
            status = ProvisionStatus.OVER_PROVISIONED
        else:
            status = ProvisionStatus.RIGHT_SIZED

        if self.sensors is not None:
            self.sensors.timer("planner.rightsize-timer").update(
                time.monotonic() - t0
            )
            self.sensors.counter("planner.rightsize-anneals").inc(anneals)
        return {
            "provisionStatus": status.value,
            "currentBrokers": current,
            "minBrokers": min_feasible,
            # best upper bound the unfinished search established (UNDECIDED
            # only): "no more than this many brokers suffice"
            "minBrokersUpperBound": upper_bound,
            "searchedRange": [lo, hi],
            "annealsRun": anneals,
            "undecided": undecided,
            "degraded": degraded,
            "preMoveViolations": pre_by_count,
            "candidates": [
                verdicts[n].to_json() for n in sorted(verdicts)
            ],
            "loadScenario": load_scenario.to_json() if load_scenario else None,
            "wallSeconds": round(time.monotonic() - t0, 3),
        }
