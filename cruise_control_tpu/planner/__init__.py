"""Scenario planner — batched what-if simulation, forecasting, rightsizing.

A read-only subsystem beside monitor/analyzer/executor/detector: it
answers "what happens if" (lose a rack, add brokers, traffic doubles)
by editing the flattened cluster model (models/whatif.py), batch-scoring
the hypotheticals on the same goal engine proposals use
(analyzer/scenario_eval.py), extrapolating load from the monitor's
windowed history (planner/forecast.py), and searching broker counts for
the minimum footprint that satisfies every hard goal
(planner/rightsizer.py).  Surfaced via POST /simulate and GET /rightsize.
"""

from cruise_control_tpu.planner.forecast import LoadForecaster, TopicTrend
from cruise_control_tpu.planner.rightsizer import ProvisionStatus, Rightsizer
from cruise_control_tpu.planner.scenario import (
    BrokerAdd,
    Scenario,
    apply_scenario,
    plan_shape,
)

__all__ = [
    "BrokerAdd",
    "LoadForecaster",
    "ProvisionStatus",
    "Rightsizer",
    "Scenario",
    "TopicTrend",
    "apply_scenario",
    "plan_shape",
]
