"""Declarative, JSON-round-trippable what-if scenarios.

A `Scenario` names a hypothetical future of one base cluster: brokers
added (with capacity profiles), brokers or whole racks lost, brokers
demoted, per-topic load scaled, an absolute load delta applied.
`apply_scenario` compiles it into an edited ClusterState via the
models/whatif.py primitives; `plan_shape` sizes ONE shared (bucketed)
ClusterShape for a whole scenario batch so every mutated state reuses a
single compiled engine (ShapeBucketPolicy padding rows become the
scenario's added brokers).

Reference analog: Cruise Control's provision/underProvisioned analysis
(`ProvisionStatus`, `GoalOptimizer`) answers one fixed hypothetical
("current load, current brokers"); the related work on online rack
placement (arxiv 2501.12725) and autoscaling via multi-objective
optimization (arxiv 2402.06085) treats capacity planning as the same
optimization problem over hypothetical topologies — which is exactly
what a vmap'd goal engine evaluates in batch.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.models.state import (
    ClusterShape,
    ClusterState,
    DEFAULT_BUCKET_POLICY,
    ShapeBucketPolicy,
)
from cruise_control_tpu.models.whatif import HostState


@dataclasses.dataclass(frozen=True)
class BrokerAdd:
    """One group of identical brokers to add.

    rack: rack NAME (resolved via the catalog) or int rack id; None
    spreads the group round-robin over existing racks (the placement a
    capacity plan usually wants).  capacity: per-resource [4] profile;
    None clones the live brokers' median profile.
    """

    count: int = 1
    rack: str | int | None = None
    capacity: tuple | None = None  # [CPU, NW_IN, NW_OUT, DISK]
    disk_capacities: tuple | None = None  # JBOD logdir split

    def to_json(self) -> dict:
        out: dict = {"count": self.count}
        if self.rack is not None:
            out["rack"] = self.rack
        if self.capacity is not None:
            out["capacity"] = list(self.capacity)
        if self.disk_capacities is not None:
            out["diskCapacities"] = list(self.disk_capacities)
        return out

    @staticmethod
    def from_json(d: dict) -> "BrokerAdd":
        return BrokerAdd(
            count=int(d.get("count", 1)),
            rack=d.get("rack"),
            capacity=tuple(d["capacity"]) if d.get("capacity") else None,
            disk_capacities=(
                tuple(d["diskCapacities"]) if d.get("diskCapacities") else None
            ),
        )


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One hypothetical future.  Every field defaults to "no change", so
    `Scenario()` is the identity — applying it must be observably
    invisible (pinned by the parity tests)."""

    name: str = "scenario"
    add_brokers: tuple = ()  # tuple[BrokerAdd, ...]
    remove_brokers: tuple = ()  # broker ids to lose (dead, not drained)
    demote_brokers: tuple = ()  # broker ids to move leadership off
    kill_racks: tuple = ()  # rack names (or int ids) to lose entirely
    #: topic name (or int id) -> load multiplier (scalar or per-resource [4])
    topic_load_factors: dict = dataclasses.field(default_factory=dict)
    load_factor: float = 1.0  # global load multiplier
    load_delta: tuple | None = None  # absolute per-resource [4] delta

    @property
    def is_identity(self) -> bool:
        return (
            not self.add_brokers
            and not self.remove_brokers
            and not self.demote_brokers
            and not self.kill_racks
            and not self.topic_load_factors
            and self.load_factor == 1.0
            and self.load_delta is None
        )

    @property
    def brokers_added(self) -> int:
        return sum(a.count for a in self.add_brokers)

    def to_json(self) -> dict:
        out: dict = {"name": self.name}
        if self.add_brokers:
            out["addBrokers"] = [a.to_json() for a in self.add_brokers]
        if self.remove_brokers:
            out["removeBrokers"] = list(self.remove_brokers)
        if self.demote_brokers:
            out["demoteBrokers"] = list(self.demote_brokers)
        if self.kill_racks:
            out["killRacks"] = list(self.kill_racks)
        if self.topic_load_factors:
            out["topicLoadFactors"] = {
                str(k): (list(v) if isinstance(v, (list, tuple, np.ndarray)) else v)
                for k, v in self.topic_load_factors.items()
            }
        if self.load_factor != 1.0:
            out["loadFactor"] = self.load_factor
        if self.load_delta is not None:
            out["loadDelta"] = list(self.load_delta)
        return out

    @staticmethod
    def from_json(d: dict) -> "Scenario":
        """Parse one scenario dict; unknown keys fail loudly (a typo'd
        `removeBrokres` silently evaluating the identity would report a
        healthy cluster for a broken plan)."""
        known = {
            "name", "addBrokers", "removeBrokers", "demoteBrokers",
            "killRacks", "topicLoadFactors", "loadFactor", "loadDelta",
        }
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown scenario fields {sorted(unknown)} (accepted: {sorted(known)})"
            )
        factors = {}
        for k, v in (d.get("topicLoadFactors") or {}).items():
            factors[k] = tuple(v) if isinstance(v, (list, tuple)) else float(v)
        return Scenario(
            name=str(d.get("name", "scenario")),
            add_brokers=tuple(
                BrokerAdd.from_json(a) for a in d.get("addBrokers") or ()
            ),
            remove_brokers=tuple(int(b) for b in d.get("removeBrokers") or ()),
            demote_brokers=tuple(int(b) for b in d.get("demoteBrokers") or ()),
            kill_racks=tuple(d.get("killRacks") or ()),
            topic_load_factors=factors,
            load_factor=float(d.get("loadFactor", 1.0)),
            load_delta=(
                tuple(float(x) for x in d["loadDelta"])
                if d.get("loadDelta") is not None
                else None
            ),
        )

    def compose(self, other: "Scenario", *, name: str | None = None) -> "Scenario":
        """This scenario with `other` applied on top (the rightsizer lays
        its broker-count change over a forecast load scenario)."""
        factors = dict(self.topic_load_factors)
        for k, v in other.topic_load_factors.items():
            if k in factors:
                a = np.broadcast_to(np.asarray(factors[k], np.float64), (4,))
                b = np.broadcast_to(np.asarray(v, np.float64), (4,))
                factors[k] = tuple((a * b).tolist())
            else:
                factors[k] = v
        delta = self.load_delta
        if other.load_delta is not None:
            delta = tuple(
                (np.asarray(delta or (0.0,) * 4) + np.asarray(other.load_delta)).tolist()
            )
        return Scenario(
            name=name or f"{self.name}+{other.name}",
            add_brokers=self.add_brokers + other.add_brokers,
            remove_brokers=self.remove_brokers + other.remove_brokers,
            demote_brokers=self.demote_brokers + other.demote_brokers,
            kill_racks=self.kill_racks + other.kill_racks,
            topic_load_factors=factors,
            load_factor=self.load_factor * other.load_factor,
            load_delta=delta,
        )


# ----------------------------------------------------------------------
# name resolution against the catalog
# ----------------------------------------------------------------------


def _rack_id(rack, catalog, n_real_racks: int) -> int:
    if isinstance(rack, (int, np.integer)):
        return int(rack)
    racks = tuple(getattr(catalog, "racks", ()) or ())
    if rack in racks:
        return racks.index(rack)
    raise ValueError(f"unknown rack {rack!r} (known: {list(racks) or range(n_real_racks)})")


def _topic_id(topic, catalog) -> int:
    if isinstance(topic, (int, np.integer)):
        return int(topic)
    if catalog is not None:
        # the catalog NAME wins: Kafka allows digit-only topic names, so a
        # topic literally called "123" must resolve by name, not as id 123
        try:
            return catalog.topic_id(topic)
        except KeyError:
            pass
    if isinstance(topic, str) and topic.isdigit():
        return int(topic)  # JSON object keys are strings; int ids survive
    raise ValueError(
        f"unknown topic {topic!r}"
        + ("" if catalog is not None else " (no catalog; use the int topic id)")
    )


# ----------------------------------------------------------------------
# shape planning + application
# ----------------------------------------------------------------------


def plan_shape(
    state: ClusterState,
    scenarios,
    *,
    bucket: ShapeBucketPolicy | None = None,
) -> ClusterShape:
    """ONE shared shape accommodating every scenario of a batch.

    Broker adds consume padding rows; only when a batch adds more brokers
    (or hosts) than the current padding holds does an axis grow — rounded
    by the bucket policy so the grown shape is itself engine-cache
    friendly.  Replica/partition/topic/rack axes never grow here (adds
    create no replicas; new brokers join existing racks)."""
    bucket = bucket if bucket is not None else DEFAULT_BUCKET_POLICY
    s = state.shape
    bv = np.asarray(state.broker_valid)
    n_real_b = int(bv.sum())
    bh = np.asarray(state.broker_host)
    n_real_h = int(bh[bv].max()) + 1 if n_real_b else 0
    max_add = max((sum(a.count for a in sc.add_brokers) for sc in scenarios), default=0)

    def axis(current: int, needed: int) -> int:
        # keep the CURRENT axis whenever its padding already fits — the
        # identity scenario (and any batch inside the padding) must not
        # change shape, so evaluation rides the engine already compiled
        # for the live model
        return current if needed <= current else bucket.bucket(needed)

    return ClusterShape(
        num_replicas=s.num_replicas,
        num_brokers=axis(s.num_brokers, n_real_b + max_add),
        num_partitions=s.num_partitions,
        num_topics=s.num_topics,
        num_racks=s.num_racks,
        num_hosts=axis(s.num_hosts, n_real_h + max_add),
        max_disks_per_broker=s.max_disks_per_broker,
    )


def apply_scenario(
    state: ClusterState,
    scenario: Scenario,
    catalog=None,
    *,
    shape: ClusterShape | None = None,
    bucket: ShapeBucketPolicy | None = None,
) -> ClusterState:
    """Edit the flattened model arrays per `scenario` -> new ClusterState.

    `shape`: the batch-shared target shape from plan_shape (padded to
    before editing); None plans for this scenario alone.  The result is
    array-for-array identical to the input for the identity scenario
    (pinned by tests/test_planner.py), so scenario evaluation inherits
    every masking/parity guarantee of the bucketing layer.
    """
    from cruise_control_tpu.models.builder import pad_state

    if shape is None:
        shape = plan_shape(state, [scenario], bucket=bucket)
    if shape != state.shape:
        state = pad_state(state, shape)
    h = HostState.of(state)
    n_real_racks = h.real_rack_count()

    # --- topology: losses first (adds must not land on a dying rack id
    #     by surprise — the scenario author sees losses applied to the
    #     base cluster, adds placed on what survives) ---
    if scenario.kill_racks:
        h.kill_racks(
            _rack_id(r, catalog, n_real_racks) for r in scenario.kill_racks
        )
    if scenario.remove_brokers:
        h.kill_brokers(scenario.remove_brokers)
    if scenario.demote_brokers:
        h.demote_brokers(scenario.demote_brokers)
    if scenario.add_brokers:
        alive_racks = np.unique(h["broker_rack"][h.alive_mask()])
        if alive_racks.size == 0:
            alive_racks = np.unique(h["broker_rack"][h["broker_valid"]])
        rr = 0
        for grp in scenario.add_brokers:
            for _ in range(grp.count):
                if grp.rack is None:
                    rack_id = int(alive_racks[rr % alive_racks.size])
                    rr += 1
                else:
                    rack_id = _rack_id(grp.rack, catalog, n_real_racks)
                h.add_broker(
                    rack_id=rack_id,
                    capacity=(
                        np.asarray(grp.capacity, np.float32)
                        if grp.capacity is not None
                        else None
                    ),
                    disk_capacities=(
                        np.asarray(grp.disk_capacities, np.float32)
                        if grp.disk_capacities is not None
                        else None
                    ),
                )

    # --- load ---
    for topic, factors in scenario.topic_load_factors.items():
        h.scale_topic_load(_topic_id(topic, catalog), factors)
    if scenario.load_factor != 1.0:
        h.scale_all_load(scenario.load_factor)
    if scenario.load_delta is not None:
        h.add_load_delta(scenario.load_delta)

    return h.to_state(state)
