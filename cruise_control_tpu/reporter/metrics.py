"""Raw metric records + wire serialization.

Reference: cruise-control-metrics-reporter metric/RawMetricType.java:27-80
(~56 types with BROKER/TOPIC/PARTITION scope and versioned serialization),
metric/CruiseControlMetric.java (classId + version wire format),
metric/MetricSerde.java (Kafka serde).

The wire format here is a compact little-endian struct mirroring the
reference's layout idea (class id byte, version byte, then fields) so a
heterogeneous stream of broker/topic/partition metrics can share one
topic/transport.
"""

from __future__ import annotations

import dataclasses
import enum
import struct


class MetricClassId(enum.IntEnum):
    """Reference CruiseControlMetric.MetricClassId."""

    BROKER_METRIC = 0
    TOPIC_METRIC = 1
    PARTITION_METRIC = 2


class MetricType(enum.IntEnum):
    """Raw metric taxonomy (reference metric/RawMetricType.java:27-80).

    Scope encoded by range: 0-39 broker, 40-49 topic, 50+ partition.
    """

    # broker scope
    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    ALL_TOPIC_REPLICATION_BYTES_IN = 2
    ALL_TOPIC_REPLICATION_BYTES_OUT = 3
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 4
    ALL_TOPIC_FETCH_REQUEST_RATE = 5
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 6
    BROKER_CPU_UTIL = 7
    BROKER_PRODUCE_REQUEST_RATE = 8
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 9
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 10
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = 11
    BROKER_REQUEST_QUEUE_SIZE = 12
    BROKER_RESPONSE_QUEUE_SIZE = 13
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 14
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 15
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 16
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 17
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 18
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 19
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 20
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 21
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 22
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 23
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 24
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 25
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 26
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 27
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 28
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 29
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 30
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 31
    BROKER_LOG_FLUSH_RATE = 32
    BROKER_LOG_FLUSH_TIME_MS_MAX = 33
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 34
    # topic scope
    TOPIC_BYTES_IN = 40
    TOPIC_BYTES_OUT = 41
    TOPIC_REPLICATION_BYTES_IN = 42
    TOPIC_REPLICATION_BYTES_OUT = 43
    TOPIC_PRODUCE_REQUEST_RATE = 44
    TOPIC_FETCH_REQUEST_RATE = 45
    TOPIC_MESSAGES_IN_PER_SEC = 46
    # partition scope
    PARTITION_SIZE = 50

    @property
    def is_broker_scope(self) -> bool:
        return self < 40

    @property
    def is_topic_scope(self) -> bool:
        return 40 <= self < 50

    @property
    def is_partition_scope(self) -> bool:
        return self >= 50


_VERSION = 0


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    metric_type: MetricType
    time_ms: int
    broker_id: int
    value: float

    class_id = MetricClassId.BROKER_METRIC


@dataclasses.dataclass(frozen=True)
class BrokerMetric(CruiseControlMetric):
    class_id = MetricClassId.BROKER_METRIC


@dataclasses.dataclass(frozen=True)
class TopicMetric(CruiseControlMetric):
    topic: str = ""

    class_id = MetricClassId.TOPIC_METRIC


@dataclasses.dataclass(frozen=True)
class PartitionMetric(CruiseControlMetric):
    topic: str = ""
    partition: int = -1

    class_id = MetricClassId.PARTITION_METRIC


class MetricSerde:
    """Binary serde (reference metric/MetricSerde.java).

    Layout: class_id u8 | version u8 | metric_type u16 | time_ms i64 |
    broker_id i32 | value f64 [| topic_len u16 | topic utf8 [| partition i32]]
    """

    _HEAD = struct.Struct("<BBHqid")

    @classmethod
    def serialize(cls, m: CruiseControlMetric) -> bytes:
        head = cls._HEAD.pack(
            int(m.class_id), _VERSION, int(m.metric_type), m.time_ms, m.broker_id, m.value
        )
        if isinstance(m, PartitionMetric):
            t = m.topic.encode()
            return head + struct.pack("<H", len(t)) + t + struct.pack("<i", m.partition)
        if isinstance(m, TopicMetric):
            t = m.topic.encode()
            return head + struct.pack("<H", len(t)) + t
        return head

    @classmethod
    def deserialize(cls, data: bytes) -> CruiseControlMetric:
        class_id, version, mtype, time_ms, broker_id, value = cls._HEAD.unpack_from(data)
        if version > _VERSION:
            raise ValueError(f"unsupported metric version {version}")
        rest = data[cls._HEAD.size:]
        mt = MetricType(mtype)
        if class_id == MetricClassId.BROKER_METRIC:
            return BrokerMetric(mt, time_ms, broker_id, value)
        (tlen,) = struct.unpack_from("<H", rest)
        topic = rest[2: 2 + tlen].decode()
        if class_id == MetricClassId.TOPIC_METRIC:
            return TopicMetric(mt, time_ms, broker_id, value, topic=topic)
        (partition,) = struct.unpack_from("<i", rest, 2 + tlen)
        return PartitionMetric(mt, time_ms, broker_id, value, topic=topic, partition=partition)
