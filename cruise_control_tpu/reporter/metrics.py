"""Raw metric records + wire serialization.

Reference: cruise-control-metrics-reporter metric/RawMetricType.java:27-80
(~56 types with BROKER/TOPIC/PARTITION scope and versioned serialization),
metric/CruiseControlMetric.java (classId + version wire format),
metric/MetricSerde.java (Kafka serde).

The wire format here is a compact little-endian struct mirroring the
reference's layout idea (class id byte, version byte, then fields) so a
heterogeneous stream of broker/topic/partition metrics can share one
topic/transport.
"""

from __future__ import annotations

import dataclasses
import enum
import struct


class MetricClassId(enum.IntEnum):
    """Reference CruiseControlMetric.MetricClassId."""

    BROKER_METRIC = 0
    TOPIC_METRIC = 1
    PARTITION_METRIC = 2


class MetricType(enum.IntEnum):
    """Raw metric taxonomy (reference metric/RawMetricType.java:27-80).

    Scope encoded by range: 0-39 broker, 40-49 topic, 50+ partition.
    """

    # broker scope
    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    ALL_TOPIC_REPLICATION_BYTES_IN = 2
    ALL_TOPIC_REPLICATION_BYTES_OUT = 3
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 4
    ALL_TOPIC_FETCH_REQUEST_RATE = 5
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 6
    BROKER_CPU_UTIL = 7
    BROKER_PRODUCE_REQUEST_RATE = 8
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 9
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 10
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = 11
    BROKER_REQUEST_QUEUE_SIZE = 12
    BROKER_RESPONSE_QUEUE_SIZE = 13
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 14
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 15
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 16
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 17
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 18
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 19
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 20
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 21
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 22
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 23
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 24
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 25
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 26
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 27
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 28
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 29
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 30
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 31
    BROKER_LOG_FLUSH_RATE = 32
    BROKER_LOG_FLUSH_TIME_MS_MAX = 33
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 34
    # topic scope
    TOPIC_BYTES_IN = 40
    TOPIC_BYTES_OUT = 41
    TOPIC_REPLICATION_BYTES_IN = 42
    TOPIC_REPLICATION_BYTES_OUT = 43
    TOPIC_PRODUCE_REQUEST_RATE = 44
    TOPIC_FETCH_REQUEST_RATE = 45
    TOPIC_MESSAGES_IN_PER_SEC = 46
    # partition scope
    PARTITION_SIZE = 50
    # broker scope, percentile latencies (reference serde v1 additions,
    # RawMetricType.java ids 43-62 — SlowBrokerFinder inputs); 60-79 is a
    # second broker-scope range so the earlier ranges stay stable
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = 60
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = 61
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 62
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 63
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 64
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 65
    BROKER_PRODUCE_TOTAL_TIME_MS_50TH = 66
    BROKER_PRODUCE_TOTAL_TIME_MS_999TH = 67
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH = 68
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH = 69
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH = 70
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH = 71
    BROKER_PRODUCE_LOCAL_TIME_MS_50TH = 72
    BROKER_PRODUCE_LOCAL_TIME_MS_999TH = 73
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH = 74
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH = 75
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH = 76
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH = 77
    BROKER_LOG_FLUSH_TIME_MS_50TH = 78
    BROKER_LOG_FLUSH_TIME_MS_999TH = 79

    @property
    def is_broker_scope(self) -> bool:
        return self < 40 or 60 <= self < 80

    @property
    def is_topic_scope(self) -> bool:
        return 40 <= self < 50

    @property
    def is_partition_scope(self) -> bool:
        return 50 <= self < 60


_VERSION = 0


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    metric_type: MetricType
    time_ms: int
    broker_id: int
    value: float

    class_id = MetricClassId.BROKER_METRIC


@dataclasses.dataclass(frozen=True)
class BrokerMetric(CruiseControlMetric):
    class_id = MetricClassId.BROKER_METRIC


@dataclasses.dataclass(frozen=True)
class TopicMetric(CruiseControlMetric):
    topic: str = ""

    class_id = MetricClassId.TOPIC_METRIC


@dataclasses.dataclass(frozen=True)
class PartitionMetric(CruiseControlMetric):
    topic: str = ""
    partition: int = -1

    class_id = MetricClassId.PARTITION_METRIC


class MetricSerde:
    """Binary serde (reference metric/MetricSerde.java).

    Layout: class_id u8 | version u8 | metric_type u16 | time_ms i64 |
    broker_id i32 | value f64 [| topic_len u16 | topic utf8 [| partition i32]]
    """

    _HEAD = struct.Struct("<BBHqid")

    @classmethod
    def serialize(cls, m: CruiseControlMetric) -> bytes:
        head = cls._HEAD.pack(
            int(m.class_id), _VERSION, int(m.metric_type), m.time_ms, m.broker_id, m.value
        )
        if isinstance(m, PartitionMetric):
            t = m.topic.encode()
            return head + struct.pack("<H", len(t)) + t + struct.pack("<i", m.partition)
        if isinstance(m, TopicMetric):
            t = m.topic.encode()
            return head + struct.pack("<H", len(t)) + t
        return head

    @classmethod
    def deserialize(cls, data: bytes) -> CruiseControlMetric:
        class_id, version, mtype, time_ms, broker_id, value = cls._HEAD.unpack_from(data)
        if version > _VERSION:
            raise ValueError(f"unsupported metric version {version}")
        rest = data[cls._HEAD.size:]
        mt = MetricType(mtype)
        if class_id == MetricClassId.BROKER_METRIC:
            return BrokerMetric(mt, time_ms, broker_id, value)
        (tlen,) = struct.unpack_from("<H", rest)
        topic = rest[2: 2 + tlen].decode()
        if class_id == MetricClassId.TOPIC_METRIC:
            return TopicMetric(mt, time_ms, broker_id, value, topic=topic)
        (partition,) = struct.unpack_from("<i", rest, 2 + tlen)
        return PartitionMetric(mt, time_ms, broker_id, value, topic=topic, partition=partition)


# ---------------------------------------------------------------------------
# drop-in interop with the REFERENCE reporter plugin's wire format
# ---------------------------------------------------------------------------

#: our MetricType name at each reference RawMetricType id (index == id) —
#: transcribed from RawMetricType.java:27-97 (id, scope, version-since).
#: The names are identical by construction; only the id spaces differ.
_REFERENCE_TYPE_NAMES = (
    "ALL_TOPIC_BYTES_IN",                                   # 0  v1 BROKER
    "ALL_TOPIC_BYTES_OUT",                                  # 1  v1 BROKER
    "TOPIC_BYTES_IN",                                       # 2  v1 TOPIC
    "TOPIC_BYTES_OUT",                                      # 3  v1 TOPIC
    "PARTITION_SIZE",                                       # 4  v1 PARTITION
    "BROKER_CPU_UTIL",                                      # 5  v1 BROKER
    "ALL_TOPIC_REPLICATION_BYTES_IN",                       # 6
    "ALL_TOPIC_REPLICATION_BYTES_OUT",                      # 7
    "ALL_TOPIC_PRODUCE_REQUEST_RATE",                       # 8
    "ALL_TOPIC_FETCH_REQUEST_RATE",                         # 9
    "ALL_TOPIC_MESSAGES_IN_PER_SEC",                        # 10
    "TOPIC_REPLICATION_BYTES_IN",                           # 11
    "TOPIC_REPLICATION_BYTES_OUT",                          # 12
    "TOPIC_PRODUCE_REQUEST_RATE",                           # 13
    "TOPIC_FETCH_REQUEST_RATE",                             # 14
    "TOPIC_MESSAGES_IN_PER_SEC",                            # 15
    "BROKER_PRODUCE_REQUEST_RATE",                          # 16
    "BROKER_CONSUMER_FETCH_REQUEST_RATE",                   # 17
    "BROKER_FOLLOWER_FETCH_REQUEST_RATE",                   # 18
    "BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT",              # 19
    "BROKER_REQUEST_QUEUE_SIZE",                            # 20
    "BROKER_RESPONSE_QUEUE_SIZE",                           # 21
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX",             # 22
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN",            # 23
    "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",      # 24
    "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",     # 25
    "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",      # 26
    "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",     # 27
    "BROKER_PRODUCE_TOTAL_TIME_MS_MAX",                     # 28
    "BROKER_PRODUCE_TOTAL_TIME_MS_MEAN",                    # 29
    "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX",              # 30
    "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN",             # 31
    "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX",              # 32
    "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN",             # 33
    "BROKER_PRODUCE_LOCAL_TIME_MS_MAX",                     # 34
    "BROKER_PRODUCE_LOCAL_TIME_MS_MEAN",                    # 35
    "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX",              # 36
    "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN",             # 37
    "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX",              # 38
    "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN",             # 39
    "BROKER_LOG_FLUSH_RATE",                                # 40
    "BROKER_LOG_FLUSH_TIME_MS_MAX",                         # 41
    "BROKER_LOG_FLUSH_TIME_MS_MEAN",                        # 42
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH",            # 43 v5
    "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH",           # 44 v5
    "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH",     # 45
    "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH",    # 46
    "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH",     # 47
    "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH",    # 48
    "BROKER_PRODUCE_TOTAL_TIME_MS_50TH",                    # 49
    "BROKER_PRODUCE_TOTAL_TIME_MS_999TH",                   # 50
    "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH",             # 51
    "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH",            # 52
    "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH",             # 53
    "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH",            # 54
    "BROKER_PRODUCE_LOCAL_TIME_MS_50TH",                    # 55
    "BROKER_PRODUCE_LOCAL_TIME_MS_999TH",                   # 56
    "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH",             # 57
    "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH",            # 58
    "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH",             # 59
    "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH",            # 60
    "BROKER_LOG_FLUSH_TIME_MS_50TH",                        # 61
    "BROKER_LOG_FLUSH_TIME_MS_999TH",                       # 62
)

_REF_TYPE_BY_ID = {i: MetricType[n] for i, n in enumerate(_REFERENCE_TYPE_NAMES)}
_REF_ID_BY_TYPE = {t: i for i, t in _REF_TYPE_BY_ID.items()}

_REFERENCE_METRIC_VERSION = 0


class ReferenceMetricSerde:
    """The REFERENCE reporter plugin's exact wire format (big-endian):

      class_id u8 | version u8 | raw_type u8 | time i64 | broker_id i32
        [| topic_len i32 | topic utf8 [| partition i32]] | value f64

    per metric/MetricSerde.java (class-id header byte) +
    BrokerMetric.java:30-41 / TopicMetric.java:37-52 /
    PartitionMetric.java:44-60 (field layouts; value LAST, unlike our
    native serde).  With this serde the service ingests records produced
    by the reference's in-broker plugin unchanged — the drop-in path for
    broker-internal metrics (request-handler idle ratio, queue sizes, the
    SlowBrokerFinder's percentile latencies) that no process-external
    sidecar can observe.

    deserialize returns None for an unknown class id, exactly like the
    reference's fromBytes (new metric class on old code -> skip).
    """

    @staticmethod
    def serialize(m: CruiseControlMetric) -> bytes:
        ref_id = _REF_ID_BY_TYPE.get(m.metric_type)
        if ref_id is None:
            raise ValueError(
                f"{m.metric_type.name} has no reference RawMetricType id"
            )
        head = struct.pack(
            ">BBBqi", int(m.class_id), _REFERENCE_METRIC_VERSION, ref_id,
            m.time_ms, m.broker_id,
        )
        if isinstance(m, PartitionMetric):
            t = m.topic.encode()
            return head + struct.pack(">i", len(t)) + t + struct.pack(
                ">id", m.partition, m.value
            )
        if isinstance(m, TopicMetric):
            t = m.topic.encode()
            return head + struct.pack(">i", len(t)) + t + struct.pack(">d", m.value)
        return head + struct.pack(">d", m.value)

    @staticmethod
    def deserialize(data: bytes) -> CruiseControlMetric | None:
        class_id = data[0]
        if class_id > max(MetricClassId):
            return None  # newer metric class than we know: skip (reference behavior)
        version, ref_id, time_ms, broker_id = struct.unpack_from(">BBqi", data, 1)
        if version > _REFERENCE_METRIC_VERSION:
            # a bumped record version may have changed the field layout —
            # skip the record rather than decode garbage; raising would
            # discard the entire already-drained poll batch
            return None
        mt = _REF_TYPE_BY_ID.get(ref_id)
        if mt is None:
            # a newer reporter plugin emitting a type we don't know yet —
            # skip the record (ids 43-62 were added exactly this way);
            # raising here would discard the whole drained batch
            return None
        off = 1 + struct.calcsize(">BBqi")
        if class_id == MetricClassId.BROKER_METRIC:
            (value,) = struct.unpack_from(">d", data, off)
            return BrokerMetric(mt, time_ms, broker_id, value)
        (tlen,) = struct.unpack_from(">i", data, off)
        off += 4
        topic = data[off: off + tlen].decode()
        off += tlen
        if class_id == MetricClassId.TOPIC_METRIC:
            (value,) = struct.unpack_from(">d", data, off)
            return TopicMetric(mt, time_ms, broker_id, value, topic=topic)
        partition, value = struct.unpack_from(">id", data, off)
        return PartitionMetric(
            mt, time_ms, broker_id, value, topic=topic, partition=partition
        )
