"""Metrics reporter — broker-side metric emission (plugin analog).

Reference: cruise-control-metrics-reporter/ (CruiseControlMetricsReporter
runs INSIDE each Kafka broker, samples Yammer/Kafka metrics on an interval
and produces serialized records to the __CruiseControlMetrics topic).
"""

from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    CruiseControlMetric,
    MetricSerde,
    MetricType,
    PartitionMetric,
    TopicMetric,
)
from cruise_control_tpu.reporter.reporter import (
    MetricsRegistrySnapshotter,
    MetricsReporter,
    MetricTransport,
    InMemoryTransport,
)
