"""Broker-side metrics reporter loop.

Reference: CruiseControlMetricsReporter.java (implements Kafka
MetricsReporter + Runnable: samples the broker's Yammer/Kafka metric
registries every reportingIntervalMs and produces to the
__CruiseControlMetrics topic, auto-creating it), metric/YammerMetricProcessor.java
(+ MetricsUtils.java filter logic).

Transport is an SPI: a real deployment produces to Kafka; in-process runs
use InMemoryTransport, which the CruiseControlMetricsReporterSampler
equivalent drains on the monitor side (reference
monitor/sampling/CruiseControlMetricsReporterSampler.java:41).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Protocol

from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    CruiseControlMetric,
    MetricSerde,
    MetricType,
    PartitionMetric,
    TopicMetric,
)


class MetricTransport(Protocol):
    """Where serialized metric records go (Kafka producer in production)."""

    def send(self, payload: bytes) -> None:
        ...

    def flush(self) -> None:
        ...


class InMemoryTransport:
    """Bounded in-process topic standing in for __CruiseControlMetrics.

    `serde` picks the record wire format: the native MetricSerde (default)
    or ReferenceMetricSerde to carry records in the REFERENCE reporter
    plugin's exact byte layout (drop-in interop path).
    """

    def __init__(self, max_records: int = 1_000_000, *, serde=MetricSerde):
        self._records: list[bytes] = []
        self._lock = threading.Lock()
        self._max = max_records
        self.serde = serde
        #: the native columnar decoder only parses the native layout; the
        #: sampler falls back to the object path for other serdes
        self.framed_native = serde is MetricSerde

    def send(self, payload: bytes) -> None:
        with self._lock:
            self._records.append(payload)
            if len(self._records) > self._max:
                del self._records[: len(self._records) - self._max]

    def flush(self) -> None:
        pass

    def poll(self, max_records: int | None = None) -> list[CruiseControlMetric]:
        """Consumer side (the sampler drains this).  Records the serde does
        not recognize (None — e.g. a newer metric class id) are skipped,
        matching the reference sampler's behavior."""
        with self._lock:
            n = len(self._records) if max_records is None else min(max_records, len(self._records))
            out, self._records = self._records[:n], self._records[n:]
        decoded = (self.serde.deserialize(r) for r in out)
        return [m for m in decoded if m is not None]

    def poll_framed(self, max_records: int | None = None) -> bytes:
        """Drain as one u32-length-framed batch for the native columnar
        decoder (cruise_control_tpu/native) — no per-record objects."""
        from cruise_control_tpu.native import frame_records

        with self._lock:
            n = len(self._records) if max_records is None else min(max_records, len(self._records))
            out, self._records = self._records[:n], self._records[n:]
        return frame_records(out)


class MetricsRegistrySnapshotter:
    """Adapter from a metrics source to raw metric records — the
    YammerMetricProcessor role.  The source is a callable returning
    {"broker": {MetricType: value}, "topics": {t: {...}}, "partitions":
    {(t, p): size}} for one broker."""

    def __init__(self, broker_id: int, source: Callable[[], dict]):
        self.broker_id = broker_id
        self.source = source

    def snapshot(self, now_ms: int) -> list[CruiseControlMetric]:
        data = self.source()
        out: list[CruiseControlMetric] = []
        for mt, v in data.get("broker", {}).items():
            out.append(BrokerMetric(MetricType(mt), now_ms, self.broker_id, float(v)))
        for topic, metrics in data.get("topics", {}).items():
            for mt, v in metrics.items():
                out.append(
                    TopicMetric(MetricType(mt), now_ms, self.broker_id, float(v), topic=topic)
                )
        for (topic, part), size in data.get("partitions", {}).items():
            out.append(
                PartitionMetric(
                    MetricType.PARTITION_SIZE, now_ms, self.broker_id, float(size),
                    topic=topic, partition=int(part),
                )
            )
        return out


class MetricsReporter:
    """The reporter loop (reference CruiseControlMetricsReporter.run)."""

    def __init__(
        self,
        snapshotter: MetricsRegistrySnapshotter,
        transport: MetricTransport,
        *,
        reporting_interval_ms: int = 60_000,
        serde=MetricSerde,
    ):
        """serde: MetricSerde (native) or ReferenceMetricSerde — the latter
        produces records a REFERENCE Cruise Control service consumes
        unchanged (interop in both directions)."""
        self.snapshotter = snapshotter
        self.transport = transport
        self.reporting_interval_ms = reporting_interval_ms
        self.serde = serde
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.reported = 0

    def report_once(self, now_ms: int | None = None) -> int:
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        metrics = self.snapshotter.snapshot(now_ms)
        for m in metrics:
            self.transport.send(self.serde.serialize(m))
        self.transport.flush()
        self.reported += len(metrics)
        return len(metrics)

    def start(self):
        def loop():
            while not self._stop.wait(self.reporting_interval_ms / 1000.0):
                try:
                    self.report_once()
                except Exception:  # noqa: BLE001 — reporter must not kill the broker
                    pass

        self._thread = threading.Thread(target=loop, daemon=True, name="metrics-reporter")
        self._thread.start()

    def close(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
