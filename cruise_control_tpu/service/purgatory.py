"""Two-step verification purgatory for POST requests.

Reference: servlet/purgatory/Purgatory.java:43,117 (maybeAddToPurgatory),
RequestInfo.java / ReviewStatus (PENDING_REVIEW -> APPROVED -> SUBMITTED,
or DISCARDED), surfaced via the REVIEW + REVIEW_BOARD endpoints.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time


class PurgatoryFullError(ValueError):
    """Parked-request cap reached (two.step.purgatory.max.requests) — a
    client error (429/400 class), not a server fault."""


class ReviewStatus(enum.Enum):
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


_VALID = {
    ReviewStatus.PENDING_REVIEW: {ReviewStatus.APPROVED, ReviewStatus.DISCARDED},
    ReviewStatus.APPROVED: {ReviewStatus.SUBMITTED, ReviewStatus.DISCARDED},
    ReviewStatus.SUBMITTED: set(),
    ReviewStatus.DISCARDED: set(),
}


@dataclasses.dataclass
class RequestInfo:
    review_id: int
    endpoint: str
    params: dict
    submitter: str
    status: ReviewStatus = ReviewStatus.PENDING_REVIEW
    reason: str = ""
    #: wall clock, for DISPLAY only (SubmissionTimeMs in the board JSON)
    submitted_ms: int = dataclasses.field(default_factory=lambda: int(time.time() * 1000))
    #: monotonic stamp driving retention — a backwards NTP step must not
    #: immortalize a parked request (or expire a fresh one), same clock-skew
    #: class the facade proposal cache fixed
    submitted_mono: float = dataclasses.field(default_factory=time.monotonic)

    def to_json(self) -> dict:
        return {
            "Id": self.review_id,
            "EndPoint": self.endpoint,
            "Status": self.status.value,
            "SubmitterAddress": self.submitter,
            "Reason": self.reason,
            "SubmissionTimeMs": self.submitted_ms,
        }


class Purgatory:
    def __init__(self, retention_ms: int = 7 * 86_400_000, max_requests: int = 25):
        """max_requests: cap on parked PENDING_REVIEW requests (reference
        WebServerConfig two.step.purgatory.max.requests)."""
        self._requests: dict[int, RequestInfo] = {}
        self._ids = itertools.count()
        self._lock = threading.RLock()
        self.retention_ms = retention_ms
        self.max_requests = max_requests

    def _prune_expired(self):
        now = time.monotonic()
        for rid in [
            r.review_id
            for r in self._requests.values()
            if (now - r.submitted_mono) * 1000.0 > self.retention_ms
        ]:
            del self._requests[rid]

    def add(self, endpoint: str, params: dict, submitter: str = "") -> RequestInfo:
        with self._lock:
            # expired parked requests must not count toward the cap (nobody
            # polling review_board must not wedge the purgatory shut)
            self._prune_expired()
            pending = sum(
                1 for r in self._requests.values()
                if r.status == ReviewStatus.PENDING_REVIEW
            )
            if pending >= self.max_requests:
                raise PurgatoryFullError(
                    f"purgatory holds {pending} pending requests "
                    f"(two.step.purgatory.max.requests={self.max_requests}); "
                    "review or discard some first"
                )
            info = RequestInfo(next(self._ids), endpoint, params, submitter)
            self._requests[info.review_id] = info
            return info

    def review(self, review_id: int, approve: bool, reason: str = "") -> RequestInfo:
        with self._lock:
            info = self._requests[review_id]
            target = ReviewStatus.APPROVED if approve else ReviewStatus.DISCARDED
            if target not in _VALID[info.status]:
                raise ValueError(f"cannot {target.value} a {info.status.value} request")
            info.status = target
            info.reason = reason
            return info

    def take_approved(self, endpoint: str, review_id: int) -> RequestInfo:
        """Claim an APPROVED request for execution (-> SUBMITTED)."""
        with self._lock:
            info = self._requests[review_id]
            if info.endpoint != endpoint:
                raise ValueError(
                    f"review {review_id} is for {info.endpoint}, not {endpoint}"
                )
            if info.status != ReviewStatus.APPROVED:
                raise ValueError(f"review {review_id} is {info.status.value}, not APPROVED")
            info.status = ReviewStatus.SUBMITTED
            return info

    def board(self) -> list[dict]:
        with self._lock:
            self._prune_expired()
            return [r.to_json() for r in self._requests.values()]
