"""User task management for async operations.

Reference: servlet/UserTaskManager.java (UUID per task, `User-Task-ID`
header, session -> task map, completed-task retention + periodic scan) and
servlet/handler/async/runnable/OperationFuture.java.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

from cruise_control_tpu.service.progress import OperationProgress, Pending

USER_TASK_ID_HEADER = "User-Task-ID"


class TenantOverloadError(RuntimeError):
    """Per-cluster pending-task cap breached (fleet.tenant.max.pending.
    tasks) — surfaces as 429, never as a 500.  Raised by submit() under
    the manager lock so concurrent submissions can't race past the cap.

    `retry_after_s` (set by the server from the tenant queue's measured
    drain rate, falling back to `fleet.tenant.retry.after.s`) rides the
    429 response as a `Retry-After` header so clients back off for a
    meaningful interval instead of hammering."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class UserTask:
    task_id: str
    endpoint: str
    future: Future
    progress: OperationProgress
    created_ms: int  # wall clock, for display (StartMs in the task JSON)
    #: monotonic stamp of submission (wall-clock steps must not expire
    #: fresh tasks or immortalize old ones)
    created_mono: float = dataclasses.field(default_factory=time.monotonic)
    #: monotonic stamp of COMPLETION — retention counts from here, never
    #: from creation.  A rightsize search (or any async op) that runs
    #: longer than the retention window would otherwise expire the moment
    #: it finished, 404ing the very poll that was waiting on it.  Stamped
    #: by a future done-callback; None while the task is in execution
    #: (in-execution tasks are exempt from retention altogether).
    completed_mono: float | None = None
    request_url: str = ""
    #: requesting client identity (reference UserTaskInfo clientIdentity,
    #: filterable via USER_TASKS client_ids)
    client_id: str = ""
    #: flight-recorder trace id of the operation (empty when tracing is
    #: off) — the handle a client uses with GET /trace to replay the
    #: operation's span tree after (or while) it runs
    trace_id: str = ""
    #: fleet cluster this operation targets (empty in single-cluster
    #: deployments) — drives the per-tenant admission control and the
    #: USER_TASKS `clusters` filter
    cluster_id: str = ""

    @property
    def status(self) -> str:
        if self.future.cancelled():
            return "Cancelled"
        if self.future.done():
            return "Completed" if self.future.exception() is None else "CompletedWithError"
        return "Active"

    def to_json(self) -> dict:
        return {
            "UserTaskId": self.task_id,
            "RequestURL": self.request_url or self.endpoint,
            "ClientIdentity": self.client_id,
            "Status": self.status,
            "StartMs": self.created_ms,
            "TraceId": self.trace_id,
            "Cluster": self.cluster_id,
        }


class UserTaskManager:
    """Reference servlet/UserTaskManager.java."""

    def __init__(
        self,
        *,
        max_active_tasks: int = 25,
        max_cached_completed: int = 100,
        completed_retention_ms: int = 86_400_000,
        num_threads: int = 3,
        category_max_cached: dict[str, int] | None = None,
        category_retention_ms: dict[str, int] | None = None,
    ):
        """category_*: per-endpoint-category overrides keyed by the
        CruiseControlEndPoint type (KAFKA_MONITOR / CRUISE_CONTROL_MONITOR /
        KAFKA_ADMIN / CRUISE_CONTROL_ADMIN) — reference
        config/constants/UserTaskManagerConfig.java; unset categories fall
        back to the general cap/retention."""
        # reference AsyncKafkaCruiseControl uses 3 session threads
        self._pool = ThreadPoolExecutor(max_workers=num_threads, thread_name_prefix="user-task")
        self._tasks: dict[str, UserTask] = {}
        self._lock = threading.RLock()
        #: per-cluster recent task-completion stamps (monotonic) — the
        #: drain-rate observations Retry-After is computed from
        self._completions: dict[str, deque] = {}
        self.max_active_tasks = max_active_tasks
        self.max_cached_completed = max_cached_completed
        self.completed_retention_ms = completed_retention_ms
        self.category_max_cached = category_max_cached or {}
        self.category_retention_ms = category_retention_ms or {}

    def submit(self, endpoint: str, fn, *, request_url: str = "",
               task_id: str | None = None, client_id: str = "",
               trace_id: str = "", cluster_id: str = "",
               cluster_max_active: int = 0) -> UserTask:
        """Run fn(progress) on the session pool; returns the UserTask.

        cluster_max_active > 0 enforces the fleet's per-tenant admission
        cap (fleet.tenant.max.pending.tasks) HERE, under the same lock
        that creates the task — a check-then-submit at the caller would
        let two concurrent requests both read count == cap-1 and breach
        the cap the 429 exists to enforce."""
        with self._lock:
            active = sum(1 for t in self._tasks.values() if t.status == "Active")
            if active >= self.max_active_tasks:
                raise RuntimeError("too many active user tasks")
            if cluster_max_active and cluster_id:
                tenant_active = sum(
                    1 for t in self._tasks.values()
                    if t.cluster_id == cluster_id and t.status == "Active"
                )
                if tenant_active >= cluster_max_active:
                    raise TenantOverloadError(
                        f"cluster {cluster_id!r} already has "
                        f"{cluster_max_active} pending tasks "
                        "(fleet.tenant.max.pending.tasks); retry when "
                        "they drain"
                    )
            tid = task_id or str(uuid.uuid4())
            progress = OperationProgress()
            progress.add_step(Pending())
            future = self._pool.submit(fn, progress)
            task = UserTask(
                task_id=tid,
                endpoint=endpoint,
                future=future,
                progress=progress,
                created_ms=int(time.time() * 1000),
                request_url=request_url,
                client_id=client_id,
                trace_id=trace_id,
                cluster_id=cluster_id,
            )
            # completion stamp for retention: set the moment the operation
            # finishes, so the retention window starts when the RESULT
            # became available, not when the task was born.  The same
            # stamp feeds the per-cluster drain-rate window Retry-After
            # is computed from.
            future.add_done_callback(
                lambda f, t=task: self._on_done(t)
            )
            self._tasks[tid] = task
            self._maybe_evict()
            return task

    def _on_done(self, task: UserTask) -> None:
        task.completed_mono = time.monotonic()
        if task.cluster_id:
            with self._lock:
                self._completions.setdefault(
                    task.cluster_id, deque(maxlen=32)
                ).append(task.completed_mono)

    #: drain-rate observation window: completions older than this are
    #: not evidence about the CURRENT drain rate — a burst hours ago
    #: must not shape today's Retry-After (nor may an hour of trickle
    #: inflate it past what the now-idle pool would actually take)
    DRAIN_WINDOW_S = 300.0

    def retry_after_s(self, cluster_id: str, *, default_s: float = 5.0) -> float:
        """Estimated seconds until the tenant's queue has room, from its
        measured drain rate: pending tasks over completions/second in the
        recent window (DRAIN_WINDOW_S; older stamps are pruned as stale
        evidence).  Falls back to `default_s` (fleet.tenant.retry.after.s)
        when too little fresh history exists, and is clamped to [1, 300]
        so a stalled queue can't tell clients to come back next week."""
        now = time.monotonic()
        with self._lock:
            pending = sum(
                1 for t in self._tasks.values()
                if t.cluster_id == cluster_id and t.status == "Active"
            )
            stamps = [
                s for s in self._completions.get(cluster_id, ())
                if now - s <= self.DRAIN_WINDOW_S
            ]
        if len(stamps) >= 2 and stamps[-1] > stamps[0]:
            rate = (len(stamps) - 1) / (stamps[-1] - stamps[0])
            est = max(1, pending) / max(rate, 1e-9)
            return float(min(300.0, max(1.0, est)))
        return float(min(300.0, max(1.0, default_s)))

    def get(self, task_id: str) -> UserTask | None:
        with self._lock:
            return self._tasks.get(task_id)

    def all_tasks(self) -> list[UserTask]:
        with self._lock:
            return list(self._tasks.values())

    def _category(self, task: UserTask) -> str | None:
        from cruise_control_tpu.config.endpoints import ENDPOINT_TYPES

        return ENDPOINT_TYPES.get(task.endpoint)

    def _maybe_evict(self):
        now = time.monotonic()
        completed = [t for t in self._tasks.values() if t.status != "Active"]
        # a done-callback can race this scan by a hair (future done, stamp
        # not yet written): treat the stamp as "now" — never older
        for t in completed:
            if t.completed_mono is None:
                t.completed_mono = now
        completed.sort(key=lambda t: t.completed_mono)
        # retention by age-SINCE-COMPLETION then by count, with per-category
        # overrides (reference UserTaskManager scanner +
        # UserTaskManagerConfig); ages are monotonic so wall-clock steps
        # cannot mass-evict.  Counting from completion (not creation) keeps
        # a long-running async op — a rightsize search outlasting the
        # retention window — pollable for the full window after it finishes.
        for t in completed:
            cat = self._category(t)
            retention = self.category_retention_ms.get(cat, self.completed_retention_ms)
            if (now - t.completed_mono) * 1000.0 > retention:
                del self._tasks[t.task_id]
        for t in [t for t in completed if t.task_id in self._tasks]:
            cat = self._category(t)
            cap = self.category_max_cached.get(cat)
            if cap is not None:
                in_cat = [
                    x for x in self._tasks.values()
                    if x.status != "Active" and self._category(x) == cat
                ]
                if len(in_cat) > cap:
                    del self._tasks[t.task_id]
                    continue
            overflow = (
                len([x for x in self._tasks.values() if x.status != "Active"])
                > self.max_cached_completed
            )
            if overflow:
                del self._tasks[t.task_id]

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
