"""CruiseControl service facade — wires monitor, analyzer, executor, detector.

Reference: KafkaCruiseControl.java:100-117 (construction wires the four
subsystems), startUp():162 (start monitor + detection + proposal
precompute), optimizations():493, executeProposals():546, and the
operation runnables (servlet/handler/async/runnable/): RebalanceRunnable,
AddBrokersRunnable, RemoveBrokersRunnable, DemoteBrokerRunnable,
FixOfflineReplicasRunnable, UpdateTopicConfigurationRunnable.

Also implements the detector's SelfHealingActions so anomaly fixes run
through the exact same paths user requests do (reference GoalViolations
fix == RebalanceRunnable self-healing constructor).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

from cruise_control_tpu.analyzer import (
    GoalChain,
    GoalOptimizer,
    OptimizationOptions,
    OptimizerConfig,
    OptimizerResult,
)
from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.detector import (
    AnomalyDetector,
    AnomalyType,
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    SelfHealingNotifier,
    TopicReplicationFactorAnomalyFinder,
)
from cruise_control_tpu.executor import ExecutionOptions, Executor, OngoingExecutionError
from cruise_control_tpu.executor.admin import ClusterAdmin
from cruise_control_tpu.fleet.scheduler import WorkClass
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.monitor import (
    LoadMonitor,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.service.progress import (
    BatchedOptimization,
    ExecutingProposals,
    GeneratingClusterModel,
    OperationProgress,
    WaitingForClusterModel,
)

log = logging.getLogger(__name__)


@dataclasses.dataclass
class _CachedResult:
    result: OptimizerResult
    computed_ms: int  # wall clock, for reporting only
    #: monotonic stamp for expiry — a backwards wall-clock step (NTP) must
    #: not make cached proposals immortal (or instantly stale)
    computed_mono: float
    model_generation: object
    #: who filled this slot: "optimizer" (request/precompute path) or
    #: "controller" (streaming controller publish) — surfaced in /state
    #: so an operator can tell which pipeline the served proposal rode
    source: str = "optimizer"


class AnalyzerCore:
    """The SHARED half of the service: everything that is expensive and
    cluster-agnostic — the goal chain, the GoalOptimizer with its compiled-
    engine cache, the DeviceSupervisor (one circuit breaker for the whole
    instance), the ScenarioEvaluator/Rightsizer, the tracer, and the
    profiling surface.

    A classic deployment builds one implicitly inside its CruiseControl
    facade (behavior unchanged); the fleet controller (fleet/manager.py)
    builds ONE explicitly and hands it to N per-cluster facades, so
    clusters whose bucketed shapes coincide reuse the same compiled
    engines (observable via the `analyzer.engine-cache-*` counters on
    this core's registry) while the cheap per-cluster halves — monitors,
    executors, journals, detectors — multiply."""

    def __init__(
        self,
        config: CruiseControlConfig,
        *,
        sensors: SensorRegistry | None = None,
        tracer=None,
        chain: GoalChain | None = None,
    ):
        self.config = config
        self.sensors = sensors if sensors is not None else SensorRegistry()
        #: flight recorder (config trace.*): ONE tracer per service — in a
        #: fleet every cluster facade records into this same store under a
        #: cluster-scoped component namespace (Tracer.scoped), so one
        #: operation's trace stays whole across shared and per-cluster
        #: subsystems
        self.tracer = tracer if tracer is not None else config.tracer()
        # device profiling surface: per-backend memory/live-buffer gauges
        # + per-device labeled collector, scrapeable via GET /metrics
        from cruise_control_tpu.common.profiling import register_device_gauges

        self.peak_tracker = register_device_gauges(self.sensors)
        #: opt-in jax.profiler dump dir (config tpu.profiler.*)
        self.profiler_dir = (
            config.get("tpu.profiler.dump.dir")
            if config.get("tpu.profiler.enabled")
            else None
        )
        self.constraint = config.balancing_constraint()
        self.chain = chain or GoalChain.from_names(config.get("default.goals"))
        #: reference AnalyzerConfig goal.balancedness.{priority,strictness}.weight
        #: — used by EVERY optimizer built over this core, including the
        #: ad-hoc per-request ones (custom goals / rebalance_disk)
        self.balancedness_weights = (
            config.get("goal.balancedness.priority.weight"),
            config.get("goal.balancedness.strictness.weight"),
        )
        #: shape-bucketing policy the monitors build models under; the
        #: precompute loops pre-warm the NEXT bucket through it
        self.bucket_policy = config.shape_bucket_policy()
        #: ONE supervisor for every optimizer over this core (default +
        #: ad-hoc per-request ones + the precompute threads): they all feed
        #: the same circuit breaker, so a wedged device degrades the whole
        #: analyzer surface coherently instead of per-optimizer
        self.supervisor = config.device_supervisor(
            sensors=self.sensors, tracer=self.tracer
        )
        #: QoS-aware device scheduler (fleet/scheduler.py, config
        #: fleet.scheduler.*): ONE per core — it arbitrates the single
        #: shared device every facade over this core dispatches onto.
        #: None (the default) keeps every dispatch path byte-for-byte
        #: unscheduled.
        self.scheduler = None
        if config.get("fleet.scheduler.enabled"):
            from cruise_control_tpu.fleet.scheduler import DeviceScheduler

            self.scheduler = DeviceScheduler(
                slice_budget_s=config.get("fleet.scheduler.slice.budget.s"),
                freshness_slo_s=config.get("fleet.scheduler.freshness.slo.s"),
                aging_s=config.get("fleet.scheduler.aging.s"),
                shed_queue_depth=config.get("fleet.scheduler.shed.queue.depth"),
                brownout_after_s=config.get("fleet.scheduler.brownout.after.s"),
                brownout_factor=config.get(
                    "fleet.scheduler.brownout.candidate.factor"
                ),
                fast_path_enabled=config.get(
                    "fleet.scheduler.fast.path.enabled"
                ),
                sensors=self.sensors,
            )
        #: black-box dispatch spool (common/blackbox.py, config
        #: blackbox.*): the PROCESS-WIDE recorder is configured here — one
        #: spool file per process under the journal/compile-cache mount,
        #: shared by every facade over this core, so a hang or a kill
        #: leaves a durable "last dispatch in flight" trail.  Disabled
        #: (one predicate per dispatch, zero writes) when no durable
        #: directory exists.
        from cruise_control_tpu.common.blackbox import RECORDER as _bb

        bb_dir = config.blackbox_dir()
        if bb_dir:
            import os

            _bb.configure(
                os.path.join(
                    os.path.expanduser(bb_dir), f"spool-{os.getpid()}.jsonl"
                ),
                max_records=config.get("blackbox.spool.max.records"),
                fsync_batch=config.get("blackbox.fsync.batch.records"),
            )
        else:
            # blackbox.enabled=false / explicitly empty dir must DISABLE
            # a recorder an earlier core in this process configured — the
            # recorder is process-wide, and the disabled contract (zero
            # writes) is pinned
            _bb.configure(None)
        self.blackbox = _bb
        self.sensors.gauge(
            "blackbox.enabled", lambda: 1.0 if _bb.enabled else 0.0
        )
        self.sensors.gauge(
            "blackbox.records-written", lambda: float(_bb.state_json()["recordsWritten"])
        )
        self.sensors.gauge(
            "blackbox.write-errors", lambda: float(_bb.write_errors)
        )
        #: ONE SLO evaluation loop per core (common/slo.py SloTicker):
        #: every facade's registry ticks on this shared thread instead of
        #: N clusters running N wakeup loops; no thread exists until the
        #: first start_up adds a registry
        from cruise_control_tpu.common.slo import SloTicker

        self.slo_ticker = SloTicker(
            interval_s=config.get("slo.tick.interval.s")
        )
        #: boot-prewarm manifest + AOT artifact store (tpu.prewarm.*,
        #: analyzer/prewarm.py): ONE per core, so N fleet facades MERGE
        #: their bucket working sets into one manifest instead of
        #: last-writer-wins, and a restart replays every cluster's
        #: buckets through claim_boot_entries() exactly once
        self.prewarm_store = None
        prewarm_dir = config.prewarm_manifest_dir()
        if prewarm_dir:
            from cruise_control_tpu.analyzer.prewarm import PrewarmStore

            self.prewarm_store = PrewarmStore(
                prewarm_dir,
                chain=self.chain,
                constraint=self.constraint,
                aot_enabled=config.get("tpu.prewarm.aot.enabled"),
                max_entries=config.get("tpu.prewarm.max.entries"),
                sensors=self.sensors,
            )
        self.optimizer = GoalOptimizer(
            chain=self.chain,
            constraint=self.constraint,
            config=config.optimizer_config(),
            parallel_mode=config.parallel_mode(),
            mesh_max_devices=config.mesh_max_devices(),
            model_shard_min_partitions=config.mesh_model_shard_min_partitions(),
            balancedness_weights=self.balancedness_weights,
            engine_cache_size=config.get("tpu.engine.cache.size"),
            sensors=self.sensors,
            shape_bucket=self.bucket_policy,
            supervisor=self.supervisor,
            degraded_budget_s=config.get("tpu.supervisor.degraded.greedy.budget.s"),
            tracer=self.tracer,
            profiler_dir=self.profiler_dir,
            prewarm_store=self.prewarm_store,
            peak_tracker=self.peak_tracker,
            mesh_ft=config.mesh_ft_controller(sensors=self.sensors),
        )
        # per-bucket cold-start attribution as labeled /metrics series
        # (only the core's long-lived default optimizer feeds it; ad-hoc
        # per-request optimizers are too short-lived to own a collector)
        self.sensors.collector(
            "analyzer.engine-compile-seconds-by-bucket",
            self.optimizer.compile_attribution_values,
        )
        from cruise_control_tpu.analyzer.scenario_eval import ScenarioEvaluator
        from cruise_control_tpu.planner.rightsizer import Rightsizer

        #: scenario planner: batched what-if evaluation over the SAME goal
        #: chain, constraint, supervisor, and optimizer (engine cache) the
        #: proposal path uses — a simulated future and a real proposal are
        #: scored by one code path
        self.scenario_evaluator = ScenarioEvaluator(
            chain=self.chain,
            constraint=self.constraint,
            optimizer=self.optimizer,
            supervisor=self.supervisor,
            sensors=self.sensors,
            balancedness_weights=self.balancedness_weights,
            # +1: simulate() rides a baseline scenario in every batch; a
            # request of exactly planner.max.scenarios must not be pushed
            # over the evaluator's limit by the rider
            max_scenarios=config.get("planner.max.scenarios") + 1,
        )
        self.rightsizer = Rightsizer(
            self.scenario_evaluator,
            min_brokers=config.get("planner.rightsize.min.brokers"),
            max_broker_factor=config.get("planner.rightsize.max.broker.factor"),
            bucket=self.bucket_policy,
            sensors=self.sensors,
        )


class CruiseControl:
    """The service facade (reference KafkaCruiseControl.java).

    One facade per Kafka cluster: it OWNS the cluster-scoped subsystems
    (monitor, executor + journal, detector, notifier, proposal cache) and
    runs the analysis surface through an AnalyzerCore — its own private
    one by default, or a shared one handed in by the fleet controller
    (`core=`), in which case `cluster_id` namespaces the executor journal
    directory and the trace components."""

    def __init__(
        self,
        config: CruiseControlConfig,
        monitor: LoadMonitor,
        admin: ClusterAdmin,
        *,
        chain: GoalChain | None = None,
        sensors: SensorRegistry | None = None,
        core: AnalyzerCore | None = None,
        cluster_id: str | None = None,
        fence=None,
    ):
        """fence (fleet HA, fleet/leases.py): this cluster's lease fence.
        When set, the execution journal stamps/checks its epoch, journal
        reconciliation is DEFERRED until the fleet manager activates the
        cluster post-acquisition, and every execution start gates on it —
        a facade without the lease serves read-only."""
        self.config = config
        self.monitor = monitor
        self.admin = admin
        self.fence = fence
        #: per-instance sensor catalog (module-global registries would mix
        #: counters across embedded instances; reference scopes its
        #: MetricRegistry per app, KafkaCruiseControlApp.java:39-41).  In a
        #: fleet this registry is cluster-labeled and distinct from the
        #: shared core's.
        self.sensors = sensors if sensors is not None else SensorRegistry()
        monitor.sensors = self.sensors
        if core is None:
            core = AnalyzerCore(config, sensors=self.sensors, chain=chain)
        self.core = core
        self.cluster_id = cluster_id
        #: cluster-scoped view of the core tracer: in a fleet, this
        #: cluster's monitor/executor/detector spans land in their own
        #: per-component retention rings (`<cluster>:executor`) while the
        #: trace ids stay instance-global
        self.tracer = (
            core.tracer.scoped(cluster_id) if cluster_id else core.tracer
        )
        monitor.tracer = self.tracer
        # shared-core aliases: every pre-fleet call site (and subclass)
        # keeps reading these off the facade
        self.profiler_dir = core.profiler_dir
        self.constraint = core.constraint
        self.chain = core.chain
        self.balancedness_weights = core.balancedness_weights
        self.bucket_policy = core.bucket_policy
        self.supervisor = core.supervisor
        self.optimizer = core.optimizer
        self.scenario_evaluator = core.scenario_evaluator
        self.rightsizer = core.rightsizer
        #: shared device scheduler (None when fleet.scheduler.enabled is
        #: off); the per-cluster freshness SLO rides each request as its
        #: deadline input
        self.scheduler = core.scheduler
        self._freshness_slo_s = config.get("fleet.scheduler.freshness.slo.s")
        from cruise_control_tpu.executor.strategy import resolve_strategy_chain

        #: the configured strategy pool gates what requests may reference
        #: (reference ExecutorConfig replica.movement.strategies); dotted
        #: paths in the pool register custom classes on first resolve
        self.allowed_strategies = set(config.get("replica.movement.strategies"))
        notifier_cls = config.get("executor.notifier.class")
        # durable execution journal (crash-safe execution): constructing the
        # Executor replays it and reconciles anything a crashed predecessor
        # left in flight; start_up() resumes the remainder
        journal = None
        journal_dir = config.get("executor.journal.dir")
        if journal_dir:
            import os

            from cruise_control_tpu.executor.journal import ExecutionJournal

            if cluster_id:
                # fleet: each cluster journals under its own subdirectory,
                # and each cluster's Executor replays ONLY its own journal
                # at construction — a fleet restart reconciles every
                # cluster's in-flight moves without one cluster ever
                # adopting another's (the ids are config-validated to be
                # path-safe)
                journal_dir = os.path.join(journal_dir, cluster_id)
            journal = ExecutionJournal(
                os.path.join(journal_dir, "execution-journal.jsonl"),
                fsync_batch=config.get("executor.journal.fsync.batch.size"),
                fence=fence,
                retention_count=config.get("executor.journal.retention.count"),
                retention_hours=config.get("executor.journal.retention.hours"),
            )
        self.executor = Executor(
            admin,
            strategy=resolve_strategy_chain(
                config.get("default.replica.movement.strategies"),
                allowed=self.allowed_strategies,
            ),
            sensors=self.sensors,
            tracer=self.tracer,
            removal_history_retention_ms=config.get(
                "removal.history.retention.time.ms"
            ),
            demotion_history_retention_ms=config.get(
                "demotion.history.retention.time.ms"
            ),
            notifier=notifier_cls() if notifier_cls is not None else None,
            journal=journal,
            # HA: reconciliation sweeps throttles on the live cluster —
            # it must wait for lease acquisition (FleetManager activates)
            defer_recovery=fence is not None,
        )
        if self.executor.recovery_info() is not None:
            log.warning(
                "executor journal reconciliation: %s",
                self.executor.recovery_info(),
            )
        self._cache: _CachedResult | None = None
        self._cache_lock = threading.Lock()
        self._proposal_expiration_ms = config.get("proposal.expiration.ms")
        webhook = config.get("slack.self.healing.notifier.webhook")
        notifier_cls = SelfHealingNotifier
        notifier_kwargs: dict = {}
        if webhook:
            from cruise_control_tpu.detector.notifier import SlackSelfHealingNotifier

            notifier_cls = SlackSelfHealingNotifier
            notifier_kwargs = dict(
                webhook_url=webhook,
                channel=config.get("slack.self.healing.notifier.channel"),
                username=config.get("slack.self.healing.notifier.user"),
            )
        notifier = notifier_cls(
            **notifier_kwargs,
            self_healing={
                AnomalyType.BROKER_FAILURE: config.get("self.healing.broker.failure.enabled"),
                AnomalyType.GOAL_VIOLATION: config.get("self.healing.goal.violation.enabled"),
                AnomalyType.DISK_FAILURE: config.get("self.healing.disk.failure.enabled"),
                AnomalyType.METRIC_ANOMALY: config.get("self.healing.metric.anomaly.enabled"),
                AnomalyType.TOPIC_ANOMALY: config.get("self.healing.topic.anomaly.enabled"),
            },
            broker_failure_alert_threshold_ms=config.get("broker.failure.alert.threshold.ms"),
            broker_failure_self_healing_threshold_ms=config.get(
                "broker.failure.self.healing.threshold.ms"
            ),
        )
        self.notifier = notifier
        self.actions = SelfHealingAdapter(self)
        self.anomaly_detector = AnomalyDetector(
            notifier,
            self.actions,
            sensors=self.sensors,
            history_size=config.get("num.cached.recent.anomaly.states"),
            tracer=self.tracer,
        )
        # the stuck-move reaper reports EXECUTION_STUCK through the same
        # queue every detector feeds, so the notifier (Slack included)
        # alerts on wedged moves like any other anomaly
        self.executor.anomaly_sink = self.anomaly_detector.add_anomaly
        #: decision ledger (analyzer/ledger.py, config analyzer.ledger.*):
        #: one durable `decision` record per published proposal, joined by
        #: an `outcome` record when its execution finishes (the executor's
        #: finish hook below) and a `calibration` record once the next
        #: complete metric window measures what the moves actually did —
        #: ROADMAP item 3's training corpus and the GET /explain surface.
        #: Fleet deployments namespace one ledger per cluster, exactly
        #: like the execution journal.
        self.ledger = None
        ledger_dir = config.ledger_dir()
        if ledger_dir:
            import os

            from cruise_control_tpu.analyzer.ledger import DecisionLedger

            if cluster_id:
                ledger_dir = os.path.join(ledger_dir, cluster_id)
            self.ledger = DecisionLedger(
                os.path.join(ledger_dir, "decision-ledger.jsonl"),
                retention_count=config.get("analyzer.ledger.retention.count"),
                retention_hours=config.get("analyzer.ledger.retention.hours"),
                sensors=self.sensors,
            )
        #: in-memory predictions of recent decisions (decision id ->
        #: predicted goal/load scores) awaiting their calibration join;
        #: bounded — a decision that never executes ages out
        from collections import OrderedDict, deque

        self._predictions: OrderedDict = OrderedDict()
        self._predictions_cap = 64
        self._ledger_lock = threading.Lock()
        #: decision id whose execution is currently in flight (the
        #: executor serializes executions, so one slot suffices)
        self._executing_decision: str | None = None
        #: calibrations awaiting the next complete metric window
        self._pending_calibrations: list[dict] = []
        #: recent calibration errors driving the MODEL_DRIFT episode —
        #: sized to hold at least drift.min.samples, or a large
        #: min-samples setting could silently never fire
        self._calibration_errors: deque = deque(
            maxlen=max(
                16, config.get("analyzer.calibration.drift.min.samples")
            )
        )
        self._drift_active = False
        self._drift_episodes = 0
        self._calibration_samples = 0
        self._last_calibration: dict | None = None
        self.executor.execution_observer = self._on_execution_finished
        if self.ledger is not None:
            self.sensors.gauge(
                "analyzer.calibration.pending",
                lambda: float(len(self._pending_calibrations)),
            )
            self.sensors.gauge(
                "analyzer.calibration.drift-active",
                lambda: 1.0 if self._drift_active else 0.0,
            )
        if core.scheduler is not None and core.scheduler.anomaly_sink is None:
            # FLEET_OVERLOAD is an INSTANCE-level episode: the first
            # facade built over the core claims the sink, so the anomaly
            # fires exactly once per episode instead of once per cluster
            core.scheduler.anomaly_sink = self.anomaly_detector.add_anomaly
        #: published-proposal age (the freshness the scheduler's SLO
        #: protects, observable): seconds since the cached proposal was
        #: computed, -1 while none is published.  Per cluster via this
        #: facade's (labeled) registry.
        self.sensors.gauge("analyzer.proposal-age-seconds", self.proposal_age_s)
        #: SLO registry (common/slo.py, config slo.*): per cluster, fed
        #: by the controller (publish latency), the scheduler (urgent
        #: queue wait), this facade (cold start) and a freshness probe;
        #: burn episodes raise SLO_BURN through this cluster's detector
        self.slo_registry = None
        self._coldstart_t0 = time.monotonic()
        self._coldstart_recorded = False
        if config.get("slo.enabled"):
            from cruise_control_tpu.common.slo import SloRegistry, SloSpec

            reg = SloRegistry(
                fast_window_s=config.get("slo.burn.fast.window.s"),
                slow_window_s=config.get("slo.burn.slow.window.s"),
                burn_threshold=config.get("slo.burn.threshold"),
                sensors=self.sensors,
                anomaly_sink=self.anomaly_detector.add_anomaly,
                cluster_id=cluster_id or "",
            )
            fresh_s = self._freshness_slo_s
            reg.register(SloSpec(
                name="proposal-freshness",
                description="a published/cached proposal no older than "
                            "the per-cluster freshness SLO is available",
                objective=0.99,
                target=f"proposal age <= {fresh_s:g}s "
                       "(fleet.scheduler.freshness.slo.s)",
                # age < 0 = nothing published yet: no data, not a breach
                # (a cold service is the cold-start SLO's business)
                probe=lambda: (
                    None if (age := self.proposal_age_s()) < 0
                    else age <= self._freshness_slo_s
                ),
            ))
            reg.register(SloSpec(
                name="cold-start",
                description="start to first served/published proposal "
                            "within the restart SLO (one sample per "
                            "process; bench.py --coldstart is the gate)",
                objective=0.99,
                target=f"<= {config.get('slo.coldstart.target.s'):g}s "
                       "(slo.coldstart.target.s)",
            ))
            reg.register(SloSpec(
                name="streaming-publish",
                description="window-roll-to-published-proposal latency "
                            "of the streaming controller's hot path "
                            "(controller.window-roll-to-publish-seconds)",
                objective=0.99,
                target=f"<= {config.get('slo.streaming.publish.target.s'):g}s "
                       "(slo.streaming.publish.target.s)",
            ))
            if core.scheduler is not None:
                reg.register(SloSpec(
                    name="urgent-queue-wait",
                    description="URGENT engine dispatches granted within "
                                "one slice budget (the scheduler's "
                                "preemption bound)",
                    objective=0.99,
                    target="queue wait <= fleet.scheduler.slice.budget.s",
                ))
                if core.scheduler.slo_registry is None:
                    # like the FLEET_OVERLOAD sink: the first facade over
                    # the core claims the scheduler's SLO feed, so urgent
                    # waits are one instance-level series
                    core.scheduler.slo_registry = reg
            self.slo_registry = reg
        self._wire_detectors()
        self._started_ms = int(time.time() * 1000)
        self._precompute_thread: threading.Thread | None = None
        self._stop_precompute = threading.Event()
        #: LoadMonitorTaskRunner attached by build_service (bootstrap/train)
        self.task_runner = None
        #: streaming controller (controller/streaming.py, config
        #: controller.*): the always-on incremental rebalancing loop.
        #: While it runs it REPLACES the legacy proposal-precompute loop
        #: (it publishes a fresh proposal every window roll) and the
        #: bucket-prewarm path stands down (the controller's donated
        #: in-place updates invalidate published state arrays, which
        #: prewarm would otherwise re-pad).  In a fleet, every cluster
        #: facade builds its own instance from its cluster config.
        self.controller = None
        if config.get("controller.enabled"):
            from cruise_control_tpu.controller.streaming import (
                StreamingController,
            )

            self.controller = StreamingController(self)
        self._compile_cache_reported = False
        #: set once the boot-time manifest prewarm has ENQUEUED its
        #: engines (compiles continue on the warm pool); pre-set so
        #: facades that never start_up (tests, bench drivers) and
        #: deployments without a manifest behave exactly as today
        self._boot_prewarm_done = threading.Event()
        self._boot_prewarm_done.set()

    def _detect_optimizer_degraded(self):
        """OPTIMIZER_DEGRADED anomaly, once per breaker-open episode.

        Edge-triggered on the supervisor's open epoch: the breaker staying
        open across detection rounds is ONE degradation event, not a new
        anomaly per round (the /state supervisor block carries the live
        state); a close-then-reopen bumps the epoch and reports again."""
        sup = self.supervisor
        if sup is None or not sup.is_degraded:
            return None
        epoch = sup.open_epoch
        if epoch == self._degraded_reported_epoch:
            return None
        self._degraded_reported_epoch = epoch
        from cruise_control_tpu.detector.anomalies import OptimizerDegraded

        last = sup.last_failure or {}
        return OptimizerDegraded(
            failure_class=last.get("class", "unknown"),
            last_error=str(last.get("error", "")),
            open_epoch=epoch,
        )

    def _detect_mesh_degraded(self):
        """MESH_DEGRADED anomaly, once per mesh degrade episode.

        The mesh-ft controller (parallel/ft.py) arms ONE pending event
        when an episode opens (first width reduction) and re-arms only
        after a run completes back at full width — so the breaker walking
        further down the ladder inside the same episode never re-fires
        (the /state meshFt block carries the live width)."""
        ft = getattr(self.optimizer, "_mesh_ft", None)
        if ft is None:
            return None
        event = ft.poll_event()
        if event is None:
            return None
        from cruise_control_tpu.detector.anomalies import MeshDegraded

        return MeshDegraded(
            lost_devices=list(event.get("lost_devices", [])),
            from_width=int(event.get("from_width", 0)),
            to_width=int(event.get("to_width", 0)),
            failure_class=str(event.get("failure_class", "unknown")),
            episode=int(event.get("episode", 0)),
        )

    def _wire_detectors(self):
        """Reference AnomalyDetector.java:63-68 wiring."""
        from cruise_control_tpu.detector.detectors import SlowBrokerFinder

        #: last breaker-open epoch reported as an anomaly (edge trigger)
        self._degraded_reported_epoch = 0

        req = ModelCompletenessRequirements(min_required_num_windows=1)
        # the violation detector watches its own (usually smaller) goal list
        # (reference AnomalyDetectorConfig anomaly.detection.goals:103-107)
        detection_goals = self.config.get("anomaly.detection.goals")
        detection_chain = (
            GoalChain.from_names(detection_goals) if detection_goals else self.chain
        )
        allow_est = self.config.get("anomaly.detection.allow.capacity.estimation")
        gvd = GoalViolationDetector(
            lambda: self.monitor.cluster_model(
                req, allow_capacity_estimation=allow_est
            ),
            detection_chain,
            self.constraint,
        )
        bfd = BrokerFailureDetector(
            self.admin.topology,
            persist_path=self.config.get("broker.failure.persisted.path"),
        )
        dfd = DiskFailureDetector(self.admin.topology)
        # pluggable topic-config provider feeds min.insync.replicas into RF
        # anomaly detection (reference topic.config.provider.class)
        tcp_cls = self.config.get("topic.config.provider.class")
        topic_config_provider = (
            tcp_cls(self.config, self.admin) if tcp_cls is not None else None
        )
        rf_finder_cls = self.config.get("topic.anomaly.finder.class")
        if rf_finder_cls is not None:
            rfd = rf_finder_cls(self.admin.topology, self.config)
        else:
            rfd = TopicReplicationFactorAnomalyFinder(
                self.admin.topology,
                target_rf=self.config.get("topic.anomaly.target.replication.factor"),
                topic_config_provider=topic_config_provider,
            )
        slow_finder_cls = self.config.get("metric.anomaly.finder.class")
        custom_slow = (
            slow_finder_cls(self.config) if slow_finder_cls is not None else None
        )
        slow = SlowBrokerFinder(
            history_percentile=self.config.get("slow.broker.history.percentile"),
            peer_ratio=self.config.get("slow.broker.peer.comparison.ratio"),
            removal_threshold=self.config.get("slow.broker.strike.removal.threshold"),
        )

        def slow_detect():
            """Feed the finder multi-family broker evidence: byte-rate-
            normalized log-flush time plus raw request latencies and queue
            depth (reference SlowBrokerFinder.java:99 collects byte rates
            AND request latencies; one family spiking must not flag)."""
            runner = self.task_runner
            agg = getattr(getattr(runner, "fetcher", None), "broker_aggregator", None)
            if agg is None or not agg.num_entities():
                return None
            try:
                from cruise_control_tpu.monitor.aggregator import AggregationOptions

                res = agg.aggregate(
                    AggregationOptions(
                        max_allowed_extrapolations_per_entity=self.config.get(
                            "max.allowed.extrapolations.per.broker"
                        )
                    )
                )
            except ValueError:
                return None
            m = agg.metric_def

            def mid(name):
                try:
                    return m.metric_id(name)
                except KeyError:
                    return None

            flush = mid("BROKER_LOG_FLUSH_TIME_MS_MEAN")
            if flush is None:
                return None
            families = {
                "log_flush_time_ms_mean": flush,
                "produce_local_time_ms_mean": mid("BROKER_PRODUCE_LOCAL_TIME_MS_MEAN"),
                "request_queue_size": mid("BROKER_REQUEST_QUEUE_SIZE"),
            }
            bytes_ids = [mid("LEADER_BYTES_IN"), mid("REPLICATION_BYTES_IN_RATE")]
            evidence: dict[int, dict[str, float]] = {}
            for i, entity in enumerate(agg.entities()):
                valid = res.window_valid[i]
                if not valid.any():
                    continue
                w = int(np.nonzero(valid)[0][0])  # newest valid window
                row = res.values[i, w]
                fams: dict[str, float] = {}
                for name, idx in families.items():
                    if idx is not None:
                        fams[name] = float(row[idx])
                # byte-normalized flush time REPLACES the raw value when a
                # byte rate exists (reference divides latency by the byte
                # rate so a busier broker is not "slower"); keeping both
                # would double-count one correlated signal toward the
                # majority bar
                rate = sum(float(row[j]) for j in bytes_ids if j is not None)
                if rate > 0:
                    fams["log_flush_time_per_mb"] = fams.pop(
                        "log_flush_time_ms_mean"
                    ) / max(rate, 1e-9)
                evidence[int(getattr(entity, "broker_id", entity))] = fams
            # a configured metric.anomaly.finder.class replaces the builtin
            anomaly = (custom_slow or slow).detect(evidence)
            # removal (decommission + rebuild) is destructive; the dedicated
            # switch gates it regardless of strike count (reference
            # AnomalyDetectorConfig slow.broker removal switches)
            if (
                anomaly is not None
                and anomaly.remove_slow_brokers
                and not self.config.get("slow.broker.removal.enabled")
            ):
                anomaly = dataclasses.replace(anomaly, remove_slow_brokers=False)
            return anomaly

        self.broker_failure_detector = bfd
        self.slow_broker_finder = slow

        def _interval(key: str) -> float | None:
            ms = self.config.get(key)
            return ms / 1000.0 if ms else None

        reg = self.anomaly_detector.register_detector
        reg(gvd.detect, interval_s=_interval("goal.violation.detection.interval.ms"))
        # broker failures are watched every round (the reference's ZK
        # watcher is effectively continuous); the backoff key only delays
        # retries after a failed detection, it is NOT a cadence
        reg(
            bfd.detect,
            error_backoff_s=_interval("broker.failure.detection.backoff.ms"),
        )
        reg(dfd.detect, interval_s=_interval("disk.failure.detection.interval.ms"))
        reg(rfd.detect, interval_s=_interval("topic.anomaly.detection.interval.ms"))
        if self.config.get("partition.size.detection.enabled"):
            from cruise_control_tpu.detector.detectors import (
                PartitionSizeAnomalyFinder,
            )

            psf = PartitionSizeAnomalyFinder(
                lambda: self.monitor.cluster_model(
                    req, allow_capacity_estimation=allow_est
                ),
                lambda: self.monitor.last_catalog,
                max_partition_size=self.config.get(
                    "self.healing.partition.size.threshold.byte"
                ),
                excluded_topics_pattern=self.config.get(
                    "topic.excluded.from.partition.size.check"
                ),
            )
            reg(psf.detect, interval_s=_interval("topic.anomaly.detection.interval.ms"))
        reg(slow_detect, interval_s=_interval("metric.anomaly.detection.interval.ms"))
        # supervisor breaker watch: every round (cheap property reads)
        reg(self._detect_optimizer_degraded)
        # mesh fault-tolerance watch: drains the once-per-episode
        # MESH_DEGRADED event the width ladder armed (cheap poll)
        reg(self._detect_mesh_degraded)
        # calibration loop + MODEL_DRIFT watch (decision ledger): cheap
        # when nothing is due — the measured-state scoring dispatch runs
        # only once an executed decision's next metric window completes
        reg(self._detect_model_drift)

    # ------------------------------------------------------------------
    # lifecycle (reference startUp():162)
    # ------------------------------------------------------------------

    def start_up(self, *, detection_interval_s: float | None = None, precompute: bool = False):
        self.monitor.start()
        self.anomaly_detector.start(
            detection_interval_s
            or self.config.get("anomaly.detection.interval.ms") / 1000.0
        )
        # boot prewarm (analyzer/prewarm.py): replay the durable manifest
        # through the warm pool so the ACTIVE buckets are compiling before
        # resume_recovered_execution() or the controller's first cycle
        # needs a proposal.  One claim per store: in a fleet, every
        # facade's start_up races here and exactly one runs the replay.
        store = getattr(self.optimizer, "prewarm_store", None)
        if store is not None:
            self._boot_prewarm_done.clear()
            threading.Thread(
                target=self._boot_prewarm, daemon=True, name="boot-prewarm"
            ).start()
        if self.executor.has_recovered_execution:
            # drive the journal-reconciled remainder off the startup path:
            # re-adopted moves progress without resubmission while the
            # service comes up (reference resumes its persisted execution
            # the same way)
            self.resume_recovered_async()
        if self.controller is not None:
            # the streaming controller IS the always-on precompute: it
            # publishes a fresh proposal every window roll, so the legacy
            # timer loop would only burn duplicate anneals beside it.
            # It starts immediately but lets the boot-time manifest
            # prewarm COMPLETE (bounded) before its first cycle takes
            # ownership — its donated in-place updates park the bucket
            # prewarm path, so boot is the one window this prewarm has.
            self.controller.start(boot_gate=self._boot_prewarm_done)
        elif precompute:
            self._precompute_thread = threading.Thread(
                target=self._precompute_loop, daemon=True, name="proposal-precompute"
            )
            self._precompute_thread.start()
        if self.slo_registry is not None:
            # continuous SLO evaluation: probes sampled + burn episodes
            # fired with nobody scraping /slo (the alert path must not
            # depend on being observed); the ticker thread is shared by
            # every facade over this core
            self.core.slo_ticker.add(self.slo_registry)
            self.core.slo_ticker.start()

    def resume_recovered_async(self):
        """Background-drive a journal-reconciled execution remainder.
        FencedError mid-resume (fleet HA: the lease was lost again) is an
        ordinary step-down, not a crashed thread."""

        def run():
            try:
                self.executor.resume_recovered_execution()
            except Exception as e:  # noqa: BLE001 — classify below
                from cruise_control_tpu.fleet.leases import FencedError

                if isinstance(e, FencedError):
                    log.warning(
                        "recovery resume fenced (lease lost): %s", e
                    )
                else:
                    log.warning("recovery resume failed", exc_info=True)

        threading.Thread(target=run, daemon=True, name="executor-recovery").start()

    def shutdown(self):
        self._stop_precompute.set()
        if self.controller is not None:
            self.controller.stop()
        if self.slo_registry is not None:
            # the shared ticker stops itself once the last facade leaves
            self.core.slo_ticker.remove(self.slo_registry)
        self.anomaly_detector.shutdown()
        if self.ledger is not None:
            self.ledger.close()

    def _precompute_loop(self):
        """Reference GoalOptimizer.run precompute loop (GoalOptimizer.java:124-175).

        The FIRST pass runs immediately: it compiles the engine for the
        live cluster shape and fills the proposal cache, so the first user
        request pays cache-hit latency instead of the cold trace+compile+
        optimize warmup."""
        allow_est = self.config.get("allow.capacity.estimation.on.proposal.precompute")
        streak_gauge = self.sensors.gauge("analyzer.precompute-consecutive-failures")
        consecutive = 0
        from cruise_control_tpu.fleet.scheduler import BackgroundShedError

        while True:
            try:
                # BACKGROUND: the periodic refresh is exactly the
                # steady-state load the scheduler's shed ladder exists
                # to relieve — under overload this cycle sheds (counted
                # by the scheduler, the cached proposal keeps serving)
                # instead of crowding out urgent/interactive dispatches.
                # Pre-check BEFORE the full model build: a cycle the
                # dispatch would shed anyway must not pay the expensive
                # host flatten while the instance is saturated.
                sched = self.scheduler
                if sched is not None and sched.should_shed_background():
                    sched.shed_background(op="precompute")
                    if self._stop_precompute.wait(
                        self._proposal_expiration_ms / 2000.0
                    ):
                        return
                    continue
                self.proposals(
                    OperationProgress(),
                    ignore_cache=True,
                    allow_capacity_estimation=allow_est,
                    work_class=WorkClass.BACKGROUND,
                )
                consecutive = 0
                streak_gauge.set(0)
                self._log_compile_cache_report()
            except BackgroundShedError:
                # a shed refresh is overload protection working, not a
                # precompute failure — don't touch the failing streak
                pass
            except Exception:  # noqa: BLE001 — the loop must keep ticking,
                # but a permanently broken precompute must be VISIBLE:
                # every failure counts, and three in a row start WARN
                # logging (one line per cycle, cycles are minutes apart).
                # Gauge before counter: a reader observing the counter must
                # never see a stale (smaller) streak.
                consecutive += 1
                streak_gauge.set(consecutive)
                self.sensors.counter("analyzer.precompute-failures").inc()
                if consecutive >= 3:
                    log.warning(
                        "proposal precompute failed %d times in a row",
                        consecutive,
                        exc_info=True,
                    )
            try:
                self._prewarm_next_bucket()
            except Exception:  # noqa: BLE001 — prewarm is best-effort
                self.sensors.counter("analyzer.prewarm-failures").inc()
            if self._stop_precompute.wait(self._proposal_expiration_ms / 2000.0):
                return

    def _boot_prewarm(self):
        """Replay the boot-prewarm manifest (analyzer/prewarm.py) through
        `GoalOptimizer.prewarm`, most-recent bucket first — the ACTIVE
        bucket's programs compile before any speculation (warm-pool
        priority = manifest rank).  Each entry builds a placeholder state
        of the recorded bucket shape (+ max_rf) and reconstructs the
        recorded OptimizerConfig, so the compiled programs are exactly
        the ones the live model of that bucket will run; entries from a
        different parallel mode are skipped.  Failures are counted, never
        fatal — a failed prewarm just means that bucket pays its cold
        compile like today."""
        t0 = time.monotonic()
        enqueued = 0
        try:
            store = self.optimizer.prewarm_store
            entries = store.claim_boot_entries() if store is not None else []
            for rank, entry in enumerate(entries):
                try:
                    shape, max_rf, cfg, pmode = store.entry_engine_inputs(entry)
                    if pmode != self.optimizer.parallel_mode:
                        continue
                    from cruise_control_tpu.models.builder import prewarm_state

                    self.optimizer.prewarm(
                        prewarm_state(shape, max_rf=max_rf),
                        config=cfg,
                        priority=rank,
                    )
                    enqueued += 1
                    self.sensors.counter("analyzer.boot-prewarm-buckets").inc()
                except Exception:  # noqa: BLE001 — per-entry, keep replaying
                    self.sensors.counter("analyzer.boot-prewarm-failures").inc()
                    log.warning(
                        "boot prewarm of manifest entry failed", exc_info=True
                    )
            if enqueued:
                log.info(
                    "boot prewarm: %d manifest bucket(s) compiling in the "
                    "background", enqueued,
                )
        except Exception:  # noqa: BLE001 — boot must never hang on prewarm
            self.sensors.counter("analyzer.boot-prewarm-failures").inc()
            log.warning("boot prewarm failed", exc_info=True)
        finally:
            self.sensors.gauge("analyzer.boot-prewarm-seconds").set(
                round(time.monotonic() - t0, 6)
            )
            self._boot_prewarm_done.set()

    def _log_compile_cache_report(self):
        """After the first proposal pass: how many XLA executables loaded
        warm from the persistent compile cache (hits) vs compiled fresh
        (misses) — the observable half of tpu.compile.cache.dir."""
        if self._compile_cache_reported:
            return
        from cruise_control_tpu.common.compilation_cache import boot_report

        report = boot_report()
        self._compile_cache_reported = True
        if report is not None:
            log.info(
                "persistent compile cache after first proposal pass: "
                "%d executables compiled fresh (misses), %d were available "
                "warm at boot (%s)",
                report["newCompiles"], report["entriesAtBoot"], report["dir"],
            )

    def _prewarm_next_bucket(self):
        """Background-compile the engine for the NEXT shape bucket up.

        Shape bucketing keeps the engine warm while churn stays inside the
        current bucket; the generation that overflows it (enough partition
        creates) would pay a cold compile exactly when the cluster is
        busiest.  Pre-warming a zero-padded copy of the latest model at the
        next bucket makes that overflow hit a compiled engine instead —
        `Engine` programs never depend on the padding data, only the shape.
        """
        if not self.bucket_policy.enabled or self.optimizer.parallel_mode != "single":
            return
        if self.controller is not None and self.controller.running:
            # the controller's donated in-place updates invalidate the
            # cached result's state_before buffers — padding them here
            # would read deleted arrays (LiveState ownership contract)
            return
        with self._cache_lock:
            cached = self._cache
        if cached is None:
            return
        state = cached.result.state_before
        nxt = self.bucket_policy.next_bucket_shape(state.shape)
        # cheap checks BEFORE materializing the padded model: pad_state is
        # a full device->host->device round trip of every model array, and
        # this loop re-runs every proposal_expiration/2 seconds
        if nxt == state.shape or self.optimizer.has_engine_for(nxt):
            return
        sched = self.scheduler
        if sched is not None and sched.brownout_active:
            # speculation is pure luxury: brownout lets real background
            # cycles run (reduced), but a next-bucket guess must never
            # add device/compile pressure mid-episode — shed it, counted
            sched.shed_background(op="prewarm-next-bucket")
            return
        from cruise_control_tpu.models.builder import pad_state

        # speculation compiles AFTER anything the boot prewarm or a
        # request enqueued (warm-pool priority ordering): the active
        # bucket's programs must never wait behind a next-bucket guess
        from cruise_control_tpu.fleet.scheduler import BackgroundShedError

        padded = pad_state(state, nxt)
        try:
            self._scheduled(
                WorkClass.BACKGROUND,
                lambda: self.optimizer.prewarm(padded, priority=100),
                op="prewarm-next-bucket",
            )
        except BackgroundShedError:
            pass  # a shed speculation is overload protection working

    # ------------------------------------------------------------------
    # proposal computation + cache (reference optimizations():276-324,493)
    # ------------------------------------------------------------------

    def _cluster_model(
        self,
        progress: OperationProgress,
        *,
        allow_capacity_estimation: bool = True,
    ) -> ClusterState:
        progress.add_step(WaitingForClusterModel())
        with self.monitor.acquire_for_model_generation():
            progress.add_step(GeneratingClusterModel())
            req = ModelCompletenessRequirements(
                min_required_num_windows=1,
                min_monitored_partitions_percentage=self.config.get(
                    "min.valid.partition.ratio"
                ),
            )
            return self.monitor.cluster_model(
                req, allow_capacity_estimation=allow_capacity_estimation
            )

    def _make_optimizer(
        self, goals: list[str], *, intra_broker: bool = False
    ) -> GoalOptimizer:
        """Ad-hoc optimizer for a custom goal list (reference builds a
        per-request goalsByPriority); carries the SAME constraint/config/
        balancedness weights as the default optimizer so a request-scoped
        knob cannot silently fall back to hardcoded defaults."""
        cfg = self.config.optimizer_config()
        if intra_broker:
            cfg = dataclasses.replace(cfg, intra_broker=True)
        return GoalOptimizer(
            chain=GoalChain.from_names(goals),
            constraint=self.constraint,
            config=cfg,
            balancedness_weights=self.balancedness_weights,
            engine_cache_size=self.config.get("tpu.engine.cache.size"),
            sensors=self.sensors,
            shape_bucket=self.bucket_policy,
            supervisor=self.supervisor,
            degraded_budget_s=self.config.get(
                "tpu.supervisor.degraded.greedy.budget.s"
            ),
            tracer=self.tracer,
            profiler_dir=self.profiler_dir,
        )

    def proposals(
        self,
        progress: OperationProgress,
        *,
        ignore_cache: bool = False,
        options: OptimizationOptions | None = None,
        goals: list[str] | None = None,
        allow_capacity_estimation: bool = True,
        work_class: "WorkClass | None" = None,
    ) -> OptimizerResult:
        """Cached unless options/goals are non-default
        (reference ignoreProposalCache():469).

        A request that forbids capacity estimation must not be served from a
        cache the precompute loop filled with estimation allowed (reference
        sanity-checks capacityEstimationInfoByBrokerId on cached results) —
        it bypasses the cache and builds its own model under the flag.  Its
        RESULT is still stored: a no-estimation result is strictly safer
        than an estimated one, so a no-estimation precompute loop fills the
        cache rather than discarding every cycle."""
        storable = options is None and goals is None
        servable = storable and allow_capacity_estimation
        if servable and not ignore_cache:
            cached = self._valid_cache()
            if cached is not None:
                return cached
        state = self._cluster_model(
            progress, allow_capacity_estimation=allow_capacity_estimation
        )
        if options is None:
            # config-level always-excluded topics apply to the default path
            # too (reference AnalyzerConfig
            # topics.excluded.from.partition.movement)
            options = self._build_options(state)
        optimizer = self.optimizer if goals is None else self._make_optimizer(goals)
        progress.add_step(BatchedOptimization(optimizer.config.num_rounds))
        # reference GoalOptimizer proposal-computation-timer (:116,155);
        # the histogram twin feeds /metrics with aggregatable buckets
        with self.sensors.timer("analyzer.proposal-computation-timer").time():
            # INTERACTIVE under the device scheduler (REST path) unless
            # the caller says otherwise — the periodic precompute loop
            # passes BACKGROUND so steady-state refresh anneals sit in
            # the shed ladder's background rung; a self-healing fix
            # pipeline reaching here carries an URGENT tag that upgrades
            # either default
            result = self._scheduled(
                work_class if work_class is not None else WorkClass.INTERACTIVE,
                lambda: optimizer.optimize(
                    state, options=options or OptimizationOptions()
                ),
                op="proposals",
            )
        self.sensors.histogram("analyzer.proposal-computation-seconds").observe(
            result.wall_seconds
        )
        self._record_coldstart_once()
        if storable:
            gen = self.monitor.model_generation()
            with self._cache_lock:
                self._cache = _CachedResult(
                    result,
                    int(time.time() * 1000),
                    time.monotonic(),
                    gen,
                )
            # a stored result IS a published proposal (it will serve
            # /proposals until superseded): one ledger decision record
            self._record_decision(
                result, source="optimizer", generation=gen,
                work_class=(
                    work_class.name.lower() if work_class is not None
                    else "interactive"
                ),
            )
        return result

    def publish_proposal(
        self,
        result: OptimizerResult,
        *,
        source: str = "controller",
        generation=None,
        prior_table=None,
        calibration_eligible: bool = True,
    ) -> bool:
        """Publish a freshly computed result into the proposal cache —
        the streaming controller's output path.  `generation` is the
        model generation the result was COMPUTED FROM (the controller
        captures it when it syncs its live model); omitting it falls
        back to a publish-time read, which can overstate freshness when
        a window rolls mid-anneal.  The freshest generation wins: a
        publish STRICTLY older than the cached result is dropped
        (False); same-or-newer SUPERSEDES the cached proposal — a fresher
        anneal of the same generation replaces it, so `/proposals` can
        never serve a staler result than `/state`'s ControllerState
        reports.

        `prior_table` (controller publishes) rides into the decision
        record's per-move prior-contribution features;
        `calibration_eligible=False` excludes this decision from
        calibration sampling — the controller's FIRST (cold-compile)
        publish passes it, mirroring the streaming-publish SLO exclusion,
        so a restart can never fire a spurious MODEL_DRIFT."""
        gen = generation if generation is not None else self.monitor.model_generation()
        new_key = (gen.metadata_generation, gen.load_generation)
        with self._cache_lock:
            c = self._cache
            if c is not None and c.model_generation is not None:
                old = c.model_generation
                old_key = (old.metadata_generation, old.load_generation)
                if old_key > new_key:
                    return False  # cached proposal is already fresher
            self._cache = _CachedResult(
                result,
                int(time.time() * 1000),
                time.monotonic(),
                gen,
                source=source,
            )
        # the controller replaces the precompute loop, so the first
        # published anneal is this deployment's "first proposal pass" —
        # report the persistent compile cache's hit/miss split here too
        self._log_compile_cache_report()
        self._record_coldstart_once()
        self._record_decision(
            result, source=source, generation=gen, work_class="background",
            prior_table=prior_table, calibration_eligible=calibration_eligible,
        )
        return True

    def _record_coldstart_once(self) -> None:
        """The cold-start SLO's one sample per process: facade
        construction to the first computed/published proposal, good when
        it landed inside `slo.coldstart.target.s` (the budget
        bench.py --coldstart gates)."""
        if self._coldstart_recorded or self.slo_registry is None:
            return
        self._coldstart_recorded = True
        wall = time.monotonic() - self._coldstart_t0
        self.slo_registry.record(
            "cold-start", wall <= self.config.get("slo.coldstart.target.s")
        )

    # ------------------------------------------------------------------
    # decision ledger + calibration (analyzer/ledger.py)
    # ------------------------------------------------------------------

    @staticmethod
    def _ledger_decision_id(result: OptimizerResult) -> str | None:
        """The ledger decision id a result was recorded under (stamped
        into its timing record), or None when it was never recorded."""
        for h in result.history:
            if h.get("timing"):
                return h.get("ledger_decision_id")
        return None

    def _record_decision(
        self,
        result: OptimizerResult,
        *,
        source: str,
        generation=None,
        work_class: str = "",
        prior_table=None,
        calibration_eligible: bool = True,
    ) -> str | None:
        """Append one `decision` record for a published proposal; stamps
        the ledger id into the result's timing record so a later
        execution of the same result joins its outcome.  Best-effort:
        ledger failures are counted, never surfaced to the caller."""
        led = self.ledger
        if led is None:
            return None
        try:
            import hashlib

            from cruise_control_tpu.analyzer.ledger import (
                build_decision_record,
                load_summary,
            )
            from cruise_control_tpu.common.trace import current_trace_id

            rec = build_decision_record(
                result,
                source=source,
                trace_id=current_trace_id(),
                cluster_id=self.cluster_id or "",
                generation=generation,
                work_class=work_class,
                config_fingerprint=hashlib.sha1(
                    repr(self.optimizer.config).encode()
                ).hexdigest()[:12],
                prior_table=prior_table,
                calibration_eligible=calibration_eligible,
            )
            did = led.record_decision(rec)
            timing = next((h for h in result.history if h.get("timing")), None)
            if timing is not None:
                timing["ledger_decision_id"] = did
            with self._ledger_lock:
                self._predictions[did] = {
                    "goal_names": list(result.goal_names),
                    "violations": [
                        float(v) for v in np.asarray(result.violations_after)
                    ],
                    "objective": float(result.objective_after),
                    "balancedness": float(result.balancedness_after),
                    "load": load_summary(result.stats_after),
                    "eligible": bool(calibration_eligible),
                }
                while len(self._predictions) > self._predictions_cap:
                    self._predictions.popitem(last=False)
            return did
        except Exception:  # noqa: BLE001 — the ledger must never fail serving
            self.sensors.counter("analyzer.ledger.errors").inc()
            log.warning("decision-ledger record failed", exc_info=True)
            return None

    def _on_execution_finished(self, info: dict) -> None:
        """Executor finish hook (PR-4 observer path): join the executed
        decision's `outcome` record and, when calibration applies, queue
        the predicted-vs-measured check for the next complete window."""
        with self._ledger_lock:
            did = self._executing_decision
            self._executing_decision = None
        if did is None or self.ledger is None:
            return
        try:
            self.ledger.record_outcome(did, dict(info))
        except Exception:  # noqa: BLE001
            self.sensors.counter("analyzer.ledger.errors").inc()
            log.warning("decision-ledger outcome failed", exc_info=True)
            return
        pred = self._predictions.get(did)
        if (
            pred is None
            or not pred.get("eligible", True)
            or not self.config.get("analyzer.calibration.enabled")
            or info.get("fencedAbort")
            or not info.get("completed")
        ):
            return
        try:
            window = self.monitor.partition_aggregator.current_window_index
        except Exception:  # noqa: BLE001 — no aggregator (bare harnesses)
            window = None
        with self._ledger_lock:
            self._pending_calibrations.append({
                "id": did,
                "window": window,
                "finished_ms": info.get("finishedMs"),
            })

    def _run_calibration_once(self) -> list[dict]:
        """Score the MEASURED cluster state for every calibration whose
        next complete metric window has rolled; append `calibration`
        records and return them.  One batched ScenarioEvaluator dispatch
        regardless of how many decisions are due (they all compare
        against the same measured state)."""
        if self.ledger is None or not self._pending_calibrations:
            return []
        try:
            cur_w = self.monitor.partition_aggregator.current_window_index
        except Exception:  # noqa: BLE001
            return []
        with self._ledger_lock:
            due = [
                e for e in self._pending_calibrations
                if cur_w is not None
                and (e["window"] is None or cur_w > e["window"])
            ]
        if not due:
            return []
        from cruise_control_tpu.analyzer.ledger import (
            load_summary,
            load_summary_error,
        )
        from cruise_control_tpu.analyzer.objective import balancedness_score
        from cruise_control_tpu.analyzer.scenario_eval import VIOLATION_TOL

        state = self._cluster_model(OperationProgress())
        obj, viol, stats, degraded = self.scenario_evaluator.score_state(state)
        pw, sw = self.balancedness_weights
        measured = {
            "objective": round(float(obj), 6),
            "violations": [round(float(v), 6) for v in viol],
            "balancedness": round(
                balancedness_score(
                    viol, self.chain, priority_weight=pw, strictness_weight=sw
                ), 3,
            ),
            "violatedGoals": [
                n for n, v in zip(self.chain.names(), viol)
                if v > VIOLATION_TOL
            ],
            "load": load_summary(stats),
            "windowIndex": int(cur_w),
            "degraded": bool(degraded),
        }
        out = []
        for entry in due:
            did = entry["id"]
            with self._ledger_lock:
                pred = self._predictions.pop(did, None)
            if pred is None:
                continue
            pv = np.asarray(pred["violations"], np.float64)
            mv = np.asarray(measured["violations"], np.float64)
            n = min(pv.size, mv.size)
            goal_err = np.abs(mv[:n] - pv[:n])
            load_err = load_summary_error(pred["load"], measured["load"])
            rec = {
                "predicted": {
                    "objective": round(pred["objective"], 6),
                    "violations": [round(float(v), 6) for v in pv],
                    "balancedness": round(pred["balancedness"], 3),
                    "load": pred["load"],
                },
                "measured": measured,
                "error": {
                    "goalAbs": [round(float(e), 6) for e in goal_err],
                    "goalMaxAbs": round(float(goal_err.max() if n else 0.0), 6),
                    "objectiveAbs": round(
                        abs(measured["objective"] - pred["objective"]), 6
                    ),
                    "load": load_err,
                },
            }
            try:
                self.ledger.record_calibration(did, rec)
            except Exception:  # noqa: BLE001
                self.sensors.counter("analyzer.ledger.errors").inc()
                continue
            self._calibration_samples += 1
            self._last_calibration = rec["error"]
            self.sensors.counter("analyzer.calibration.samples").inc()
            self.sensors.histogram("analyzer.calibration.goal-error").observe(
                rec["error"]["goalMaxAbs"]
            )
            self.sensors.histogram("analyzer.calibration.load-error").observe(
                rec["error"]["load"].get("maxAbsAvgError", 0.0)
            )
            with self._ledger_lock:
                self._calibration_errors.append((
                    rec["error"]["goalMaxAbs"],
                    rec["error"]["load"].get("maxAbsAvgError", 0.0),
                ))
            out.append(rec)
        with self._ledger_lock:
            done = {e["id"] for e in due}
            self._pending_calibrations = [
                e for e in self._pending_calibrations if e["id"] not in done
            ]
        return out

    def _detect_model_drift(self):
        """Detector-loop hook: run due calibrations, then watch for
        SUSTAINED prediction error.  MODEL_DRIFT fires EXACTLY once per
        episode (alert-only, like OPTIMIZER_DEGRADED); the episode
        re-arms once the mean error falls back under the threshold."""
        try:
            self._run_calibration_once()
        except Exception:  # noqa: BLE001 — calibration must not kill the loop
            self.sensors.counter("analyzer.calibration.failures").inc()
            log.warning("calibration cycle failed", exc_info=True)
        min_samples = self.config.get("analyzer.calibration.drift.min.samples")
        threshold = self.config.get("analyzer.calibration.drift.threshold")
        with self._ledger_lock:
            errs = list(self._calibration_errors)[-min_samples:]
        if len(errs) < min_samples:
            return None
        mean_goal = float(np.mean([g for g, _l in errs]))
        mean_load = float(np.mean([l for _g, l in errs]))
        if mean_goal <= threshold:
            self._drift_active = False  # episode re-arms on recovery
            return None
        if self._drift_active:
            return None  # once per episode
        self._drift_active = True
        self._drift_episodes += 1
        from cruise_control_tpu.detector.anomalies import ModelDrift

        return ModelDrift(
            cluster_id=self.cluster_id or "",
            samples=len(errs),
            mean_goal_error=round(mean_goal, 6),
            mean_load_error=round(mean_load, 6),
            threshold=threshold,
            episode=self._drift_episodes,
        )

    def calibration_state(self) -> dict:
        """The /fleet //state calibration block: sample counts, last
        prediction error, drift-episode state."""
        with self._ledger_lock:
            pending = len(self._pending_calibrations)
        return {
            "samples": self._calibration_samples,
            "pending": pending,
            "lastError": self._last_calibration,
            "driftActive": self._drift_active,
            "driftEpisodes": self._drift_episodes,
        }

    def ledger_entries(self, *, limit: int = 50) -> list[dict]:
        """Joined decision→outcome→calibration episodes, newest first
        (GET /ledger raw passthrough)."""
        if self.ledger is None:
            return []
        return self.ledger.entries(limit=limit)

    def explain(
        self, *, trace_id: str | None = None, decision_id: str | None = None
    ) -> dict:
        """Replay one ledger episode as a structured explanation (GET
        /explain?trace_id=|proposal=): goal deltas, top moves by
        objective contribution, the convergence curve, and — when the
        episode progressed that far — its outcome and calibration.
        Raises KeyError when nothing matches (the server's 404),
        ValueError when the ledger is disabled (400)."""
        if self.ledger is None:
            raise ValueError(
                "decision ledger disabled (analyzer.ledger.enabled, "
                "analyzer.ledger.dir)"
            )
        if not trace_id and not decision_id:
            raise ValueError("explain needs trace_id= or proposal=")
        entry = self.ledger.find(decision_id=decision_id, trace_id=trace_id)
        if entry is None:
            raise KeyError(
                f"no ledger episode for "
                f"{'proposal ' + decision_id if decision_id else 'trace ' + (trace_id or '')}"
            )
        d = entry["decision"]
        goals = d.get("goals", {})
        names = goals.get("names", [])
        before = goals.get("violationsBefore", [])
        after = goals.get("violationsAfter", [])
        out = {
            "decisionId": d.get("id"),
            "traceId": d.get("trace_id", ""),
            "cluster": d.get("cluster", ""),
            "source": d.get("source", ""),
            "workClass": d.get("workClass", ""),
            "computedMs": d.get("ms"),
            "generation": d.get("generation"),
            "bucket": d.get("bucket"),
            "degraded": bool(d.get("degraded")),
            "goalDeltas": [
                {
                    "goal": n,
                    "before": b,
                    "after": a,
                    "delta": round(float(a) - float(b), 6),
                }
                for n, b, a in zip(names, before, after)
            ],
            "objective": {
                "before": goals.get("objectiveBefore"),
                "after": goals.get("objectiveAfter"),
            },
            "balancedness": {
                "before": goals.get("balancednessBefore"),
                "after": goals.get("balancednessAfter"),
            },
            "numReplicaMovements": d.get("numReplicaMovements"),
            "numLeaderMovements": d.get("numLeaderMovements"),
            "dataToMoveMB": d.get("dataToMoveMB"),
            "topMoves": d.get("moves", []),
            "convergence": d.get("convergence"),
            "predictedLoad": d.get("predictedLoad"),
            "outcome": entry.get("outcome"),
            "calibration": entry.get("calibration"),
        }
        return out

    def _valid_cache(self) -> OptimizerResult | None:
        with self._cache_lock:
            c = self._cache
            if c is None:
                return None
            expired = (
                time.monotonic() - c.computed_mono
            ) * 1000.0 > self._proposal_expiration_ms
            if c.source == "controller":
                # controller results refresh every window roll and are
                # stamped with the generation their live model REFLECTS;
                # an unrelated model build (detector round, cache-miss
                # request) bumping the monitor's load generation must not
                # sideline them — only a TOPOLOGY change (or expiry)
                # invalidates, and the controller re-flattens and
                # republishes on exactly that signal
                stale = (
                    c.model_generation.metadata_generation
                    != self.monitor.metadata.topology().generation
                )
            else:
                stale = c.model_generation != self.monitor.model_generation()
            if expired or stale:
                self._cache = None
                return None
            return c.result

    def invalidate_proposal_cache(self):
        with self._cache_lock:
            self._cache = None

    def proposal_age_s(self) -> float:
        """Age (seconds, monotonic) of the published/cached proposal; -1
        when none is cached.  The observable half of the scheduler's
        proposal-freshness SLO (`fleet.scheduler.freshness.slo.s`):
        exported as the `analyzer.proposal-age-seconds` gauge and the
        /fleet per-cluster `proposalAgeS` field."""
        with self._cache_lock:
            c = self._cache
        if c is None:
            return -1.0
        return round(time.monotonic() - c.computed_mono, 3)

    def _scheduled(self, work_class, fn, *, op: str):
        """Run one device-dispatching body under the shared device
        scheduler (no-op passthrough when fleet.scheduler.enabled is
        off).  The effective class is the dispatch site's default
        upgraded by any ambient pipeline tag — a self-healing fix
        pipeline tags itself URGENT (scheduler.tagged), so its inner
        proposals() dispatch acquires the slot urgently while its long
        executor phase holds nothing."""
        sched = self.scheduler
        if sched is None:
            return fn()
        from cruise_control_tpu.fleet.scheduler import effective_class

        return sched.run(
            effective_class(work_class), fn,
            cluster_id=self.cluster_id or "",
            op=op,
            freshness_slo_s=self._freshness_slo_s,
        )

    # ------------------------------------------------------------------
    # operations (reference servlet/handler/async/runnable/*)
    # ------------------------------------------------------------------

    def _execute(
        self,
        result: OptimizerResult,
        progress: OperationProgress,
        *,
        removed: set[int] | None = None,
        demoted: set[int] | None = None,
        extra_proposals: list[ExecutionProposal] | None = None,
        execution_overrides: dict | None = None,
    ) -> dict:
        """execution_overrides: per-request values for the concurrency caps
        and throttle (reference request-level parameters,
        servlet/parameters/ParameterUtils.java: concurrent_partition_
        movements_per_broker, concurrent_leader_movements,
        replication_throttle)."""
        progress.add_step(ExecutingProposals())
        if self.fence is not None:
            # fleet HA: only the lease holder may start an execution — a
            # degraded (read-only) facade fails the request up front with
            # FencedError instead of fencing mid-batch
            self.fence.check(op="execute")
        ov = execution_overrides or {}
        proposals = list(result.proposals) + list(extra_proposals or [])
        strategy = None
        if ov.get("replica_movement_strategies"):
            from cruise_control_tpu.executor.strategy import resolve_strategy_chain

            strategy = resolve_strategy_chain(
                ov["replica_movement_strategies"], allowed=self.allowed_strategies
            )
        self.executor.catalog = self.monitor.last_catalog
        did = None
        claimed = False
        if self.ledger is not None:
            # the decision about to be acted on: published results carry
            # their ledger id already; a custom (never-published) result
            # is recorded now so its outcome still has a join target
            did = self._ledger_decision_id(result)
            if did is None:
                did = self._record_decision(
                    result, source="request",
                    generation=self.monitor.model_generation(),
                    work_class="interactive",
                )
            if did is not None:
                # CLAIM, never overwrite: a concurrent second execution
                # attempt (about to be rejected with OngoingExecutionError)
                # must not clobber the in-flight execution's join slot —
                # that would orphan its real outcome forever and wedge
                # ledger rotation behind the stranded pending id
                with self._ledger_lock:
                    if self._executing_decision is None:
                        self._executing_decision = did
                        claimed = True
                if claimed:
                    self.ledger.begin_outcome(did)
        try:
            out = self.executor.execute_proposals(
                proposals, self._exec_options(ov),
                removed_brokers=removed, demoted_brokers=demoted,
                strategy=strategy,
            )
        except BaseException as e:
            # the executor's finish hook did not fire (setup failure or
            # mid-batch exception outside the fenced path): the episode's
            # outcome is the error — never leave a pending join forever.
            # Only the attempt that CLAIMED the slot may write it.
            still = False
            if claimed:
                with self._ledger_lock:
                    still = self._executing_decision == did
                    if still:
                        self._executing_decision = None
            if still and self.ledger is not None:
                try:
                    self.ledger.record_outcome(did, {
                        "error": repr(e), "completed": 0, "aborted": 0,
                        "dead": 0, "stopped": False, "fencedAbort": False,
                        "reaped": 0,
                    })
                except Exception:  # noqa: BLE001
                    pass
            raise
        if self.controller is not None:
            # executed proposals are the strongest signal the learned
            # move-acceptance prior gets (controller/prior.py)
            try:
                self.controller.observe_executed(proposals)
            except Exception:  # noqa: BLE001 — prior fitting is best-effort
                log.warning("controller prior execution feedback failed",
                            exc_info=True)
        self.invalidate_proposal_cache()
        return {
            "completed": out.completed,
            "aborted": out.aborted,
            "dead": out.dead,
            "stopped": out.stopped,
        }

    def _execution_eta(self, result, execution_overrides: dict | None = None) -> dict:
        """Per-phase execution ETA for an optimization result.

        Derived, transparently, from data-to-move over the caps THIS
        request's execution would run with: request execution overrides
        first, then any live mid-execution /admin override, then config:
          * interBroker/intraBroker: bytes over the aggregate replication
            bandwidth (per-broker throttle x brokers moving concurrently);
            null when no throttle applies (bandwidth unknown).
          * leadership: election batches x progress-check interval.
        The reference exposes only dataToMoveMB
        (executor/ExecutionProposal.java:106-229); the ETA is this
        framework's derived convenience, with its inputs echoed under
        "assumptions" so operators can audit it.
        """
        import math

        cfg = self.config
        ov = execution_overrides or {}
        req = (
            self.executor.requested_concurrency()
            if self.executor.has_ongoing_execution
            else {}
        )
        lead_cap = ov.get("concurrent_leader_movements") or req.get(
            "leadership", cfg.get("num.concurrent.leader.movements")
        )
        interval_s = req.get(
            "interval_s", cfg.get("execution.progress.check.interval.ms") / 1000.0
        )
        throttle = self._effective_throttle(ov)  # bytes/s per broker
        leads = result.num_leadership_moves
        # brokers shipping data concurrently.  The per-broker MOVE cap does
        # not appear in the formula on purpose: under a per-BROKER byte
        # throttle, splitting a broker's bandwidth across more concurrent
        # moves does not change its aggregate egress rate.
        ps = result.proposals
        if hasattr(ps, "source_brokers"):
            src_brokers = ps.source_brokers  # columnar, no materialization
        else:
            src_brokers = {
                b for p in ps if p.has_replica_action
                for b in p.old_replicas if b not in p.new_replicas
            }
        inter_s = intra_s = None
        if throttle:
            agg_bw = float(throttle) * max(1, len(src_brokers))
            inter_s = result.data_to_move * 1024.0 * 1024.0 / agg_bw
            intra_mb = (
                ps.intra_data_to_move
                if hasattr(ps, "intra_data_to_move")
                else sum(p.intra_broker_data_to_move for p in ps)
            )
            intra_s = intra_mb * 1024.0 * 1024.0 / agg_bw if intra_mb else 0.0
        lead_s = math.ceil(leads / max(1, lead_cap)) * interval_s if leads else 0.0
        return {
            "interBrokerSeconds": round(inter_s, 1) if inter_s is not None else None,
            "intraBrokerSeconds": round(intra_s, 1) if intra_s is not None else None,
            "leadershipSeconds": round(lead_s, 1),
            # only inputs the estimate actually uses, so operators can
            # audit it
            "assumptions": {
                "replicationThrottleBytesPerSec": throttle,
                "concurrentLeaderMovements": lead_cap,
                "progressCheckIntervalSeconds": interval_s,
                "sourceBrokers": len(src_brokers),
                "dataToMoveMB": result.data_to_move,
            },
        }

    def _effective_throttle(self, ov: dict | None = None) -> float | None:
        """Replication throttle for a request: request override, else the
        configured default; non-positive values (the conventional -1 =
        'disabled') normalize to None so neither the throttle helper nor
        the ETA ever sees a bogus negative rate."""
        v = (ov or {}).get("replication_throttle")
        if v is None:
            v = self.config.get("default.replication.throttle")
        return float(v) if v is not None and v > 0 else None

    def _exec_options(self, ov: dict | None = None) -> ExecutionOptions:
        """ExecutionOptions from config + per-request overrides — ONE
        builder for every execution path (rebalance/add/remove/demote/
        RF-change), so each honors the configured caps, timeouts and
        alerting floors."""
        ov = ov or {}

        def _ov(name, default_key):
            v = ov.get(name)
            return v if v is not None else self.config.get(default_key)

        return ExecutionOptions(
            concurrent_partition_movements_per_broker=_ov(
                "concurrent_partition_movements_per_broker",
                "num.concurrent.partition.movements.per.broker",
            ),
            concurrent_intra_broker_partition_movements=self.config.get(
                "num.concurrent.intra.broker.partition.movements"
            ),
            concurrent_leader_movements=_ov(
                "concurrent_leader_movements", "num.concurrent.leader.movements"
            ),
            max_num_cluster_movements=self.config.get("max.num.cluster.movements"),
            leader_movement_timeout_s=self.config.get("leader.movement.timeout.ms")
            / 1000.0,
            inter_broker_rate_alerting_mb_s=self.config.get(
                "inter.broker.replica.movement.rate.alerting.threshold"
            ),
            intra_broker_rate_alerting_mb_s=self.config.get(
                "intra.broker.replica.movement.rate.alerting.threshold"
            ),
            replication_throttle_bytes_per_s=self._effective_throttle(ov),
            progress_check_interval_s=self.config.get(
                "execution.progress.check.interval.ms"
            )
            / 1000.0,
            task_execution_alerting_s=self.config.get(
                "task.execution.alerting.threshold.ms"
            )
            / 1000.0,
            reaper_stuck_timeout_s=(
                self.config.get("executor.reaper.stuck.timeout.s")
                if self.config.get("executor.reaper.enabled")
                else None
            ),
            adaptive_enabled=self.config.get("executor.adaptive.enabled"),
            adaptive_min_concurrency=self.config.get("executor.adaptive.min"),
            adaptive_max_concurrency=self.config.get("executor.adaptive.max"),
            adaptive_backoff_factor=self.config.get(
                "executor.adaptive.backoff.factor"
            ),
            adaptive_recover_step=self.config.get(
                "executor.adaptive.recover.step"
            ),
            adaptive_urp_slack=self.config.get("executor.adaptive.urp.slack"),
            adaptive_stall_ticks=self.config.get("executor.adaptive.stall.ticks"),
        )

    def _build_options(
        self,
        state: ClusterState,
        *,
        destination_broker_ids: list[int] | None = None,
        excluded_topics_pattern: str | None = None,
        excluded_brokers_for_replica_move: list[int] | None = None,
        excluded_brokers_for_leadership: list[int] | None = None,
    ) -> OptimizationOptions:
        """Translate request parameters into array masks
        (reference OptimizationOptions construction in RunnableUtils).

        The config-level topics.excluded.from.partition.movement pattern is
        always merged in (reference AnalyzerConfig; per-request
        excluded_topics only ever widens the exclusion)."""
        import re

        bvalid = np.asarray(state.broker_valid)
        n_real = int(bvalid.sum())

        def _mask(ids, *, strict: bool):
            # strict (explicitly requested brokers, e.g. add_broker
            # destinations): an unknown id must FAIL the request — silently
            # dropping it would degrade add_broker into an unconstrained
            # full-cluster rebalance.  With shape bucketing the model's
            # broker axis carries padding rows past the real brokers, so
            # "known" means broker_valid, not merely in-range.  Non-strict
            # (history-derived exclusions): the recently-removed history
            # legitimately retains brokers the shrunken model no longer
            # has — drop those.
            unknown = [
                b for b in (ids or ())
                if not (0 <= b < state.shape.B and bvalid[b])
            ]
            if strict and unknown:
                raise ValueError(
                    f"broker ids {unknown} are not in the cluster model "
                    f"(brokers 0..{n_real - 1})"
                )
            ids = [b for b in (ids or ()) if 0 <= b < state.shape.B and bvalid[b]]
            if not ids:
                return None
            m = np.zeros(state.shape.B, bool)
            m[ids] = True
            return m

        excluded_topics = None
        patterns = [
            p
            for p in (
                self.config.get("topics.excluded.from.partition.movement"),
                excluded_topics_pattern,
            )
            if p
        ]
        if patterns and self.monitor.last_catalog is not None:
            rxs = [re.compile(p) for p in patterns]
            excluded_topics = np.array(
                [
                    any(rx.fullmatch(t) for rx in rxs)
                    for t in self.monitor.last_catalog.topics
                ],
                bool,
            )

        return OptimizationOptions(
            excluded_topics=excluded_topics,
            requested_destination_brokers=_mask(destination_broker_ids, strict=True),
            excluded_brokers_for_replica_move=_mask(
                excluded_brokers_for_replica_move, strict=False
            ),
            excluded_brokers_for_leadership=_mask(
                excluded_brokers_for_leadership, strict=False
            ),
        )

    def rebalance(
        self,
        progress: OperationProgress,
        *,
        dryrun: bool = True,
        goals: list[str] | None = None,
        destination_broker_ids: list[int] | None = None,
        excluded_topics_pattern: str | None = None,
        excluded_brokers_for_replica_move: list[int] | None = None,
        excluded_brokers_for_leadership: list[int] | None = None,
        rebalance_disk: bool = False,
        allow_capacity_estimation: bool = True,
        execution_overrides: dict | None = None,
    ) -> dict:
        """Reference RebalanceRunnable.workWithoutClusterModel:116.

        rebalance_disk selects the intra-broker (JBOD) goal chain and an
        engine whose candidates move replicas between a broker's own logdirs
        (reference rebalance_disk semantics; AnalyzerConfig.java:236
        default.intra.broker.goals)."""
        custom = bool(
            destination_broker_ids or excluded_topics_pattern or goals
            or rebalance_disk or excluded_brokers_for_replica_move
            or excluded_brokers_for_leadership
        )
        if custom:
            state = self._cluster_model(
                progress, allow_capacity_estimation=allow_capacity_estimation
            )
            options = self._build_options(
                state,
                destination_broker_ids=destination_broker_ids,
                excluded_topics_pattern=excluded_topics_pattern,
                excluded_brokers_for_replica_move=excluded_brokers_for_replica_move,
                excluded_brokers_for_leadership=excluded_brokers_for_leadership,
            )
            optimizer = self.optimizer
            if rebalance_disk:
                optimizer = self._make_optimizer(
                    goals or self.config.get("intra.broker.goals"),
                    intra_broker=True,
                )
            elif goals is not None:
                optimizer = self._make_optimizer(goals)
            progress.add_step(BatchedOptimization(optimizer.config.num_rounds))
            result = self._scheduled(
                WorkClass.INTERACTIVE,
                lambda: optimizer.optimize(state, options=options),
                op="rebalance",
            )
        else:
            result = self.proposals(
                progress, allow_capacity_estimation=allow_capacity_estimation
            )
        out = result.summary()
        out["estimatedExecutionTime"] = self._execution_eta(
            result, execution_overrides
        )
        out["proposals"] = [p.to_json() for p in result.proposals[:100]]
        if not dryrun:
            out["execution"] = self._execute(
                result, progress, execution_overrides=execution_overrides
            )
        return out

    def add_brokers(self, progress: OperationProgress, broker_ids: list[int], *,
                    dryrun: bool = True, execution_overrides: dict | None = None) -> dict:
        """Reference AddBrokersRunnable: only move replicas TO the new brokers."""
        return self.rebalance(
            progress, dryrun=dryrun, destination_broker_ids=broker_ids,
            execution_overrides=execution_overrides,
        )

    def remove_brokers(self, progress: OperationProgress, broker_ids: list[int], *,
                       dryrun: bool = True, execution_overrides: dict | None = None) -> dict:
        """Reference RemoveBrokersRunnable: evacuate the given brokers."""
        state = self._cluster_model(progress)
        state = _mark_brokers_dead(state, broker_ids)
        progress.add_step(BatchedOptimization(self.optimizer.config.num_rounds))
        dest_mask = np.ones(state.shape.B, bool)
        dest_mask[list(broker_ids)] = False
        options = OptimizationOptions(
            excluded_brokers_for_replica_move=~dest_mask,
            excluded_brokers_for_leadership=~dest_mask,
        )
        result = self._scheduled(
            WorkClass.INTERACTIVE,
            lambda: self.optimizer.optimize(state, options=options),
            op="remove_brokers",
        )
        out = result.summary()
        out["estimatedExecutionTime"] = self._execution_eta(
            result, execution_overrides
        )
        if not dryrun:
            out["execution"] = self._execute(
                result, progress, removed=set(broker_ids),
                execution_overrides=execution_overrides,
            )
        return out

    def demote_brokers(self, progress: OperationProgress, broker_ids: list[int], *,
                       dryrun: bool = True) -> dict:
        """Reference DemoteBrokerRunnable: move leadership (only) off brokers."""
        state = self._cluster_model(progress)
        proposals = _demotion_proposals(state, set(broker_ids), self.monitor.last_catalog)
        out = {
            "numLeaderMovements": len(proposals),
            "proposals": [p.to_json() for p in proposals[:100]],
        }
        if not dryrun and proposals:
            if self.fence is not None:
                self.fence.check(op="execute")
            self.executor.catalog = self.monitor.last_catalog
            progress.add_step(ExecutingProposals())
            r = self.executor.execute_proposals(
                proposals, self._exec_options(), demoted_brokers=set(broker_ids)
            )
            out["execution"] = {"completed": r.completed, "dead": r.dead}
        return out

    def fix_offline_replicas(self, progress: OperationProgress, *, dryrun: bool = True) -> dict:
        """Reference FixOfflineReplicasRunnable — the OfflineReplicaGoal
        drives evacuation of dead brokers/disks during a normal optimize."""
        result = self.proposals(progress, ignore_cache=True)
        out = result.summary()
        out["estimatedExecutionTime"] = self._execution_eta(result)
        out["proposals"] = [p.to_json() for p in result.proposals[:100]]
        if not dryrun:
            out["execution"] = self._execute(result, progress)
        return out

    def update_topic_replication_factor(
        self, progress: OperationProgress, topic_rf: dict[str, int], *, dryrun: bool = True
    ) -> dict:
        """Reference UpdateTopicConfigurationRunnable (RF change)."""
        state = self._cluster_model(progress)
        proposals = _rf_change_proposals(state, topic_rf, self.monitor.last_catalog)
        out = {
            "numProposals": len(proposals),
            "proposals": [p.to_json() for p in proposals[:100]],
        }
        if not dryrun and proposals:
            if self.fence is not None:
                self.fence.check(op="execute")
            self.executor.catalog = self.monitor.last_catalog
            progress.add_step(ExecutingProposals())
            r = self.executor.execute_proposals(proposals, self._exec_options())
            out["execution"] = {"completed": r.completed, "dead": r.dead}
        return out

    # ------------------------------------------------------------------
    # scenario planner (read-only what-if analysis; planner/)
    # ------------------------------------------------------------------

    def simulate(
        self,
        progress: OperationProgress,
        scenarios,
        *,
        optimize: bool | None = None,
        allow_capacity_estimation: bool = True,
    ) -> dict:
        """Batch-evaluate what-if scenarios against the live model
        (POST /simulate).  `scenarios`: planner.scenario.Scenario list (the
        parameter layer parses the JSON).  Never touches the cluster."""
        from cruise_control_tpu.planner.scenario import Scenario

        t0 = time.monotonic()
        if optimize is None:
            optimize = self.config.get("planner.simulate.optimize.default")
        scenarios = list(scenarios)
        if len(scenarios) > self.config.get("planner.max.scenarios"):
            raise ValueError(
                f"{len(scenarios)} scenarios exceed planner.max.scenarios="
                f"{self.config.get('planner.max.scenarios')}"
            )
        state = self._cluster_model(
            progress, allow_capacity_estimation=allow_capacity_estimation
        )
        if optimize:
            progress.add_step(
                BatchedOptimization(self.optimizer.config.num_rounds)
            )
        with self.sensors.timer("planner.simulate-timer").time(), self.tracer.span(
            "planner.simulate",
            component="planner",
            scenarios=len(scenarios),
            optimize=bool(optimize),
        ) as sp:
            # the identity scenario rides the SAME batch so "vs today" in
            # the response cannot drift from the mutated states' scoring;
            # its optimize flag is False — the response never serializes a
            # baseline fix, so annealing it would be a wasted full anneal
            outcomes = self._scheduled(
                WorkClass.INTERACTIVE,
                lambda: self.scenario_evaluator.evaluate(
                    state,
                    [Scenario(name="__baseline__")] + scenarios,
                    self.monitor.last_catalog,
                    optimize=[False] + [bool(optimize)] * len(scenarios),
                    bucket=self.bucket_policy,
                ),
                op="simulate",
            )
            sp.set(degraded=any(o.degraded for o in outcomes))
        base, rest = outcomes[0], outcomes[1:]
        return {
            "scenarios": [o.to_json() for o in rest],
            "baseline": {
                "objective": base.objective,
                "violatedGoals": list(base.violated_goals),
                "balancedness": base.balancedness,
                "brokersAlive": base.brokers_alive,
            },
            "degraded": any(o.degraded for o in outcomes),
            "wallSeconds": round(time.monotonic() - t0, 3),
        }

    def _forecast_scenario(self, horizon_ms: int):
        """Load Scenario at `horizon_ms` from the partition aggregator's
        windowed history; None when too little history exists to trend."""
        from cruise_control_tpu.planner.forecast import LoadForecaster

        try:
            history = self.monitor.partition_aggregator.history_snapshot()
        except ValueError:
            return None
        forecaster = LoadForecaster(
            method=self.config.get("planner.forecast.method"),
            min_windows=self.config.get("planner.forecast.min.windows"),
            max_factor=self.config.get("planner.forecast.max.factor"),
        )
        catalog = self.monitor.last_catalog
        trends = forecaster.fit(
            history,
            self.monitor.partition_aggregator.metric_def,
            catalog.topic_names_by_id() if catalog is not None else None,
        )
        if not trends:
            return None
        return forecaster.scenario_at(
            trends, horizon_ms, history.window_ms, name=f"forecast+{horizon_ms}ms"
        )

    def rightsize(
        self,
        progress: OperationProgress,
        *,
        horizon_ms: int | None = None,
        min_brokers: int | None = None,
        max_broker_factor: float | None = None,
        allow_capacity_estimation: bool = True,
    ) -> dict:
        """Minimum brokers satisfying all hard goals (GET /rightsize) —
        Cruise Control's ProvisionStatus, answered by a monotone what-if
        search.  With `horizon_ms`, the verdict is ALSO computed under the
        forecast load at that horizon and reported under `forecast`."""
        state = self._cluster_model(
            progress, allow_capacity_estimation=allow_capacity_estimation
        )
        progress.add_step(BatchedOptimization(self.optimizer.config.num_rounds))
        rs = self.rightsizer
        if min_brokers is not None or max_broker_factor is not None:
            from cruise_control_tpu.planner.rightsizer import Rightsizer

            rs = Rightsizer(
                self.scenario_evaluator,
                min_brokers=min_brokers if min_brokers is not None else rs.min_brokers,
                max_broker_factor=(
                    max_broker_factor
                    if max_broker_factor is not None
                    else rs.max_broker_factor
                ),
                bucket=self.bucket_policy,
                sensors=self.sensors,
            )
        max_anneals = self.config.get("planner.rightsize.max.anneals")
        catalog = self.monitor.last_catalog
        with self.tracer.span("planner.rightsize", component="planner") as sp:
            out = self._scheduled(
                WorkClass.INTERACTIVE,
                lambda: rs.rightsize(state, catalog, max_anneals=max_anneals),
                op="rightsize",
            )
            sp.set(
                status=out.get("provisionStatus"),
                anneals=out.get("annealsRun"),
                min_brokers=out.get("minBrokers"),
            )
        # trend outlook at the CONFIGURED horizons (planner.forecast.
        # horizons.ms): the fitted per-topic scale factors, no extra
        # anneals — the full forecast VERDICT still needs an explicit
        # horizon_ms (a search per horizon is an operator's choice to pay)
        outlook = []
        for h in self.config.get("planner.forecast.horizons.ms"):
            sc = self._forecast_scenario(int(h))
            if sc is not None:
                outlook.append({"horizonMs": int(h), "scenario": sc.to_json()})
        out["forecastOutlook"] = outlook
        if horizon_ms is not None:
            load_sc = self._forecast_scenario(horizon_ms)
            if load_sc is None:
                out["forecast"] = {
                    "horizonMs": horizon_ms,
                    "error": "not enough windowed history to fit a trend",
                }
            else:
                fc = self._scheduled(
                    WorkClass.INTERACTIVE,
                    lambda: rs.rightsize(
                        state, catalog, load_scenario=load_sc,
                        max_anneals=max_anneals,
                    ),
                    op="rightsize-forecast",
                )
                fc["horizonMs"] = horizon_ms
                out["forecast"] = fc
        return out

    def stop_proposal_execution(self, *, force: bool = False) -> dict:
        self.executor.stop_execution(force=force)
        return {"message": "execution stop requested", "force": force}

    # ------------------------------------------------------------------
    # state (reference STATE endpoint aggregating all substates)
    # ------------------------------------------------------------------

    def state(self, substates: list[str] | None = None) -> dict:
        substates = [
            s.lower()
            for s in (
                substates
                or ["monitor", "executor", "analyzer", "controller",
                    "anomaly_detector", "sensors"]
            )
        ]
        out: dict = {"version": 1}
        if "sensors" in substates:
            # reference publishes these via JMX (KafkaCruiseControlApp.java:39-41,
            # docs/wiki/User Guide/Sensors.md); here they ride the /state JSON
            out["Sensors"] = self.sensors.snapshot()
        if "monitor" in substates:
            out["MonitorState"] = self.monitor.monitor_state()
            runner = getattr(self, "task_runner", None)
            if runner is not None:
                out["MonitorState"]["trainingState"] = runner.regression.state()
                out["MonitorState"]["bootstrapProgressPct"] = runner.state()[
                    "bootstrapProgressPct"
                ]
        if "executor" in substates:
            out["ExecutorState"] = self.executor.executor_state()
        if "analyzer" in substates:
            with self._cache_lock:
                cache = self._cache
            out["AnalyzerState"] = {
                "isProposalReady": cache is not None,
                "readyGoals": self.chain.names() if cache is not None else [],
                "goalReadiness": self.chain.names(),
                # which pipeline filled the cached proposal: "optimizer"
                # (request/precompute) or "controller" (streaming publish)
                "proposalSource": cache.source if cache is not None else None,
                # degraded-serving surface (supervised optimizer runtime):
                # degraded=true means proposals are currently CPU-greedy
                # because the device breaker is not closed
                "degraded": self.supervisor is not None
                and self.supervisor.is_degraded,
                # per-bucket cumulative cold-start bill (compile + first
                # run); the /metrics collector mirrors coldWallSeconds
                "compileAttribution": self.optimizer.compile_attribution(),
            }
            if self.supervisor is not None:
                # includes deviceHealth: latest per-device probe verdicts
                # from mesh attribution fan-outs (which chip, not just
                # which slice)
                out["AnalyzerState"]["supervisor"] = self.supervisor.state_json()
            mesh_ft = getattr(self.optimizer, "_mesh_ft", None)
            if mesh_ft is not None:
                out["AnalyzerState"]["meshFt"] = mesh_ft.state_json()
            if self.ledger is not None:
                # decision ledger + predicted-vs-measured calibration
                # (analyzer/ledger.py; full episodes on GET /ledger)
                out["AnalyzerState"]["ledger"] = self.ledger.state_json()
                out["AnalyzerState"]["calibration"] = self.calibration_state()
        if "controller" in substates and self.controller is not None:
            out["ControllerState"] = self.controller.state_json()
        if "anomaly_detector" in substates:
            out["AnomalyDetectorState"] = self.anomaly_detector.detector_state()
        return out


class SelfHealingAdapter:
    """detector.SelfHealingActions implementation over the facade — anomaly
    fixes run through the exact user-operation paths (reference: anomaly fix
    constructors of the runnables)."""

    def __init__(self, cc: CruiseControl):
        self.cc = cc
        #: last non-busy fix failure: surfaced by detector_state() so an
        #: operator reading /state sees WHY self-healing is not healing
        self.last_fix_failure: dict | None = None

    @property
    def fix_failure_info(self) -> dict | None:
        return self.last_fix_failure

    def _guarded(self, fn, *, op: str) -> bool:
        """Run one self-healing fix; False means it did not start.

        Busy executor is the EXPECTED no (the detector re-checks later)
        and stays silent.  Everything else used to be swallowed
        indistinguishably — now it is logged with the traceback, counted
        (`self-healing.fix-failed`), and kept as last-failure info.

        Under the device scheduler every fix pipeline is tagged URGENT:
        a broker-failure / EXECUTION_STUCK / lease-takeover re-anneal's
        engine dispatch preempts whatever background slice holds the
        device (never shed, never 429'd), while the pipeline's long
        executor phase — which dispatches nothing — holds no slot."""
        try:
            if self.cc.scheduler is not None:
                from cruise_control_tpu.fleet.scheduler import tagged

                with tagged(WorkClass.URGENT):
                    fn()
            else:
                fn()
            return True
        except OngoingExecutionError:
            return False
        except Exception as e:  # noqa: BLE001 — fix failure is reported, not fatal
            self.cc.sensors.counter("self-healing.fix-failed").inc()
            self.last_fix_failure = {
                "operation": op,
                "error": repr(e),
                "ms": int(time.time() * 1000),
            }
            log.warning("self-healing fix %s failed to start", op, exc_info=True)
            return False

    def _healing_kwargs(self) -> dict:
        """Self-healing runs with its own goal list and keeps replicas and
        leadership off recently removed/demoted brokers (reference
        AnomalyDetectorConfig self.healing.goals +
        self.healing.exclude.recently.{removed,demoted}.brokers)."""
        cfg = self.cc.config
        kwargs: dict = {}
        healing_goals = cfg.get("self.healing.goals")
        if healing_goals:
            kwargs["goals"] = healing_goals
        ex = self.cc.executor
        if cfg.get("self.healing.exclude.recently.removed.brokers"):
            removed = sorted(ex.removed_brokers)
            if removed:
                kwargs["excluded_brokers_for_replica_move"] = removed
        if cfg.get("self.healing.exclude.recently.demoted.brokers"):
            demoted = sorted(ex.demoted_brokers)
            if demoted:
                kwargs["excluded_brokers_for_leadership"] = demoted
        return kwargs

    def rebalance(self, reason: str) -> bool:
        return self._guarded(
            lambda: self.cc.rebalance(
                OperationProgress(), dryrun=False, **self._healing_kwargs()
            ),
            op="rebalance",
        )

    def remove_brokers(self, broker_ids, reason: str) -> bool:
        # destructive-removal guard (reference AnomalyDetectorConfig
        # fixable.failed.broker.{count,percentage}.threshold:138-147): when
        # too much of the cluster is implicated the anomaly is not fixable
        # by removal and a human must intervene
        cfg = self.cc.config
        ids = list(broker_ids)
        if len(ids) > cfg.get("fixable.failed.broker.count.threshold"):
            return False
        try:
            total = len(self.cc.admin.topology().brokers)
        except Exception:  # noqa: BLE001 — unknown size: fall back to count gate
            total = 0
        if total and len(ids) / total > cfg.get(
            "fixable.failed.broker.percentage.threshold"
        ):
            return False
        return self._guarded(
            lambda: self.cc.remove_brokers(OperationProgress(), ids, dryrun=False),
            op="remove_brokers",
        )

    def demote_brokers(self, broker_ids, reason: str) -> bool:
        return self._guarded(
            lambda: self.cc.demote_brokers(OperationProgress(), list(broker_ids), dryrun=False),
            op="demote_brokers",
        )

    def fix_offline_replicas(self, reason: str) -> bool:
        return self._guarded(
            lambda: self.cc.fix_offline_replicas(OperationProgress(), dryrun=False),
            op="fix_offline_replicas",
        )

    def fix_topic_replication_factor(self, topics, target_rf: int, reason: str) -> bool:
        return self._guarded(
            lambda: self.cc.update_topic_replication_factor(
                OperationProgress(), {t: target_rf for t in topics}, dryrun=False
            ),
            op="fix_topic_replication_factor",
        )

    @property
    def is_busy(self) -> bool:
        return self.cc.executor.has_ongoing_execution


# ----------------------------------------------------------------------
# host-side proposal builders
# ----------------------------------------------------------------------


def _mark_brokers_dead(state: ClusterState, broker_ids: list[int]) -> ClusterState:
    import jax.numpy as jnp

    alive = np.asarray(state.broker_alive).copy()
    alive[list(broker_ids)] = False
    offline = np.asarray(state.replica_offline) | np.isin(
        np.asarray(state.replica_broker), list(broker_ids)
    )
    return dataclasses.replace(
        state,
        broker_alive=jnp.asarray(alive),
        replica_offline=jnp.asarray(offline & np.asarray(state.replica_valid)),
    )


def _demotion_proposals(state: ClusterState, demoted: set[int], catalog) -> list[ExecutionProposal]:
    """Leadership-only proposals moving leaders off demoted brokers
    (reference DemoteBrokerRunnable + PreferredLeaderElectionGoal)."""
    valid = np.asarray(state.replica_valid)
    part = np.asarray(state.replica_partition)
    brk = np.asarray(state.replica_broker)
    lead = np.asarray(state.replica_is_leader)
    pos = np.asarray(state.replica_pos)
    alive = np.asarray(state.broker_alive)
    topic = np.asarray(state.replica_topic)
    proposals = []
    for p in np.unique(part[valid & lead & np.isin(brk, list(demoted))]):
        rows = np.nonzero(valid & (part == p))[0]
        rows = rows[np.argsort(pos[rows])]
        old_leader = int(brk[rows[lead[rows]]][0])
        candidates = [
            int(brk[r]) for r in rows if int(brk[r]) not in demoted and alive[brk[r]]
        ]
        if not candidates:
            continue
        new_leader = candidates[0]
        replicas = tuple(int(brk[r]) for r in rows)
        proposals.append(
            ExecutionProposal(
                partition=int(p),
                topic=int(topic[rows[0]]),
                old_leader=old_leader,
                new_leader=new_leader,
                old_replicas=replicas,
                new_replicas=replicas,
            )
        )
    return proposals


def _rf_change_proposals(
    state: ClusterState, topic_rf: dict[str, int], catalog
) -> list[ExecutionProposal]:
    """Replication-factor change proposals: grow rack-aware onto the least
    loaded brokers, shrink by dropping the most loaded non-leader replicas
    (reference TopicReplicationFactorAnomalyFinder fix semantics)."""
    from cruise_control_tpu.common.resources import Resource

    valid = np.asarray(state.replica_valid)
    part = np.asarray(state.replica_partition)
    brk = np.asarray(state.replica_broker)
    lead = np.asarray(state.replica_is_leader)
    topic = np.asarray(state.replica_topic)
    rack = np.asarray(state.broker_rack)
    alive = np.asarray(state.broker_alive) & np.asarray(state.broker_valid)
    load = np.zeros(state.shape.B)
    eff = np.asarray(state.replica_load_leader)[:, Resource.DISK]
    for r in np.nonzero(valid)[0]:
        load[brk[r]] += eff[r]

    name_to_tid = {name: i for i, name in enumerate(catalog.topics)} if catalog else {}
    proposals = []
    for tname, target in topic_rf.items():
        tid = name_to_tid.get(tname)
        if tid is None:
            continue
        for p in np.unique(part[valid & (topic == tid)]):
            rows = np.nonzero(valid & (part == p))[0]
            replicas = [int(brk[r]) for r in rows]
            leader_rows = rows[lead[rows]]
            leader = int(brk[leader_rows[0]]) if leader_rows.size else replicas[0]
            new = list(replicas)
            if len(new) < target:
                used_racks = {int(rack[b]) for b in new}
                candidates = sorted(
                    (b for b in np.nonzero(alive)[0] if int(b) not in new),
                    key=lambda b: (int(rack[b]) in used_racks, load[b]),
                )
                for b in candidates[: target - len(new)]:
                    new.append(int(b))
                    used_racks.add(int(rack[b]))
            elif len(new) > target:
                droppable = sorted(
                    (b for b in new if b != leader), key=lambda b: -load[b]
                )
                for b in droppable[: len(new) - target]:
                    new.remove(b)
            if set(new) != set(replicas):
                proposals.append(
                    ExecutionProposal(
                        partition=int(p),
                        topic=tid,
                        old_leader=leader,
                        new_leader=leader,
                        old_replicas=tuple(replicas),
                        new_replicas=tuple([leader] + [b for b in new if b != leader]),
                    )
                )
    return proposals
