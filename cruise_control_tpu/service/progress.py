"""Operation progress tracking for async operations.

Reference: async/progress/OperationProgress.java + OperationStep.java and
the concrete steps (Pending, RetrievingMetrics, WaitingForClusterModel,
GeneratingClusterModel with % complete, OptimizationForGoal,
WaitingForOngoingExecutionToStop).  Surfaced through 202 responses while
an operation runs (SURVEY §5 tracing).
"""

from __future__ import annotations

import threading
import time


class OperationStep:
    def __init__(self, description: str):
        self._description = description
        self._start = time.time()
        self._done_pct = 0.0

    @property
    def description(self) -> str:
        return self._description

    def completeness(self) -> float:
        return self._done_pct

    def set_completeness(self, pct: float):
        self._done_pct = min(1.0, max(0.0, pct))

    def done(self):
        self._done_pct = 1.0


class Pending(OperationStep):
    def __init__(self):
        super().__init__("OPERATION IS PENDING")


class RetrievingMetrics(OperationStep):
    def __init__(self):
        super().__init__("RETRIEVING METRICS")


class WaitingForClusterModel(OperationStep):
    def __init__(self):
        super().__init__("WAITING FOR CLUSTER MODEL")


class GeneratingClusterModel(OperationStep):
    def __init__(self):
        super().__init__("GENERATING CLUSTER MODEL")


class OptimizationForGoal(OperationStep):
    def __init__(self, goal_name: str):
        super().__init__(f"OPTIMIZING {goal_name}")


class BatchedOptimization(OperationStep):
    """TPU-specific: one step for the whole batched goal chain."""

    def __init__(self, round_count: int):
        super().__init__(f"BATCHED OPTIMIZATION ({round_count} ROUNDS)")


class WaitingForOngoingExecutionToStop(OperationStep):
    def __init__(self):
        super().__init__("WAITING FOR ONGOING EXECUTION TO STOP")


class ExecutingProposals(OperationStep):
    def __init__(self):
        super().__init__("EXECUTING PROPOSALS")


class OperationProgress:
    def __init__(self):
        self._steps: list[OperationStep] = []
        self._lock = threading.Lock()

    def add_step(self, step: OperationStep) -> OperationStep:
        with self._lock:
            if self._steps:
                self._steps[-1].done()
            self._steps.append(step)
        return step

    def refer_to(self, other: "OperationProgress"):
        """Share another operation's progress (reference
        OperationProgress.refer — used when ops join a cached computation)."""
        with self._lock:
            self._steps = other._steps

    def to_json(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "step": s.description,
                    "completionPercentage": round(100.0 * s.completeness(), 1),
                    "timeInMs": int((time.time() - s._start) * 1000),
                }
                for s in self._steps
            ]
