"""Declared request parameters per endpoint + pluggable override maps.

Reference: config/constants/CruiseControlParametersConfig.java:1 and
CruiseControlRequestConfig.java:1 — every endpoint maps to a parameters
class (which declares and validates its query parameters) and a request
class (which executes it), BOTH overridable per endpoint through config
({endpoint}.parameters.class / {endpoint}.request.class).

Here each endpoint declares its parameter set as data; `parse` validates
types and REJECTS unknown parameters (the reference 400s unrecognized
params — silently ignoring a typo like `dry_run` executes a rebalance the
caller believed was a dry run).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable


class ParameterError(ValueError):
    pass


def _bool(s: str):
    if s.lower() in ("true", "1", "yes"):
        return True
    if s.lower() in ("false", "0", "no"):
        return False
    raise ParameterError(f"expected boolean, got {s!r}")


def _int(s: str):
    return int(s)


def _float(s: str):
    return float(s)


def _int_list(s: str):
    return [int(x) for x in s.split(",") if x != ""]


def _str_list(s: str):
    return [x for x in s.split(",") if x]


def _regex(s: str):
    re.compile(s)  # validation only; handlers re-compile as needed
    return s


@dataclasses.dataclass(frozen=True)
class Param:
    name: str
    parse: Callable[[str], object]
    doc: str = ""


@dataclasses.dataclass(frozen=True)
class EndpointParameters:
    """Declared parameter set for one endpoint (the reference's
    *Parameters class).  Subclass / replace via {endpoint}.parameters.class
    to accept custom parameters."""

    endpoint: str
    params: tuple

    def parse(self, raw: dict) -> dict:
        """raw: urllib parse_qs dict.  Validates every value; unknown
        parameter names are rejected."""
        by_name = {p.name: p for p in self.params}
        out = {}
        for name, values in raw.items():
            p = by_name.get(name)
            if p is None:
                raise ParameterError(
                    f"unknown parameter {name!r} for {self.endpoint} "
                    f"(accepted: {sorted(by_name)})"
                )
            try:
                out[name] = p.parse(values[0])
            except ParameterError:
                raise
            except (ValueError, TypeError) as e:
                raise ParameterError(f"bad {name}: {e}") from e
        return out


def _min1_int(s: str):
    v = int(s)
    if not v >= 1:  # also rejects NaN-shaped junk; a 0 cap stalls the executor
        raise ParameterError(f"must be >= 1, got {v}")
    return v


def _min1_float(s: str):
    v = float(s)
    if not v >= 1:
        raise ParameterError(f"must be >= 1, got {v}")
    return v


def _scenario_list(s: str):
    """JSON scenario list for /simulate — validated structurally HERE so a
    malformed scenario 400s before a cluster model is built for it."""
    import json

    from cruise_control_tpu.planner.scenario import Scenario

    try:
        raw = json.loads(s)
    except json.JSONDecodeError as e:
        raise ParameterError(f"scenarios is not valid JSON: {e}") from e
    if isinstance(raw, dict):
        raw = [raw]
    if not isinstance(raw, list) or not raw:
        raise ParameterError("scenarios must be a non-empty JSON list of objects")
    try:
        return [Scenario.from_json(d) for d in raw]
    except (TypeError, ValueError, KeyError) as e:
        raise ParameterError(f"bad scenario: {e}") from e


# bounds MATCH server._parse_execution_overrides — the declared parser is
# what custom request classes consume, so the two layers must agree
_STRATEGIES = Param(
    "replica_movement_strategies", _str_list,
    "ordered strategy names from the replica.movement.strategies pool",
)
_EXECUTION = (
    Param("concurrent_partition_movements_per_broker", _min1_int),
    Param("concurrent_leader_movements", _min1_int),
    Param("replication_throttle", _min1_float),
    _STRATEGIES,
)
_DRYRUN = Param("dryrun", _bool)
_REVIEW_ID = Param("review_id", _int, "two-step verification approval id")
_REASON = Param("reason", str)
#: fleet routing: every endpoint accepts `cluster` (appended below, like
#: `reason`).  Single-cluster deployments reject any value (no fleet
#: configured); in fleet mode cluster-scoped endpoints require it and the
#: fleet-global ones (fleet/metrics/trace/user_tasks/review_board/review)
#: treat it as an optional filter
_CLUSTER = Param("cluster", str, "fleet cluster id the request targets")

#: the builtin parameter map (reference CruiseControlParametersConfig's
#: DEFAULT_* constants tree).  Every POST endpoint accepts `reason`
#: (enforced when request.reason.required is on; feeds the audit log).
_RAW_PARAMETERS: dict[str, tuple] = {
        "bootstrap": (Param("start", _int), Param("end", _int),
                      Param("clearmetrics", _bool)),
        "train": (Param("start", _int), Param("end", _int)),
        "load": (Param("allow_capacity_estimation", _bool),),
        "partition_load": (Param("resource", str), Param("entries", _int),
                           Param("allow_capacity_estimation", _bool)),
        "proposals": (Param("ignore_proposal_cache", _bool),
                      Param("allow_capacity_estimation", _bool)),
        "state": (Param("substates", _str_list),),
        "kafka_cluster_state": (),
        "user_tasks": (Param("user_task_ids", _str_list),
                       Param("client_ids", _str_list),
                       Param("endpoints", _str_list),
                       Param("types", _str_list),
                       Param("clusters", _str_list),
                       Param("fetch_completed_task", _bool)),
        "review_board": (Param("review_ids", _int_list),),
        "add_broker": (Param("brokerid", _int_list), _DRYRUN, _REVIEW_ID,
                       *_EXECUTION),
        "remove_broker": (Param("brokerid", _int_list), _DRYRUN, _REVIEW_ID,
                          *_EXECUTION),
        "fix_offline_replicas": (_DRYRUN, _REVIEW_ID, *_EXECUTION),
        "rebalance": (_DRYRUN, Param("goals", _str_list),
                      Param("destination_broker_ids", _int_list),
                      Param("excluded_topics", _regex),
                      Param("rebalance_disk", _bool),
                      Param("allow_capacity_estimation", _bool),
                      Param("exclude_recently_removed_brokers", _bool),
                      Param("exclude_recently_demoted_brokers", _bool),
                      _REVIEW_ID, *_EXECUTION),
        "stop_proposal_execution": (Param("force_stop", _bool), _REVIEW_ID),
        "pause_sampling": (_REASON, _REVIEW_ID),
        "resume_sampling": (_REASON, _REVIEW_ID),
        "demote_broker": (Param("brokerid", _int_list), _DRYRUN, _REVIEW_ID),
        "admin": (Param("enable_self_healing_for", _str_list),
                  Param("disable_self_healing_for", _str_list),
                  Param("drop_recently_removed_brokers", _int_list),
                  Param("drop_recently_demoted_brokers", _int_list),
                  # mid-execution concurrency change (reference
                  # AdminParameters.java:31-38)
                  Param("concurrent_partition_movements_per_broker", _min1_int),
                  Param("concurrent_intra_broker_partition_movements", _min1_int),
                  Param("concurrent_leader_movements", _min1_int),
                  Param("execution_progress_check_interval_ms", _min1_int),
                  _REVIEW_ID),
        "review": (Param("approve", _int_list), Param("discard", _int_list),
                   _REASON),
        "topic_configuration": (Param("topic", str),
                                Param("replication_factor", _int), _DRYRUN,
                                _REVIEW_ID),
        # --- scenario planner (read-only what-if analysis) ---
        "simulate": (Param("scenarios", _scenario_list,
                           "JSON list of scenario objects (see docs/rest-api.md)"),
                     Param("optimize", _bool,
                           "also run the full anneal per scenario (projected "
                           "post-fix view; slower)"),
                     Param("allow_capacity_estimation", _bool),
                     _REVIEW_ID),
        "rightsize": (Param("horizon_ms", _min1_int,
                            "also rightsize at the load forecast this far out"),
                      Param("min_brokers", _min1_int),
                      Param("max_broker_factor", _min1_float),
                      Param("allow_capacity_estimation", _bool)),
        # --- observability (flight recorder + Prometheus exposition) ---
        "trace": (Param("id", str,
                        "trace id to replay (from _traceId of an async "
                        "response); omit to list recent root traces"),
                  Param("limit", _min1_int,
                        "max recent traces listed without id (default 50)"),
                  Param("blackbox", _bool,
                        "also embed the black-box dispatch spool's tail + "
                        "in-flight dispatches (common/blackbox.py) — the "
                        "durable twin of the in-memory trace store")),
        "metrics": (Param("format", str,
                          "'openmetrics' renders the OpenMetrics flavor "
                          "with per-bucket trace-id exemplars (also "
                          "negotiated via the Accept header)"),),
        "slo": (),
        # --- decision ledger (analyzer/ledger.py) ---
        "explain": (Param("trace_id", str,
                          "flight-recorder trace id of the decision to "
                          "explain (the _traceId of the async response "
                          "that computed it)"),
                    Param("proposal", str,
                          "ledger decision id to explain (from GET "
                          "/ledger or a decision record)")),
        "ledger": (Param("limit", _min1_int,
                         "max joined decision→outcome→calibration "
                         "episodes returned, newest first (default 50)"),),
        # --- fleet controller (whole-instance rollup) ---
        "fleet": (Param("score", _bool,
                        "also batch-score every cluster's current placement "
                        "on the shared goal chain (same-bucket clusters ride "
                        "one device dispatch); slower"),),
}

from cruise_control_tpu.config.endpoints import (  # noqa: E402
    ALL_ENDPOINTS,
    POST_ENDPOINTS,
)

def _with_cross_cutting(ep: str, params: tuple) -> tuple:
    """Append the cross-cutting params every endpoint accepts: `reason` on
    POSTs (audit trail) and `cluster` everywhere (fleet routing)."""
    if ep in POST_ENDPOINTS and not any(p.name == "reason" for p in params):
        params = (*params, _REASON)
    return (*params, _CLUSTER)


ENDPOINT_PARAMETERS: dict[str, EndpointParameters] = {
    ep: EndpointParameters(ep, _with_cross_cutting(ep, params))
    for ep, params in _RAW_PARAMETERS.items()
}


# the canonical endpoint list and this registry must agree — a new
# endpoint without declared parameters would silently skip validation

assert set(ENDPOINT_PARAMETERS) == set(ALL_ENDPOINTS), (
    set(ENDPOINT_PARAMETERS) ^ set(ALL_ENDPOINTS)
)


def build_override_maps(config) -> tuple[dict, dict]:
    """(parameter parsers, request handlers) per endpoint from config.

    {endpoint}.parameters.class (T.CLASS, resolved by the config layer)
    is called with (endpoint, builtin: EndpointParameters) and must expose
    .parse(raw) — the builtin instance is passed so overrides can extend
    rather than re-declare.  {endpoint}.request.class is called as
    (app, endpoint, parsed_params) -> (status, payload).  Unset keys keep
    the builtins.
    """
    from cruise_control_tpu.config.endpoints import reference_key_name

    parsers: dict[str, object] = dict(ENDPOINT_PARAMETERS)
    handlers: dict[str, object] = {}
    for ep in ENDPOINT_PARAMETERS:
        ref = reference_key_name(ep)

        def _get(kind: str):
            # our spelling wins; the reference's dotted spelling is accepted
            v = config.get(f"{ep}.{kind}.class")
            if v is None and ref != ep:
                v = config.get(f"{ref}.{kind}.class")
            return v

        p_cls = _get("parameters")
        if p_cls:
            parsers[ep] = p_cls(ep, ENDPOINT_PARAMETERS[ep])
        r_cls = _get("request")
        if r_cls:
            handlers[ep] = r_cls
    return parsers, handlers
