"""REST API server — the reference's 20-endpoint servlet surface.

Reference: servlet/KafkaCruiseControlServlet.java:96-130 (doGetOrPost
dispatch), CruiseControlEndPoint.java:16-37 (endpoints: 9 GET — BOOTSTRAP,
TRAIN, LOAD, PARTITION_LOAD, PROPOSALS, STATE, KAFKA_CLUSTER_STATE,
USER_TASKS, REVIEW_BOARD; 11 POST — ADD_BROKER, REMOVE_BROKER,
FIX_OFFLINE_REPLICAS, REBALANCE, STOP_PROPOSAL_EXECUTION, PAUSE_SAMPLING,
RESUME_SAMPLING, DEMOTE_BROKER, ADMIN, REVIEW, TOPIC_CONFIGURATION),
parameter parsing (servlet/parameters/ParameterUtils.java), the async
202-with-progress pattern, and basic-auth security
(servlet/security/BasicSecurityProvider.java).

Built on the stdlib threading HTTP server — the service is control-plane
I/O; no framework dependency is warranted.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from cruise_control_tpu.common.resources import RESOURCE_NAMES, Resource
from cruise_control_tpu.service.facade import CruiseControl
from cruise_control_tpu.service.parameters import ParameterError, build_override_maps
from cruise_control_tpu.service.purgatory import Purgatory, PurgatoryFullError
from cruise_control_tpu.fleet.scheduler import SchedulerOverloadError
from cruise_control_tpu.service.tasks import (
    USER_TASK_ID_HEADER,
    TenantOverloadError,
    UserTaskManager,
)

from cruise_control_tpu.config.endpoints import GET_ENDPOINTS, POST_ENDPOINTS


class BadRequest(ValueError):
    pass


class RawResponse:
    """A non-JSON endpoint body (the Prometheus exposition): the handler
    returns one of these and `_send` writes it verbatim under its own
    Content-Type instead of JSON-encoding it."""

    def __init__(self, body: str, content_type: str):
        self.body = body
        self.content_type = content_type


#: operation audit trail (reference OPERATION_LOGGER, executor/Executor.java:74,
#: detector/AnomalyDetector.java:56): one line per REST operation with the
#: authenticated principal and outcome.  Route to a file via standard logging
#: config (`logging.getLogger("cruisecontrol.operations")`).
OPERATION_LOGGER = logging.getLogger("cruisecontrol.operations")


class AccessLog:
    """NCSA-format access log with daily roll + day-based retention
    (reference Jetty NCSARequestLog wiring, KafkaCruiseControlApp.java:133-148,
    WebServerConfig webserver.accesslog.{enabled,path,retention.days})."""

    def __init__(self, path: str, *, retention_days: int = 7):
        import os
        import time as _time

        self.path = path
        self.retention_days = retention_days
        self._lock = threading.Lock()
        self._day: str | None = None
        self._file = None  # persistent handle; reopened only on the daily roll
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # a leftover log from a previous day (service restart) must roll
        # before today's entries append to it — seed _day from the file's
        # mtime so the first log() call observes the day change
        try:
            self._day = _time.strftime(
                "%Y-%m-%d", _time.localtime(os.path.getmtime(path))
            )
        except OSError:
            pass

    def log(self, client: str, user: str, method: str, path: str, status: int,
            size: int):
        import os
        import time as _time

        now = _time.time()
        day = _time.strftime("%Y-%m-%d", _time.localtime(now))
        stamp = _time.strftime("%d/%b/%Y:%H:%M:%S %z", _time.localtime(now))
        line = (
            f'{client} - {user or "-"} [{stamp}] "{method} {path} HTTP/1.1" '
            f"{status} {size}\n"
        )
        with self._lock:
            if self._day is not None and day != self._day:
                # roll: current file -> path.YYYY-MM-DD, prune old rolls
                if self._file is not None:
                    self._file.close()
                    self._file = None
                try:
                    os.replace(self.path, f"{self.path}.{self._day}")
                except OSError:
                    pass
                self._prune(now)
            self._day = day
            if self._file is None:
                self._file = open(self.path, "a")  # noqa: SIM115 — held open
            self._file.write(line)
            self._file.flush()

    def _prune(self, now: float):
        import glob
        import os

        cutoff = now - self.retention_days * 86_400
        for rolled in glob.glob(f"{self.path}.*"):
            try:
                if os.path.getmtime(rolled) < cutoff:
                    os.remove(rolled)
            except OSError:
                pass


def _parse_bool(params: dict, name: str, default: bool) -> bool:
    if name not in params:
        return default
    return params[name][0].lower() in ("true", "1", "yes")


def _parse_execution_overrides(params: dict, allowed_strategies=None) -> dict:
    """Per-request execution knobs (reference ParameterUtils: concurrency
    caps + replication_throttle request parameters).

    allowed_strategies: the configured replica.movement.strategies pool —
    an unknown strategy name 400s HERE, before a full proposal computation
    is wasted on a request that can never execute."""
    out = {}
    for name, cast, lo in (
        ("concurrent_partition_movements_per_broker", int, 1),
        ("concurrent_leader_movements", int, 1),
        ("replication_throttle", float, 1),
    ):
        if name in params:
            try:
                v = cast(params[name][0])
            except ValueError as e:
                raise BadRequest(f"bad {name}: {e}") from e
            if not v >= lo:  # also rejects NaN (NaN comparisons are False)
                # a zero/negative cap would stall the executor loop forever;
                # reject loudly rather than hang the user task
                raise BadRequest(f"{name} must be >= {lo}, got {v}")
            out[name] = v
    if "replica_movement_strategies" in params:
        # per-request task-ordering override (reference ParameterUtils
        # replica_movement_strategies)
        names = [
            s.strip()
            for s in params["replica_movement_strategies"][0].split(",")
            if s.strip()
        ]
        if allowed_strategies is not None:
            unknown = [n for n in names if n not in allowed_strategies]
            if unknown:
                raise BadRequest(
                    f"unknown replica movement strategies {unknown}; "
                    f"allowed: {sorted(allowed_strategies)}"
                )
        out["replica_movement_strategies"] = names
    return out


def _parse_int_list(params: dict, name: str) -> list[int]:
    if name not in params:
        raise BadRequest(f"missing parameter {name}")
    try:
        return [int(x) for x in params[name][0].split(",") if x != ""]
    except ValueError as e:
        raise BadRequest(f"bad {name}: {e}") from e


class CruiseControlApp:
    """Server wrapper (reference KafkaCruiseControlApp.java).

    With `fleet=` (a fleet.FleetManager) the one server fronts N clusters:
    every request resolves its target facade from the `cluster=` parameter
    (bound thread-locally so the existing handlers keep reading `self.cc`),
    `/metrics` renders every cluster's labeled registry, and new async
    operations pass per-tenant admission control.  Without it, behavior is
    byte-for-byte the classic single-cluster server."""

    def __init__(self, cc: CruiseControl, *, port: int | None = None,
                 host: str | None = None, fleet=None):
        from cruise_control_tpu.service.security import (
            AllowAllSecurityProvider,
            BasicSecurityProvider,
            JwtRs256SecurityProvider,
            JwtSecurityProvider,
            SessionManager,
        )

        self._default_cc = cc
        self.fleet = fleet
        #: webserver/user-task keys come from the BASE config in fleet mode
        #: (per-cluster configs only override cluster-scoped concerns)
        self.config = fleet.config if fleet is not None else cc.config
        # flight recorder + exposition (facade-owned; standalone facades
        # built without the config keys fall back to the process tracer).
        # In a fleet this is the BASE (unscoped) tracer: /trace replays the
        # shared store; per-cluster scoped tracers mint the spans.
        from cruise_control_tpu.common.trace import TRACER

        if fleet is not None:
            self.tracer = fleet.core.tracer
        else:
            self.tracer = getattr(self._default_cc, "tracer", None) or TRACER
        self.tenant_max_pending = (
            fleet.tenant_max_pending if fleet is not None else 0
        )

        def _cat_map(fmt: str) -> dict:
            cats = {
                "KAFKA_MONITOR": "kafka.monitor",
                "CRUISE_CONTROL_MONITOR": "cruise.control.monitor",
                "KAFKA_ADMIN": "kafka.admin",
                "CRUISE_CONTROL_ADMIN": "cruise.control.admin",
            }
            out = {}
            for cat, key_part in cats.items():
                v = self.config.get(fmt.format(key_part))
                if v is not None:
                    out[cat] = v
            return out

        self.user_tasks = UserTaskManager(
            max_active_tasks=self.config.get("max.active.user.tasks"),
            max_cached_completed=self.config.get("max.cached.completed.user.tasks"),
            completed_retention_ms=self.config.get("completed.user.task.retention.time.ms"),
            category_max_cached=_cat_map("max.cached.completed.{}.user.tasks"),
            category_retention_ms=_cat_map("completed.{}.user.task.retention.time.ms"),
        )
        self.purgatory = Purgatory(
            retention_ms=self.config.get("two.step.purgatory.retention.time.ms"),
            max_requests=self.config.get("two.step.purgatory.max.requests"),
        )
        self.two_step = self.config.get("two.step.verification.enabled")
        self.reason_required = self.config.get("request.reason.required")
        self.sessions = SessionManager(
            max_expiry_ms=self.config.get("webserver.session.maxExpiryPeriodMs")
        )
        self.session_path = self.config.get("webserver.session.path")
        # security provider selection (reference webserver.security.provider)
        jwt_cert = self.config.get("jwt.auth.certificate.location") or self.config.get(
            "jwt.authentication.certificate.location"
        )
        jwt_kwargs = dict(
            cookie_name=self.config.get("jwt.cookie.name"),
            expected_audiences=self.config.get("jwt.expected.audiences") or None,
        )
        self.auth_provider_url = self.config.get("jwt.authentication.provider.url")
        custom_security = self.config.get("webserver.security.provider")
        if not self.config.get("webserver.security.enable"):
            self.security = AllowAllSecurityProvider()
        elif custom_security is not None:
            # pluggable provider outranks the builtin selection
            # (reference webserver.security.provider)
            self.security = custom_security(self.config)
        elif jwt_cert:
            # certificate-based RS256 outranks shared-secret HS256
            self.security = JwtRs256SecurityProvider(jwt_cert, **jwt_kwargs)
        elif self.config.get("jwt.secret.key"):
            self.security = JwtSecurityProvider(
                self.config.get("jwt.secret.key"), **jwt_kwargs
            )
        else:
            # reference key name wins over the legacy alias
            self.security = BasicSecurityProvider(
                self.config.get("webserver.auth.credentials.file")
                or self.config.get("basic.auth.credentials.file")
            )
        # CORS (reference WebServerConfig webserver.http.cors.*)
        self.cors_headers: dict[str, str] = {}
        if self.config.get("webserver.http.cors.enabled"):
            self.cors_headers = {
                "Access-Control-Allow-Origin": self.config.get("webserver.http.cors.origin"),
                "Access-Control-Allow-Methods": self.config.get(
                    "webserver.http.cors.allowmethods"
                ),
                "Access-Control-Expose-Headers": self.config.get(
                    "webserver.http.cors.exposeheaders"
                ),
            }
        self.access_log = (
            AccessLog(
                self.config.get("webserver.accesslog.path"),
                retention_days=self.config.get("webserver.accesslog.retention.days"),
            )
            if self.config.get("webserver.accesslog.enabled")
            else None
        )
        # static UI (reference webserver.ui.{diskpath,urlprefix})
        self.ui_diskpath = self.config.get("webserver.ui.diskpath")
        self.ui_prefix = (self.config.get("webserver.ui.urlprefix") or "/ui").rstrip("/")
        # API routes are dispatched before the UI, so a UI prefix can never
        # shadow them — which also means a UI prefix NESTED UNDER the API
        # prefix would be silently unreachable; both misconfigurations fail
        # loudly at startup instead
        if self.ui_diskpath:
            api = self.config.get("webserver.api.urlprefix").rstrip("/")
            nested = self.ui_prefix == api or self.ui_prefix.startswith(api + "/")
            if not self.ui_prefix or nested:
                raise ValueError(
                    "webserver.ui.urlprefix must be a non-root prefix outside "
                    f"the API prefix {api!r}, got "
                    f"{self.config.get('webserver.ui.urlprefix')!r}"
                )
        # per-endpoint parameter/request override maps (reference
        # CruiseControlParametersConfig / CruiseControlRequestConfig)
        self.param_parsers, self.request_handlers = build_override_maps(self.config)
        self.prefix = self.config.get("webserver.api.urlprefix").rstrip("/")
        self.host = host or self.config.get("webserver.http.address")
        self.port = port if port is not None else self.config.get("webserver.http.port")
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # per-request context (each request runs on its own handler thread)
        self._local = threading.local()

    # ------------------------------------------------------------------
    # fleet routing
    # ------------------------------------------------------------------

    @property
    def cc(self) -> CruiseControl:
        """The facade the CURRENT request targets: the thread-locally bound
        per-cluster facade in fleet mode, else the single facade.  Bound by
        handle() on the request thread and re-bound by _async_op's wrapper
        on the user-task pool thread before the operation body runs."""
        return getattr(self._local, "cc", None) or self._default_cc

    def _resolve_cluster(self, endpoint: str, cluster: str | None):
        """-> (facade, cluster_id) for this request; raises BadRequest on
        an unknown cluster, a `cluster=` outside fleet mode, or a missing
        one on a cluster-scoped endpoint in fleet mode."""
        from cruise_control_tpu.config.endpoints import FLEET_GLOBAL_ENDPOINTS

        if self.fleet is None:
            if cluster:
                raise BadRequest(
                    f"cluster={cluster!r} but this instance manages no fleet "
                    "(fleet.clusters is empty)"
                )
            return self._default_cc, ""
        if not cluster:
            if endpoint in FLEET_GLOBAL_ENDPOINTS:
                return self._default_cc, ""
            raise BadRequest(
                f"parameter 'cluster' is required for {endpoint} in fleet "
                f"mode; clusters: {self.fleet.cluster_ids()}"
            )
        try:
            return self.fleet.facade(cluster), cluster
        except KeyError as e:
            raise BadRequest(str(e.args[0])) from e

    # ------------------------------------------------------------------
    # endpoint handlers; each returns (status, payload)
    # ------------------------------------------------------------------

    def handle(self, method: str, endpoint: str, params: dict, headers) -> tuple[int, dict]:
        if method == "GET" and endpoint not in GET_ENDPOINTS:
            raise BadRequest(f"unknown GET endpoint {endpoint}")
        if method == "POST" and endpoint not in POST_ENDPOINTS:
            raise BadRequest(f"unknown POST endpoint {endpoint}")
        # fleet routing: bind the target facade for this request thread
        # BEFORE anything touches self.cc (the 202-resume path below never
        # does — the task already carries its operation)
        self._local.cc, self._local.cluster_id = self._resolve_cluster(
            endpoint, params.get("cluster", [None])[0]
        )
        if (
            method == "POST"
            and self.reason_required
            and not params.get("reason", [""])[0]
            # an approved two-step resubmit carries only review_id — its
            # reason rides the PARKED params (which passed this check when
            # the request first parked).  The exemption only applies while
            # two-step verification is ON: otherwise review_id is ignored
            # downstream and a bare review_id would bypass the reason check
            and not (self.two_step and "review_id" in params)
        ):
            # reference WebServerConfig request.reason.required: mutating
            # requests must say why (feeds the operation audit log)
            raise BadRequest("parameter 'reason' is required on POST requests")

        # resume an async task by header (reference UserTaskManager flow)
        tid = headers.get(USER_TASK_ID_HEADER)
        if tid:
            task = self.user_tasks.get(tid)
            if task is None:
                # reference UserTaskManager rejects unknown task ids rather
                # than silently re-executing the operation
                return 404, {"errorMessage": f"unknown user task id {tid}"}
            status, payload = self._task_response(task)
            if status != 202:
                # response delivered: drop any session bound to this task, or
                # a later identical request would resume the stale result
                self.sessions.release_task(tid)
            return status, payload
        # header lost: rebind via session key (reference SessionManager).
        # Binding needs a client identity (reference: the HTTP session) —
        # anonymous requests must NOT share one namespace, or client B's
        # identical POST would silently resume client A's operation.
        client = headers.get("X-Client")
        self._local.client = client or ""
        # content negotiation (the /metrics OpenMetrics flavor reads it)
        self._local.accept = str(headers.get("Accept") or "")
        self._local.session_key = (
            self.sessions.session_key(
                client, method, endpoint,
                "&".join(f"{k}={v[0]}" for k, v in sorted(params.items())),
            )
            if client
            else None
        )

        # declared-parameter validation BEFORE the purgatory: unknown names
        # and malformed values 400 now (a `dry_run` typo must not execute
        # the rebalance the caller believed was a dry run), and an invalid
        # request must not park with a 200 only to burn its one approval
        # when the resubmit finally validates
        parsed = params
        parser = self.param_parsers.get(endpoint)
        if parser is not None:
            try:
                parsed = parser.parse(params)
            except ParameterError as e:
                raise BadRequest(str(e)) from e

        # two-step verification parks POSTs in the purgatory first
        if (
            method == "POST"
            and self.two_step
            and endpoint not in ("review", "stop_proposal_execution")
        ):
            if "review_id" in params:
                rid = int(params["review_id"][0])
                info = self.purgatory.take_approved(endpoint, rid)
                params = {**{k: [str(v)] for k, v in info.params.items()}, **params}
                if parser is not None:
                    # re-parse the MERGED params: a custom request handler
                    # consumes `parsed`, which must carry the parked
                    # parameters, not just the resubmit's review_id
                    parsed = parser.parse(params)
            else:
                try:
                    info = self.purgatory.add(
                        endpoint, {k: v[0] for k, v in params.items()}
                    )
                except PurgatoryFullError as e:
                    raise BadRequest(str(e)) from e
                return 200, {"reviewId": info.review_id, "status": info.status.value}

        custom = self.request_handlers.get(endpoint)
        if custom is not None:
            # custom request classes receive the PARSED parameter dict
            # (build_override_maps contract)
            return custom(self, endpoint, parsed)
        fn = getattr(self, f"_ep_{endpoint}")
        return fn(params)

    def _task_response(self, task) -> tuple[int, dict]:
        # every shape carries the flight-recorder trace id (when tracing
        # is on): a client polling a 202 can ALREADY replay the live span
        # tree via GET /trace?id=..., and a 500's trace shows which stage
        # died
        rider = {"_traceId": task.trace_id} if task.trace_id else {}
        try:
            result = task.future.result(timeout=1.0)
            return 200, {**result, "_userTaskId": task.task_id, **rider}
        except FutureTimeout:
            return 202, {
                "progress": task.progress.to_json(),
                "_userTaskId": task.task_id,
                **rider,
            }
        except Exception as e:  # noqa: BLE001 — operation failed
            return 500, {
                "errorMessage": str(e), "_userTaskId": task.task_id, **rider,
            }

    def _async_op(self, endpoint: str, fn) -> tuple[int, dict]:
        # fleet context: the facade resolved on the REQUEST thread rides
        # into the pool-thread wrapper, which re-binds it thread-locally so
        # handler bodies reading self.cc resolve the same cluster there
        cc = self.cc
        cluster_id = getattr(self._local, "cluster_id", "") or ""
        # flight recorder: ONE trace per submitted operation.  The id is
        # minted here (synchronously, so the UserTask carries it and the
        # very first 202 can hand it to the client); the root span opens
        # on the pool thread when the operation actually runs, and every
        # pipeline stage beneath (model build, optimize, device ops,
        # execution) parents into it via context propagation.  In fleet
        # mode the facade's CLUSTER-SCOPED tracer mints the root, so the
        # whole operation files under this cluster's trace components.
        tracer = getattr(cc, "tracer", None) or self.tracer
        trace_id = tracer.new_trace_id() if tracer.enabled else ""

        def wrapped(progress, _op=fn):
            self._local.cc = cc
            self._local.cluster_id = cluster_id
            span_attrs = {"cluster": cluster_id} if cluster_id else {}
            with tracer.span(
                f"service.{endpoint}", component="service",
                trace_id=trace_id, root=True, **span_attrs,
            ):
                out = _op(progress)
            # degraded serving must be visible in the ops audit trail, not
            # only in the payload: the analyzer's device breaker is open
            # and this answer came from the CPU greedy fallback
            if isinstance(out, dict) and out.get("degraded"):
                OPERATION_LOGGER.warning(
                    "%s served DEGRADED (CPU greedy fallback; "
                    "see /state AnalyzerState.supervisor)",
                    endpoint,
                )
            return out

        fn = wrapped

        def _submit():
            # admission control runs HERE — _submit only fires for NEW
            # work, so polling an already-running task (User-Task-ID
            # header, or the session rebind below) is never rejected.
            # Two rungs: the device scheduler's INTERACTIVE shed (severe
            # overload: 429 + drain-rate Retry-After BEFORE a task is
            # created), then the per-tenant pending cap enforced inside
            # the task manager's lock (atomic count-and-admit).
            sched = getattr(cc, "scheduler", None)
            if sched is not None:
                try:
                    sched.admit_interactive(
                        cluster_id=cluster_id,
                        default_retry_after_s=self.config.get(
                            "fleet.tenant.retry.after.s"
                        ),
                    )
                except SchedulerOverloadError:
                    cc.sensors.counter("fleet.scheduler-rejections").inc()
                    raise
            cap = (
                self.tenant_max_pending
                if self.fleet is not None and cluster_id else 0
            )
            try:
                return self.user_tasks.submit(
                    endpoint, fn, client_id=client, trace_id=trace_id,
                    cluster_id=cluster_id, cluster_max_active=cap,
                )
            except TenantOverloadError:
                cc.sensors.counter("fleet.tenant-rejections").inc()
                raise

        key = getattr(self._local, "session_key", None)
        client = getattr(self._local, "client", "") or ""
        try:
            if key is None:
                return self._task_response(_submit())
            # bind the session to the submitted task so a client that lost
            # the User-Task-ID header resumes the same operation instead of
            # re-executing it (reference servlet/SessionManager.java)
            tid = self.sessions.get_or_bind(key, lambda: _submit().task_id)
            task = self.user_tasks.get(tid)
            if task is None:  # bound task evicted; start fresh
                self.sessions.release(key)
                tid = self.sessions.get_or_bind(key, lambda: _submit().task_id)
                task = self.user_tasks.get(tid)
        except TenantOverloadError as e:
            # Retry-After from the tenant queue's measured drain rate
            # (fallback: fleet.tenant.retry.after.s) — the rider becomes
            # a real Retry-After header in _send
            ra = e.retry_after_s
            if ra is None:
                ra = self.user_tasks.retry_after_s(
                    cluster_id,
                    default_s=self.config.get("fleet.tenant.retry.after.s"),
                )
            return 429, {"errorMessage": str(e), "_retryAfter": int(round(ra))}
        except SchedulerOverloadError as e:
            return 429, {
                "errorMessage": str(e),
                "_retryAfter": int(round(e.retry_after_s)),
            }
        status, payload = self._task_response(task)
        if status != 202:  # response delivered -> close the session
            self.sessions.release(key)
        return status, payload

    # --- GET ---

    def _ep_state(self, params) -> tuple[int, dict]:
        subs = params.get("substates", [None])[0]
        return 200, self.cc.state(subs.split(",") if subs else None)

    def _ep_kafka_cluster_state(self, params) -> tuple[int, dict]:
        topo = self.cc.admin.topology()
        by_broker: dict[int, dict] = {
            b.broker_id: {"replicaCount": 0, "leaderCount": 0, "isAlive": b.alive,
                          "rack": b.rack}
            for b in topo.brokers
        }
        urp = 0
        offline = 0
        alive = topo.alive_broker_ids()
        for p in topo.partitions:
            for b in p.replicas:
                if b in by_broker:
                    by_broker[b]["replicaCount"] += 1
                if b not in alive:
                    offline += 1
            if any(b not in alive for b in p.replicas):
                urp += 1
            if p.leader in by_broker:
                by_broker[p.leader]["leaderCount"] += 1
        return 200, {
            "KafkaBrokerState": by_broker,
            "KafkaPartitionState": {
                "numTotalPartitions": len(topo.partitions),
                "numUnderReplicatedPartitions": urp,
                "numOfflineReplicas": offline,
            },
        }

    def _ep_load(self, params) -> tuple[int, dict]:
        def op(progress):
            state = self.cc._cluster_model(progress)
            from cruise_control_tpu.models.aggregates import compute_aggregates

            agg = compute_aggregates(state)
            load = np.asarray(agg.broker_load)
            cap = np.asarray(state.broker_capacity)
            alive = np.asarray(state.broker_alive)
            bvalid = np.asarray(state.broker_valid)
            hosts = (
                self.cc.monitor.last_catalog.hosts
                if self.cc.monitor.last_catalog and self.cc.monitor.last_catalog.hosts
                else None
            )
            brokers = []
            for b in range(state.shape.B):
                if not bvalid[b]:
                    continue  # shape-bucket padding rows are not brokers
                row = {
                    "Broker": b,
                    "BrokerState": "ALIVE" if alive[b] else "DEAD",
                    "Leaders": int(np.asarray(agg.broker_leader_count)[b]),
                    "Replicas": int(np.asarray(agg.broker_replica_count)[b]),
                }
                for r in range(4):
                    name = RESOURCE_NAMES[r]
                    row[name] = round(float(load[b, r]), 3)
                    row[f"{name}Pct"] = round(
                        float(100.0 * load[b, r] / max(cap[b, r], 1e-9)), 2
                    )
                brokers.append(row)
            return {"brokers": brokers, "hosts": hosts or []}

        return self._async_op("load", op)

    def _ep_partition_load(self, params) -> tuple[int, dict]:
        resource = params.get("resource", ["DISK"])[0].upper()
        if resource not in RESOURCE_NAMES:
            raise BadRequest(f"unknown resource {resource}")
        max_entries = int(params.get("entries", ["50"])[0])

        def op(progress):
            state = self.cc._cluster_model(progress)
            catalog = self.cc.monitor.last_catalog
            r = int(Resource[resource])
            lead = np.asarray(state.replica_is_leader) & np.asarray(state.replica_valid)
            loads = np.asarray(state.replica_load_leader)[:, r]
            part = np.asarray(state.replica_partition)
            order = np.argsort(-np.where(lead, loads, -np.inf))
            records = []
            for i in order[:max_entries]:
                if not lead[i]:
                    break
                t, p = catalog.partition_key(int(part[i]))
                records.append(
                    {"topic": t, "partition": p, resource: round(float(loads[i]), 3)}
                )
            return {"records": records, "resource": resource}

        return self._async_op("partition_load", op)

    def _ep_proposals(self, params) -> tuple[int, dict]:
        ignore_cache = _parse_bool(params, "ignore_proposal_cache", False)
        allow_est = _parse_bool(params, "allow_capacity_estimation", True)

        def op(progress):
            result = self.cc.proposals(
                progress,
                ignore_cache=ignore_cache,
                allow_capacity_estimation=allow_est,
            )
            out = result.summary()
            out["estimatedExecutionTime"] = self.cc._execution_eta(result)
            out["proposals"] = [p.to_json() for p in result.proposals[:100]]
            return out

        return self._async_op("proposals", op)

    def _ep_user_tasks(self, params) -> tuple[int, dict]:
        """Reference UserTasksParameters filters
        (servlet/parameters/UserTasksParameters.java:1): user_task_ids,
        client_ids, endpoints, and types (task status names) are each a
        comma-separated allowlist; unset filters match everything."""
        tasks = self.user_tasks.all_tasks()
        # (param, task attribute, case-sensitive) — client identities are
        # opaque strings and compare exactly; ids/endpoints/statuses fold
        for pname, attr, exact in (
            ("user_task_ids", "task_id", False),
            ("client_ids", "client_id", True),
            ("endpoints", "endpoint", False),
            ("types", "status", False),
            # fleet: filter the task board down to one or more clusters
            ("clusters", "cluster_id", True),
        ):
            raw = params.get(pname, [None])[0]
            if not raw:
                continue
            wanted = {x.strip() if exact else x.strip().lower()
                      for x in raw.split(",") if x.strip()}
            tasks = [
                t for t in tasks
                if (getattr(t, attr) if exact else getattr(t, attr).lower())
                in wanted
            ]
        return 200, {"userTasks": [t.to_json() for t in tasks]}

    def _ep_review_board(self, params) -> tuple[int, dict]:
        return 200, {"requestInfo": self.purgatory.board()}

    def _ep_bootstrap(self, params) -> tuple[int, dict]:
        """Reference LoadMonitor.bootstrap:325-345 + BootstrapTask's 3 modes:
        RANGE (start+end), SINCE (start only), RECENT (neither)."""
        runner = getattr(self.cc, "task_runner", None)
        if runner is None:
            raise BadRequest("no task runner configured")
        start = params.get("start", [None])[0]
        end = params.get("end", [None])[0]
        clear = _parse_bool(params, "clearmetrics", start is None and end is None)

        def op(progress):
            if start is not None and end is not None:
                mode, n = "RANGE", runner.bootstrap_range(int(start), int(end), clear)
            elif start is not None:
                mode, n = "SINCE", runner.bootstrap_since(int(start), clear)
            else:
                mode, n = "RECENT", runner.bootstrap_recent(clear)
            return {"mode": mode, "samplesAbsorbed": n, **runner.state()}

        return self._async_op("bootstrap", op)

    def _ep_train(self, params) -> tuple[int, dict]:
        """Reference LoadMonitor.train:354 -> TrainingTask -> regression."""
        runner = getattr(self.cc, "task_runner", None)
        if runner is None:
            raise BadRequest("no task runner configured")
        import time as _time

        now = int(_time.time() * 1000)
        start = int(params.get("start", [str(now - 3_600_000)])[0])
        end = int(params.get("end", [str(now)])[0])
        return self._async_op(
            "train", lambda progress: runner.train(start, end)
        )

    def _blackbox_block(self) -> dict:
        """The black-box spool's live view (the durable twin of the
        in-memory trace store): recorder state, the trailing records
        re-read from disk, and the dispatches currently in flight."""
        from cruise_control_tpu.common.blackbox import RECORDER

        return {
            "state": RECORDER.state_json(),
            "records": RECORDER.tail(),
            "inFlight": RECORDER.in_flight(),
        }

    def _ep_trace(self, params) -> tuple[int, dict]:
        """GET /trace — flight-recorder replay.  With ?id=<traceId> the
        span forest of one trace (404 when nothing of it is retained);
        without, a newest-first index of recent root traces.  With
        ?blackbox=true the response also embeds the on-disk dispatch
        spool's tail + in-flight dispatches."""
        tid = params.get("id", [None])[0]
        with_bb = _parse_bool(params, "blackbox", False)
        if tid is None:
            # the declared Param("limit", _min1_int) parser already 400'd
            # malformed/<1 values before dispatch reached this handler
            limit = int(params.get("limit", ["50"])[0])
            out = {"traces": self.tracer.recent_traces(limit)}
            if with_bb:
                out["blackbox"] = self._blackbox_block()
            return 200, out
        spans = self.tracer.trace_tree(tid)
        if not spans:
            # KeyError -> the dispatcher's 404 path: an unknown (or
            # already-evicted) trace id is "not found", not an empty tree
            raise KeyError(f"no retained spans for trace id {tid}")
        out = {"traceId": tid, "spans": spans}
        if with_bb:
            out["blackbox"] = self._blackbox_block()
        return 200, out

    def _ep_metrics(self, params) -> tuple[int, dict]:
        """GET /metrics — Prometheus text exposition of the whole sensor
        surface (common/exposition.py); text/plain, not JSON.  Fleet mode
        renders EVERY registry: the shared core's unlabeled plus each
        cluster's `{cluster=...}`-labeled one.  `?format=openmetrics` (or
        an Accept header naming application/openmetrics-text) renders the
        OpenMetrics flavor: histogram buckets carry trace-id exemplars
        linking latency outliers to their /trace replays."""
        from cruise_control_tpu.common.exposition import (
            CONTENT_TYPE,
            CONTENT_TYPE_OPENMETRICS,
            prometheus_text,
        )

        openmetrics = (
            params.get("format", [""])[0].lower() == "openmetrics"
            or "application/openmetrics-text"
            in getattr(self._local, "accept", "")
        )
        registries = (
            self.fleet.registries() if self.fleet is not None else self.cc.sensors
        )
        body = prometheus_text(
            registries,
            namespace=self.config.get("metrics.prometheus.namespace"),
            openmetrics=openmetrics,
        )
        return 200, RawResponse(
            body, CONTENT_TYPE_OPENMETRICS if openmetrics else CONTENT_TYPE
        )

    def _ep_slo(self, params) -> tuple[int, dict]:
        """GET /slo — the SLO registries' live state: per-SLO fast/slow
        burn rates, compliance, and breach-episode status, evaluated
        fresh on every scrape (common/slo.py).  Fleet mode reports every
        cluster (or one, with ?cluster=); single-cluster deployments
        answer under the synthetic id "default" like /fleet."""
        cluster = params.get("cluster", [None])[0]

        def block(cc) -> dict:
            reg = cc.slo_registry
            if reg is None:
                return {"enabled": False, "slos": []}
            return {"enabled": True, **reg.state_json()}

        if self.fleet is None:
            clusters = {"default": block(self._default_cc)}
        else:
            ids = [cluster] if cluster else self.fleet.cluster_ids()
            clusters = {cid: block(self.fleet.facade(cid)) for cid in ids}
        return 200, {"numClusters": len(clusters), "clusters": clusters}

    def _ep_explain(self, params) -> tuple[int, dict]:
        """GET /explain?trace_id=|proposal= — replay one decision-ledger
        episode as a structured explanation: goal deltas, top moves,
        convergence curve, outcome + calibration when present
        (analyzer/ledger.py; cluster-scoped — each cluster owns its own
        ledger)."""
        trace_id = params.get("trace_id", [None])[0]
        proposal = params.get("proposal", [None])[0]
        try:
            out = self.cc.explain(trace_id=trace_id, decision_id=proposal)
        except ValueError as e:
            raise BadRequest(str(e)) from e
        # KeyError (unknown trace/proposal) rides to the dispatcher's 404
        return 200, out

    def _ep_ledger(self, params) -> tuple[int, dict]:
        """GET /ledger — the raw joined decision→outcome→calibration
        episode stream, newest first (the flywheel's training-corpus
        export; `cccli ledger` prints it verbatim)."""
        limit = int(params.get("limit", ["50"])[0])
        cc = self.cc
        if cc.ledger is None:
            return 200, {"enabled": False, "entries": []}
        return 200, {
            "enabled": True,
            "entries": cc.ledger_entries(limit=limit),
            "state": cc.ledger.state_json(),
        }

    def _ep_fleet(self, params) -> tuple[int, dict]:
        """GET /fleet — whole-instance rollup: per-cluster summaries + the
        shared core (engine cache, supervisor, admission control).  With
        ?score=true every cluster's current placement is also scored on
        the shared goal chain, same-bucket clusters batched through one
        device dispatch.  Single-cluster deployments answer with a
        one-entry rollup under the id "default"."""
        cluster = params.get("cluster", [None])[0]
        if self.fleet is not None:
            out = self.fleet.fleet_state(cluster)
            if _parse_bool(params, "score", False):
                out["scores"] = self.fleet.score_clusters()
            return 200, out
        # single-cluster view: same shape, one synthetic entry, so fleet
        # dashboards work unchanged against classic deployments
        from cruise_control_tpu.fleet.manager import (
            ClusterContext,
            shared_core_rollup,
        )

        cc = self.cc
        return 200, {
            "numClusters": 1,
            "clusters": {"default": ClusterContext("default", cc).rollup()},
            "shared": shared_core_rollup(cc.core),
        }

    def _ep_rightsize(self, params) -> tuple[int, dict]:
        """GET /rightsize — minimum brokers satisfying all hard goals at
        current (and, with horizon_ms, forecast) load.  Read-only."""
        allow_est = _parse_bool(params, "allow_capacity_estimation", True)

        def _opt_num(name, cast, lo):
            # bounds match the declared _min1_* parsers (parameters.py):
            # a negative horizon would "forecast" backwards and a
            # sub-1 factor degenerates the search ceiling silently
            v = params.get(name, [None])[0]
            if v is None:
                return None
            try:
                v = cast(v)
            except ValueError as e:
                raise BadRequest(f"bad {name}: {e}") from e
            if not v >= lo:
                raise BadRequest(f"{name} must be >= {lo}, got {v}")
            return v

        horizon = _opt_num("horizon_ms", int, 1)
        min_brokers = _opt_num("min_brokers", int, 1)
        max_factor = _opt_num("max_broker_factor", float, 1)
        return self._async_op(
            "rightsize",
            lambda progress: self.cc.rightsize(
                progress,
                horizon_ms=horizon,
                min_brokers=min_brokers,
                max_broker_factor=max_factor,
                allow_capacity_estimation=allow_est,
            ),
        )

    # --- POST ---

    def _ep_simulate(self, params) -> tuple[int, dict]:
        """POST /simulate — batched what-if evaluation.  POST because the
        scenario payload is a JSON document (rides the form body), but the
        operation never mutates the cluster."""
        from cruise_control_tpu.service.parameters import (
            ParameterError,
            _scenario_list,
        )

        raw = params.get("scenarios", [None])[0]
        if raw is None:
            raise BadRequest("missing parameter scenarios (JSON list)")
        try:
            scenarios = _scenario_list(raw)
        except ParameterError as e:
            raise BadRequest(str(e)) from e
        cap = self.cc.config.get("planner.max.scenarios")
        if len(scenarios) > cap:
            # 400 HERE: an oversized batch is a client error, not a task
            # failure surfaced as 500 after a cluster model was built
            raise BadRequest(
                f"{len(scenarios)} scenarios exceed planner.max.scenarios={cap}"
            )
        optimize = (
            _parse_bool(params, "optimize", False)
            if "optimize" in params
            else None  # None -> planner.simulate.optimize.default
        )
        allow_est = _parse_bool(params, "allow_capacity_estimation", True)
        return self._async_op(
            "simulate",
            lambda progress: self.cc.simulate(
                progress,
                scenarios,
                optimize=optimize,
                allow_capacity_estimation=allow_est,
            ),
        )

    def _ep_rebalance(self, params) -> tuple[int, dict]:
        dryrun = _parse_bool(params, "dryrun", True)
        rebalance_disk = _parse_bool(params, "rebalance_disk", False)
        allow_est = _parse_bool(params, "allow_capacity_estimation", True)
        goals = params.get("goals", [None])[0]
        dests = params.get("destination_broker_ids", [None])[0]
        excluded = params.get("excluded_topics", [None])[0]
        overrides = _parse_execution_overrides(params, self.cc.allowed_strategies)
        # reference rebalance parameters exclude recently removed/demoted
        # brokers from receiving replicas/leadership
        ex_removed = (
            sorted(self.cc.executor.removed_brokers)
            if _parse_bool(params, "exclude_recently_removed_brokers", False)
            else None
        )
        ex_demoted = (
            sorted(self.cc.executor.demoted_brokers)
            if _parse_bool(params, "exclude_recently_demoted_brokers", False)
            else None
        )

        def op(progress):
            return self.cc.rebalance(
                progress,
                dryrun=dryrun,
                goals=goals.split(",") if goals else None,
                destination_broker_ids=[int(x) for x in dests.split(",")] if dests else None,
                excluded_topics_pattern=excluded,
                excluded_brokers_for_replica_move=ex_removed,
                excluded_brokers_for_leadership=ex_demoted,
                rebalance_disk=rebalance_disk,
                allow_capacity_estimation=allow_est,
                execution_overrides=overrides,
            )

        return self._async_op("rebalance", op)

    def _ep_add_broker(self, params) -> tuple[int, dict]:
        ids = _parse_int_list(params, "brokerid")
        dryrun = _parse_bool(params, "dryrun", True)
        overrides = _parse_execution_overrides(params, self.cc.allowed_strategies)
        return self._async_op(
            "add_broker",
            lambda progress: self.cc.add_brokers(
                progress, ids, dryrun=dryrun, execution_overrides=overrides
            ),
        )

    def _ep_remove_broker(self, params) -> tuple[int, dict]:
        ids = _parse_int_list(params, "brokerid")
        dryrun = _parse_bool(params, "dryrun", True)
        overrides = _parse_execution_overrides(params, self.cc.allowed_strategies)
        return self._async_op(
            "remove_broker",
            lambda progress: self.cc.remove_brokers(
                progress, ids, dryrun=dryrun, execution_overrides=overrides
            ),
        )

    def _ep_demote_broker(self, params) -> tuple[int, dict]:
        ids = _parse_int_list(params, "brokerid")
        dryrun = _parse_bool(params, "dryrun", True)
        return self._async_op(
            "demote_broker",
            lambda progress: self.cc.demote_brokers(progress, ids, dryrun=dryrun),
        )

    def _ep_fix_offline_replicas(self, params) -> tuple[int, dict]:
        dryrun = _parse_bool(params, "dryrun", True)
        return self._async_op(
            "fix_offline_replicas",
            lambda progress: self.cc.fix_offline_replicas(progress, dryrun=dryrun),
        )

    def _ep_stop_proposal_execution(self, params) -> tuple[int, dict]:
        force = _parse_bool(params, "force_stop", False)
        return 200, self.cc.stop_proposal_execution(force=force)

    def _ep_pause_sampling(self, params) -> tuple[int, dict]:
        reason = params.get("reason", ["user request"])[0]
        self.cc.monitor.pause(reason)
        return 200, {"message": f"sampling paused: {reason}"}

    def _ep_resume_sampling(self, params) -> tuple[int, dict]:
        self.cc.monitor.resume()
        return 200, {"message": "sampling resumed"}

    def _ep_topic_configuration(self, params) -> tuple[int, dict]:
        topic = params.get("topic", [None])[0]
        if topic is None:
            raise BadRequest("missing parameter topic")
        rf = int(params.get("replication_factor", ["0"])[0])
        if rf < 1:
            raise BadRequest("replication_factor must be >= 1")
        dryrun = _parse_bool(params, "dryrun", True)
        return self._async_op(
            "topic_configuration",
            lambda progress: self.cc.update_topic_replication_factor(
                progress, {topic: rf}, dryrun=dryrun
            ),
        )

    def _ep_admin(self, params) -> tuple[int, dict]:
        """Reference AdminRequest: toggle self-healing, drop broker history,
        and change the concurrency of a RUNNING execution
        (servlet/parameters/AdminParameters.java:31-38 ->
        ChangeExecutionConcurrencyParameters, applied via
        executor/Executor.java:485-510)."""
        out: dict = {}
        from cruise_control_tpu.detector import AnomalyType

        # validate the WHOLE request before applying any of it: a 400 must
        # not leave earlier side effects (e.g. a self-healing toggle)
        # silently committed
        conc = {}
        for pname, kwarg, cast in (
            ("concurrent_partition_movements_per_broker", "inter_broker", int),
            ("concurrent_intra_broker_partition_movements", "intra_broker", int),
            ("concurrent_leader_movements", "leadership", int),
            ("execution_progress_check_interval_ms", "progress_check_interval_s",
             lambda v: int(v) / 1000.0),
        ):
            raw = params.get(pname, [None])[0]
            if raw is not None:
                try:
                    conc[kwarg] = cast(raw)
                except (TypeError, ValueError) as e:
                    raise BadRequest(f"bad {pname}: {raw!r}") from e
        # mid-execution concurrency change first: the executor applies it
        # atomically under its lock (raising when no execution is live, so
        # an execution finishing mid-request 400s instead of 200ing a
        # silent no-op) — and a 400 here must precede the self-healing /
        # history side effects below
        if conc:
            from cruise_control_tpu.executor.executor import NoOngoingExecutionError

            try:
                out["requestedConcurrency"] = (
                    self.cc.executor.set_requested_concurrency(**conc)
                )
            except (NoOngoingExecutionError, ValueError) as e:
                raise BadRequest(str(e)) from e
            # applied on the executor's next progress tick, so a live
            # rebalance can be throttled or unstuck
            out["ongoingExecution"] = True

        enable = params.get("enable_self_healing_for", [None])[0]
        disable = params.get("disable_self_healing_for", [None])[0]
        for arg, value in ((enable, True), (disable, False)):
            if arg:
                for name in arg.split(","):
                    self.cc.notifier.set_self_healing(AnomalyType[name.upper()], value)
        if enable or disable:
            out["selfHealingEnabled"] = [
                t.name for t, on in self.cc.notifier.self_healing_enabled().items() if on
            ]
        drop = params.get("drop_recently_removed_brokers", [None])[0]
        if drop:
            self.cc.executor.drop_removed_brokers(int(b) for b in drop.split(","))
            out["recentlyRemovedBrokers"] = sorted(self.cc.executor.removed_brokers)
        drop_dem = params.get("drop_recently_demoted_brokers", [None])[0]
        if drop_dem:
            self.cc.executor.drop_demoted_brokers(int(b) for b in drop_dem.split(","))
            out["recentlyDemotedBrokers"] = sorted(self.cc.executor.demoted_brokers)
        return 200, out

    def _ep_review(self, params) -> tuple[int, dict]:
        approve = params.get("approve", [None])[0]
        discard = params.get("discard", [None])[0]
        reason = params.get("reason", [""])[0]
        for arg, ok in ((approve, True), (discard, False)):
            if arg:
                for rid in arg.split(","):
                    self.purgatory.review(int(rid), ok, reason)
        return 200, {"requestInfo": self.purgatory.board()}

    # ------------------------------------------------------------------

    def start(self):
        # crash-safe execution: an execution journal-reconciled at
        # construction belongs in the operation audit trail — the operator
        # reading it learns the service came up mid-rebalance and is
        # resuming (the live detail rides /state ExecutorState.recovery).
        # Fleet mode reports EVERY cluster's reconciliation: each cluster
        # replayed its own namespaced journal at facade construction.
        facades = (
            [(ctx.cluster_id, ctx.cc) for ctx in self.fleet.contexts.values()]
            if self.fleet is not None
            else [("", self._default_cc)]
        )
        for cid, facade in facades:
            recovery = facade.executor.recovery_info()
            if recovery is not None:
                OPERATION_LOGGER.warning(
                    "executor%s recovered in-flight execution from journal: %s",
                    f" [cluster {cid}]" if cid else "",
                    recovery,
                )
        app = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _dispatch(self, method: str):
                parsed = urllib.parse.urlparse(self.path)
                self._new_session_id = None
                if "X-Client" not in self.headers:
                    # browser flow: sticky client identity via a session
                    # cookie so header-less clients still get session->task
                    # rebind (reference servlet HTTP sessions; cookie Path
                    # from webserver.session.path)
                    from http.cookies import SimpleCookie

                    jar = SimpleCookie()
                    try:
                        jar.load(self.headers.get("Cookie", ""))
                    except Exception:  # noqa: BLE001 — malformed cookie header
                        jar = SimpleCookie()
                    if "CCSESSION" in jar:
                        self.headers["X-Client"] = "cookie:" + jar["CCSESSION"].value
                    else:
                        import uuid as _uuid

                        self._new_session_id = _uuid.uuid4().hex
                        self.headers["X-Client"] = "cookie:" + self._new_session_id
                # API paths are checked FIRST: no webserver.ui.urlprefix
                # value (e.g. an ancestor of the API prefix) may shadow an
                # API route
                if not parsed.path.startswith(app.prefix + "/"):
                    if (
                        method == "GET"
                        and app.ui_diskpath
                        and (
                            parsed.path == app.ui_prefix
                            or parsed.path.startswith(app.ui_prefix + "/")
                        )
                    ):
                        # the UI sits behind the same authentication as the
                        # API (reference: the security handler wraps the
                        # whole server), with the same login challenge
                        if app.security.authenticate(self.headers) is None:
                            self._auth_challenge(method)
                            return
                        self._serve_ui(parsed.path)
                        return
                    self._send(404, {"errorMessage": "unknown path"})
                    return
                endpoint = parsed.path[len(app.prefix) + 1:].strip("/").lower()
                params = urllib.parse.parse_qs(parsed.query)
                if method == "POST" and int(self.headers.get("Content-Length") or 0):
                    body = self.rfile.read(int(self.headers["Content-Length"])).decode()
                    params.update(urllib.parse.parse_qs(body))
                auth = app.security.authenticate(self.headers)
                if auth is None:
                    # denied attempts are the most security-relevant audit
                    # entries — log them too
                    OPERATION_LOGGER.info(
                        "%s %s by <unauthenticated> -> 401", method, endpoint
                    )
                    self._auth_challenge(method)
                    return
                principal, role = auth
                if not app.security.authorize(role, method, endpoint):
                    OPERATION_LOGGER.info(
                        "%s %s by %s(%s) -> 403", method, endpoint, principal, role
                    )
                    self._send(403, {
                        "errorMessage": f"role {role} of {principal} may not {method} {endpoint}"
                    })
                    return
                try:
                    status, payload = app.handle(method, endpoint, params, self.headers)
                except BadRequest as e:
                    status, payload = 400, {"errorMessage": str(e)}
                except KeyError as e:
                    status, payload = 404, {"errorMessage": f"not found: {e}"}
                except Exception as e:  # noqa: BLE001
                    status, payload = 500, {"errorMessage": repr(e)}
                OPERATION_LOGGER.info(
                    "%s %s by %s(%s) -> %d",
                    method, endpoint, principal, role, status,
                )
                self._user = principal
                self._send(status, payload)

            def _send(self, status: int, payload):
                if isinstance(payload, RawResponse):
                    body = payload.body.encode()
                    self.send_response(status)
                    self.send_header("Content-Type", payload.content_type)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in app.cors_headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                    if app.access_log:
                        app.access_log.log(
                            self.client_address[0], getattr(self, "_user", ""),
                            self.command, self.path, status, len(body),
                        )
                    return
                body = json.dumps(payload, default=_json_default).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in app.cors_headers.items():
                    self.send_header(k, v)
                if getattr(self, "_new_session_id", None):
                    self.send_header(
                        "Set-Cookie",
                        f"CCSESSION={self._new_session_id}; "
                        f"Path={app.session_path}; HttpOnly",
                    )
                tid = payload.get("_userTaskId") if isinstance(payload, dict) else None
                if tid:
                    self.send_header(USER_TASK_ID_HEADER, tid)
                ra = payload.get("_retryAfter") if isinstance(payload, dict) else None
                if ra is not None:
                    # 429 backoff hint (admission control / scheduler
                    # shed): standard header, integer seconds
                    self.send_header("Retry-After", str(int(ra)))
                self.end_headers()
                self.wfile.write(body)
                if app.access_log:
                    app.access_log.log(
                        self.client_address[0],
                        getattr(self, "_user", ""),
                        self.command,
                        self.path,
                        status,
                        len(body),
                    )

            def _auth_challenge(self, method: str):
                """401 with a WWW-Authenticate challenge, or a 302 to the
                configured auth provider (jwt.authentication.provider.url) —
                shared by the API and UI paths so a browser can always log
                in."""
                if app.auth_provider_url:
                    # reference jwt.authentication.provider.url: browsers
                    # are bounced to the token issuer with the original
                    # URL so they come back authenticated
                    loc = app.auth_provider_url.replace(
                        "{redirect}", urllib.parse.quote(self.path, safe="")
                    )
                    self.send_response(302)
                    self.send_header("Location", loc)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    if app.access_log:
                        app.access_log.log(
                            self.client_address[0], "", method, self.path, 302, 0
                        )
                    return
                body = json.dumps({"errorMessage": "authentication required"}).encode()
                self.send_response(401)
                self.send_header("WWW-Authenticate", 'Basic realm="cruise-control"')
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                if app.access_log:
                    app.access_log.log(
                        self.client_address[0], "", method, self.path,
                        401, len(body),
                    )

            def _serve_ui(self, path: str):
                """Static UI files (reference serves cruise-control-ui from
                webserver.ui.diskpath under webserver.ui.urlprefix)."""
                import mimetypes
                import os

                rel = path[len(app.ui_prefix):].lstrip("/") or "index.html"
                root = os.path.realpath(app.ui_diskpath)
                full = os.path.realpath(os.path.join(root, rel))
                # realpath containment defeats ../ traversal
                if not (full == root or full.startswith(root + os.sep)) or not os.path.isfile(full):
                    self._send(404, {"errorMessage": "not found"})
                    return
                with open(full, "rb") as f:
                    body = f.read()
                ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                # same cross-cutting headers as _send: the session cookie
                # (sticky session->task rebind starts at the UI) and CORS
                for k, v in app.cors_headers.items():
                    self.send_header(k, v)
                if getattr(self, "_new_session_id", None):
                    self.send_header(
                        "Set-Cookie",
                        f"CCSESSION={self._new_session_id}; "
                        f"Path={app.session_path}; HttpOnly",
                    )
                self.end_headers()
                self.wfile.write(body)
                if app.access_log:
                    app.access_log.log(
                        self.client_address[0], "", "GET", path, 200, len(body)
                    )

            def do_GET(self):  # noqa: N802
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

            def do_OPTIONS(self):  # noqa: N802 — CORS preflight
                self.send_response(200 if app.cors_headers else 405)
                for k, v in app.cors_headers.items():
                    self.send_header(k, v)
                if app.cors_headers:
                    self.send_header(
                        "Access-Control-Allow-Headers",
                        "Authorization, Content-Type, " + USER_TASK_ID_HEADER,
                    )
                self.send_header("Content-Length", "0")
                self.end_headers()

        # TLS listener (reference KafkaCruiseControlApp.java:100-120 wraps the
        # Jetty connector in an SslContextFactory).  The handshake runs in
        # the PER-CONNECTION thread (finish_request), never the accept loop —
        # wrapping the listening socket would let one stalled client (open
        # TCP, no ClientHello) block every other request.
        ssl_ctx = None
        if self.config.get("webserver.ssl.enable"):
            import ssl

            cert = self.config.get("webserver.ssl.certificate.location")
            if not cert:
                raise ValueError(
                    "webserver.ssl.enable requires webserver.ssl.certificate.location"
                )
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            # reference webserver.ssl.protocol (WebServerConfig:226):
            # "TLS" keeps the library default; TLSv1.2/TLSv1.3 pin a floor
            proto = (self.config.get("webserver.ssl.protocol") or "TLS").upper()
            floors = {
                "TLSV1.2": ssl.TLSVersion.TLSv1_2,
                "TLSV1.3": ssl.TLSVersion.TLSv1_3,
            }
            if proto in floors:
                ssl_ctx.minimum_version = floors[proto]
            elif proto != "TLS":
                raise ValueError(
                    f"unsupported webserver.ssl.protocol {proto!r}; "
                    "use TLS, TLSv1.2 or TLSv1.3"
                )
            ssl_ctx.load_cert_chain(
                certfile=cert,
                keyfile=self.config.get("webserver.ssl.key.location") or None,
                password=self.config.get("webserver.ssl.key.password") or None,
            )

        class Server(ThreadingHTTPServer):
            def finish_request(self, request, client_address):
                if ssl_ctx is not None:
                    import ssl

                    try:
                        request.settimeout(30)  # bound the handshake
                        request = ssl_ctx.wrap_socket(request, server_side=True)
                        request.settimeout(None)
                    except (ssl.SSLError, OSError):
                        try:
                            request.close()
                        except OSError:
                            pass
                        return
                    # socketserver's shutdown_request only sees the pre-wrap
                    # socket; close the wrapped one here (sends close_notify)
                    try:
                        self.RequestHandlerClass(request, client_address, self)
                    finally:
                        try:
                            request.close()
                        except OSError:
                            pass
                    return
                self.RequestHandlerClass(request, client_address, self)

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.user_tasks.shutdown()


def _json_default(o):
    import numpy as _np

    if isinstance(o, (_np.integer,)):
        return int(o)
    if isinstance(o, (_np.floating,)):
        return float(o)
    if isinstance(o, _np.ndarray):
        return o.tolist()
    return str(o)
