"""Service bootstrap — assemble and start a full Cruise Control instance.

Reference: KafkaCruiseControlMain.java:26-40 (parse props file -> start app)
and KafkaCruiseControlApp.java:36-66.  `build_service` wires the stack for
any MetadataProvider/ClusterAdmin pair: real Kafka adapters in production,
the simulated backend in tests/demos (`build_simulated_service`).
"""

from __future__ import annotations

import sys

from cruise_control_tpu.config.app_config import CruiseControlConfig, load_properties
from cruise_control_tpu.monitor import (
    FixedCapacityResolver,
    KAFKA_METRIC_DEF,
    LoadMonitor,
    MetricFetcherManager,
    WindowedMetricSampleAggregator,
)
from cruise_control_tpu.monitor.capacity import (
    BrokerCapacityConfigResolver,
    FileCapacityResolver,
)
from cruise_control_tpu.service.facade import CruiseControl
from cruise_control_tpu.service.server import CruiseControlApp


def _build_cluster_stack(
    config: CruiseControlConfig,
    metadata,
    admin,
    sampler,
    *,
    sensors,
    capacity_resolver: BrokerCapacityConfigResolver | None = None,
    sample_store=None,
    partitions_fn=None,
    core=None,
    cluster_id: str | None = None,
    fence=None,
):
    """Wire ONE cluster's monitoring + facade stack: capacity resolver,
    aggregators, fetcher, monitor, task runner, and the CruiseControl
    facade.  `core`/`cluster_id` are the fleet seam — a shared
    AnalyzerCore makes this facade one tenant of a fleet; None keeps the
    classic self-contained build.  `fence` (fleet HA) is the cluster's
    lease fence — the journal stamps it and recovery defers to lease
    acquisition.  Returns (cc, fetcher, task_runner)."""
    if capacity_resolver is None:
        resolver_cls = config.get("broker.capacity.config.resolver.class")
        path = config.get("capacity.config.file")
        if resolver_cls is not None:
            # pluggable resolver (reference broker.capacity.config.resolver.class)
            capacity_resolver = resolver_cls(config)
        else:
            capacity_resolver = (
                FileCapacityResolver(path)
                if path
                else FixedCapacityResolver([100.0, 1e5, 1e5, 1e6])
            )
    partition_agg = WindowedMetricSampleAggregator(
        num_windows=config.get("num.partition.metrics.windows"),
        window_ms=config.get("partition.metrics.window.ms"),
        min_samples_per_window=config.get("min.samples.per.partition.metrics.window"),
        metric_def=KAFKA_METRIC_DEF,
    )
    broker_agg = WindowedMetricSampleAggregator(
        num_windows=config.get("num.broker.metrics.windows"),
        window_ms=config.get("broker.metrics.window.ms"),
        min_samples_per_window=config.get("min.samples.per.broker.metrics.window"),
        metric_def=KAFKA_METRIC_DEF,
    )
    assignor_cls = config.get("metric.sampler.partition.assignor.class")
    fetcher = MetricFetcherManager(
        sampler,
        partition_agg,
        broker_agg,
        sample_store=sample_store,
        sampling_interval_ms=config.get("metric.sampling.interval.ms"),
        num_fetchers=config.get("num.metric.fetchers"),
        assignor=assignor_cls() if assignor_cls is not None else None,
        sensors=sensors,
    )
    from cruise_control_tpu.monitor.cpu_model import LinearRegressionModelParameters
    from cruise_control_tpu.monitor.sampling import PartitionEntity
    from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner

    import re

    excluded_rx = re.compile(config.get("monitor.excluded.topics.pattern"))

    def topic_filter(name: str) -> bool:
        return not excluded_rx.match(str(name))

    # one knob governs every layer: samplers that support a topic filter
    # (CruiseControlMetricsReporterSampler) get the CONFIGURED pattern, not
    # their built-in default — otherwise the model and the sample stream
    # silently diverge on what "excluded" means
    if hasattr(sampler, "topic_filter"):
        sampler.topic_filter = topic_filter
    # reference sampling.allow.cpu.capacity.estimation: samplers that can
    # skip CPU attribution for CPU-less brokers get the configured flag
    if hasattr(sampler, "allow_cpu_estimation"):
        sampler.allow_cpu_estimation = config.get(
            "sampling.allow.cpu.capacity.estimation"
        )

    regression = LinearRegressionModelParameters(
        cpu_util_bucket_size=config.get("linear.regression.model.cpu.util.bucket.size"),
        required_samples_per_bucket=config.get(
            "linear.regression.model.required.samples.per.bucket"
        ),
        min_num_cpu_util_buckets=config.get(
            "linear.regression.model.min.num.cpu.util.buckets"
        ),
    )
    monitor = LoadMonitor(
        metadata, capacity_resolver, partition_agg,
        regression=regression, topic_filter=topic_filter,
        bucket_policy=config.shape_bucket_policy(),
        max_allowed_extrapolations=config.get(
            "max.allowed.extrapolations.per.partition"
        ),
        cpu_weights=(
            config.get("leader.network.inbound.weight.for.cpu.util"),
            config.get("leader.network.outbound.weight.for.cpu.util"),
            config.get("follower.network.inbound.weight.for.cpu.util"),
        ),
    )

    if partitions_fn is None:
        if hasattr(sampler, "all_partition_entities"):
            partitions_fn = sampler.all_partition_entities
        else:
            # derive entities from metadata, with the same first-appearance
            # topic-id mapping LoadMonitor._build_state uses (and the same
            # internal-topic exclusion)
            def partitions_fn():
                topo = metadata.topology()
                tids: dict = {}
                return [
                    PartitionEntity(tids.setdefault(p.topic, len(tids)), p.partition)
                    for p in topo.partitions
                    if topic_filter(p.topic)
                ]

    task_runner = LoadMonitorTaskRunner(
        monitor,
        fetcher,
        partitions_fn,
        window_ms=config.get("partition.metrics.window.ms"),
        regression=regression,
        auto_train=config.get("use.linear.regression.model"),
    )
    cc = CruiseControl(
        config, monitor, admin, sensors=sensors, core=core,
        cluster_id=cluster_id, fence=fence,
    )
    cc.task_runner = task_runner
    # warm restart: replay the sample store off the startup path (reference
    # SampleLoadingTask runs async; skip.loading.samples disables it)
    if sample_store is not None and not config.get("skip.loading.samples"):
        import threading

        threading.Thread(
            target=task_runner.load_samples,
            daemon=True,
            name=f"sample-loading{'-' + cluster_id if cluster_id else ''}",
        ).start()
    return cc, fetcher, task_runner


def build_service(
    config: CruiseControlConfig,
    metadata,
    admin,
    sampler,
    *,
    capacity_resolver: BrokerCapacityConfigResolver | None = None,
    sample_store=None,
    partitions_fn=None,
) -> tuple[CruiseControlApp, MetricFetcherManager]:
    from cruise_control_tpu.common.compilation_cache import enable_persistent_cache
    from cruise_control_tpu.common.sensors import SensorRegistry

    enable_persistent_cache(config.compile_cache_dir())
    # ONE registry shared by the fetcher and the facade stack — the monitor
    # health gauges must surface in /state?substates=sensors
    sensors = SensorRegistry()
    cc, fetcher, _task_runner = _build_cluster_stack(
        config, metadata, admin, sampler,
        sensors=sensors,
        capacity_resolver=capacity_resolver,
        sample_store=sample_store,
        partitions_fn=partitions_fn,
    )
    app = CruiseControlApp(cc)
    return app, fetcher


def build_fleet_service(
    config: CruiseControlConfig,
    backends: dict,
    *,
    sample_stores: dict | None = None,
    ha_clock=None,
) -> tuple[CruiseControlApp, "FleetManager"]:
    """ONE service instance over N Kafka clusters (fleet/manager.py).

    `backends`: {cluster_id: (metadata_provider, cluster_admin, sampler)}
    covering every id in `fleet.clusters`.  Builds ONE shared AnalyzerCore
    (optimizer + compiled-engine cache + device supervisor + scenario
    evaluator + tracer) and, per cluster, its own monitor/fetcher/executor
    stack from `config.cluster_config(id)` (base config + fleet.<id>.*
    overrides), a cluster-labeled SensorRegistry, and a journal under
    <executor.journal.dir>/<id>/.  Returns (app, fleet_manager).

    With `fleet.ha.enabled` (fleet/leases.py): a FileLeaseStore in
    <executor.journal.dir>/_leases shards ownership across the M
    instances pointed at the same journal dir — each cluster's admin is
    wrapped in a FencedClusterAdmin and its journal fenced on the lease
    epoch, and contexts only start once this instance holds the lease.
    `ha_clock` injects the instance clock (tests/benches)."""
    from cruise_control_tpu.common.compilation_cache import enable_persistent_cache
    from cruise_control_tpu.common.sensors import SensorRegistry
    from cruise_control_tpu.fleet.manager import ClusterContext, FleetManager
    from cruise_control_tpu.service.facade import AnalyzerCore

    ids = config.fleet_cluster_ids()
    if not ids:
        raise ValueError("build_fleet_service needs a non-empty fleet.clusters")
    missing = [cid for cid in ids if cid not in backends]
    if missing:
        raise ValueError(f"no backend supplied for fleet clusters {missing}")
    enable_persistent_cache(config.compile_cache_dir())
    shared_sensors = SensorRegistry()
    lease_manager = None
    if config.get("fleet.ha.enabled"):
        lease_manager = _build_lease_manager(
            config, ids, sensors=shared_sensors, clock=ha_clock
        )
    core = AnalyzerCore(config, sensors=shared_sensors)
    contexts: dict[str, ClusterContext] = {}
    for cid in ids:
        metadata, admin, sampler = backends[cid]
        fence = None
        if lease_manager is not None:
            from cruise_control_tpu.executor.admin import FencedClusterAdmin

            fence = lease_manager.fence(cid)
            # every cluster mutation this instance ever issues rides the
            # fenced wrapper — a lost lease turns the whole admin surface
            # read-only at the SPI boundary
            admin = FencedClusterAdmin(admin, fence)
        cc, fetcher, task_runner = _build_cluster_stack(
            config.cluster_config(cid), metadata, admin, sampler,
            sensors=SensorRegistry(base_labels={"cluster": cid}),
            sample_store=(sample_stores or {}).get(cid),
            core=core,
            cluster_id=cid,
            fence=fence,
        )
        contexts[cid] = ClusterContext(
            cid, cc, fetcher=fetcher, task_runner=task_runner
        )
    fleet = FleetManager(
        core, contexts, sensors=shared_sensors, config=config,
        lease_manager=lease_manager,
    )
    app = CruiseControlApp(contexts[ids[0]].cc, fleet=fleet)
    return app, fleet


def _build_lease_manager(config, cluster_ids, *, sensors, clock=None):
    """FileLeaseStore + LeaseManager from the fleet.ha.* keys; the store
    lives in <executor.journal.dir>/_leases (the journal dir IS the
    fleet's shared durable state — requiring it keeps the HA story on
    one mount)."""
    import os
    import socket

    from cruise_control_tpu.fleet.leases import FileLeaseStore, LeaseManager

    journal_dir = config.get("executor.journal.dir")
    if not journal_dir:
        raise ValueError(
            "fleet.ha.enabled requires executor.journal.dir: the lease "
            "store lives in <journal.dir>/_leases and a takeover replays "
            "the dead holder's journal from the same mount"
        )
    instance_id = config.get("fleet.ha.instance.id") or (
        f"{socket.gethostname()}-{os.getpid()}"
    )
    skew = config.get("fleet.ha.skew.slack.s")
    store = FileLeaseStore(
        os.path.join(os.path.expanduser(journal_dir), "_leases"),
        skew_slack_s=skew,
        clock=clock,
    )
    return LeaseManager(
        store,
        cluster_ids,
        holder_id=instance_id,
        ttl_s=config.get("fleet.ha.lease.ttl.s"),
        renew_s=config.get("fleet.ha.renew.s"),
        skew_slack_s=skew,
        clock=clock,
        sensors=sensors,
    )


def parse_bootstrap_servers(bootstrap_servers: str) -> list[tuple[str, int]]:
    """Parse a Kafka bootstrap list ("h1:9092,h2") into (host, port) seeds.

    Supports bracketed IPv6 ("[::1]:9092", "[::1]") and bare IPv6 literals
    without a port ("::1") — rpartition(':') alone would split those wrong.
    """
    seeds = []
    for hp in bootstrap_servers.split(","):
        hp = hp.strip()
        if not hp:
            continue
        if hp.startswith("["):  # bracketed IPv6: [::1] or [::1]:9092
            addr, sep, rest = hp[1:].partition("]")
            if not sep or (rest and not rest.startswith(":")):
                raise ValueError(f"malformed bootstrap server {hp!r}")
            host, port = addr, (rest[1:] or "9092")
        elif hp.count(":") > 1:  # bare IPv6 literal, no port
            import ipaddress

            try:  # reject comma typos like "h1:9092:h2:9093" fast
                ipaddress.ip_address(hp)
            except ValueError:
                raise ValueError(f"malformed bootstrap server {hp!r}") from None
            host, port = hp, "9092"
        else:
            host, sep, port = hp.rpartition(":")
            if not sep:  # bare hostname: Kafka's default port shorthand
                host, port = hp, "9092"
        if not port.isdigit():
            raise ValueError(f"malformed bootstrap server {hp!r}")
        seeds.append((host or "127.0.0.1", int(port)))
    if not seeds:
        raise ValueError(f"no bootstrap servers in {bootstrap_servers!r}")
    return seeds


def sasl_credentials_from_config(config: CruiseControlConfig):
    """SaslCredentials from sasl.* keys (None when SASL is off) — EVERY
    client a deployment opens (admin, metrics consumer) must authenticate
    the same way (sasl.password.file wins over sasl.password)."""
    if not config.get("sasl.mechanism"):
        return None
    from cruise_control_tpu.kafka.sasl import SaslCredentials

    password = config.get("sasl.password")
    pw_file = config.get("sasl.password.file")
    if pw_file:
        with open(pw_file) as f:
            password = f.read().strip()
    if not config.get("sasl.username") or password is None:
        raise ValueError(
            "sasl.mechanism set but sasl.username/sasl.password missing"
        )
    return SaslCredentials(
        username=config.get("sasl.username"),
        password=password,
        mechanism=config.get("sasl.mechanism"),
    )


def build_kafka_service(
    config: CruiseControlConfig,
    bootstrap_servers: str,
    sampler,
    *,
    client_id: str = "cruise-control-tpu",
    sample_store=None,
):
    """Service against a LIVE Kafka cluster over the wire-protocol adapters
    (kafka/admin.py): metadata + reassignments + elections + logdir moves +
    throttles all ride the binary protocol — no JVM, no ZooKeeper
    (reference KafkaCruiseControlMain + the ZK/Scala bridge it starts).

    `sampler` supplies partition/broker load samples (MetricSampler SPI,
    monitor/sampling.py).  The stock choice is
    CruiseControlMetricsReporterSampler fed by a transport that consumes
    the reporter topic (reporter/reporter.py Transport SPI).
    """
    from cruise_control_tpu.kafka import (
        KafkaAdminClient,
        KafkaClusterAdmin,
        KafkaMetadataProvider,
    )

    sasl = sasl_credentials_from_config(config)
    client = KafkaAdminClient(
        parse_bootstrap_servers(bootstrap_servers), client_id=client_id, sasl=sasl
    )
    # fail fast with the full list of unsupported APIs rather than on the
    # first mid-operation decode error against an old broker
    client.check_api_support()
    metadata = KafkaMetadataProvider(client)
    admin = KafkaClusterAdmin(client)
    app, fetcher = build_service(
        config, metadata, admin, sampler, sample_store=sample_store
    )
    return app, fetcher, admin, client


def build_simulated_service(
    config: CruiseControlConfig | None = None,
    *,
    num_brokers: int = 6,
    topics: dict[str, int] | None = None,
    seed: int = 0,
    sampled_windows: int = 3,
):
    """Full in-process service against the simulated cluster (the embedded
    harness analog, reference CruiseControlIntegrationTestHarness)."""
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import (
        SyntheticWorkloadSampler,
        synthetic_topology,
    )

    config = config or CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "num.partition.metrics.windows": max(3, sampled_windows),
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,  # ephemeral
            "tpu.num.candidates": 128,
            "tpu.leadership.candidates": 32,
            "tpu.steps.per.round": 16,
            "tpu.num.rounds": 2,
        }
    )
    topo = synthetic_topology(num_brokers=num_brokers, topics=topics or {"T0": 12, "T1": 12},
                              seed=seed)
    metadata = StaticMetadataProvider(topo)
    admin = SimulatedClusterAdmin(metadata, link_rate_bytes_per_s=1e12)
    sampler = SyntheticWorkloadSampler(topo, seed=seed)
    app, fetcher = build_service(config, metadata, admin, sampler)
    window_ms = config.get("partition.metrics.window.ms")
    parts = sampler.all_partition_entities()
    for w in range(sampled_windows + 1):
        fetcher.fetch_once(parts, w * window_ms, (w + 1) * window_ms - 1)
    return app, fetcher, admin, sampler


def build_simulated_fleet(
    props: dict | None = None,
    *,
    clusters: dict[str, dict] | None = None,
    seed: int = 0,
    sampled_windows: int = 3,
    backends: dict | None = None,
    ha_clock=None,
):
    """Full in-process FLEET over N simulated clusters — the embedded
    harness for fleet tests and `bench.py --fleet-smoke`/`--ha-smoke`.

    `clusters`: {cluster_id: synthetic_topology kwargs}; the default is 3
    clusters, two of which share a bucketed model shape (so they must
    share one compiled engine through the fleet's AnalyzerCore).
    `backends`: pre-built {cluster_id: (metadata, admin, sampler)} —
    fleet-HA harnesses pass the SAME backends to two instances so both
    "see" one set of simulated Kafka clusters."""
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import (
        SyntheticWorkloadSampler,
        synthetic_topology,
    )

    clusters = clusters or {
        # east/west: identical geometry -> identical shape bucket -> ONE
        # compiled engine serves both
        "east": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
        "west": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
        # south: a different bucket, its own engine
        "south": dict(num_brokers=12, topics={"T0": 48, "T1": 48}),
    }
    base = {
        "fleet.clusters": ",".join(clusters),
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": max(3, sampled_windows),
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,  # ephemeral
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
    }
    base.update(props or {})
    config = CruiseControlConfig(base)
    if backends is None:
        backends = {}
        for i, (cid, spec) in enumerate(clusters.items()):
            topo = synthetic_topology(seed=seed + i, **spec)
            metadata = StaticMetadataProvider(topo)
            admin = SimulatedClusterAdmin(metadata, link_rate_bytes_per_s=1e12)
            sampler = SyntheticWorkloadSampler(topo, seed=seed + i)
            backends[cid] = (metadata, admin, sampler)
    app, fleet = build_fleet_service(config, backends, ha_clock=ha_clock)
    window_ms = config.get("partition.metrics.window.ms")
    for cid, ctx in fleet.contexts.items():
        parts = backends[cid][2].all_partition_entities()
        for w in range(sampled_windows + 1):
            ctx.fetcher.fetch_once(parts, w * window_ms, (w + 1) * window_ms - 1)
    return app, fleet


def _kafka_cluster_backend(ccfg: CruiseControlConfig, bootstrap: str):
    """(metadata, admin, sampler) + clients for one LIVE Kafka cluster of a
    fleet, wired exactly like the single-cluster main() path."""
    from cruise_control_tpu.kafka import (
        KafkaAdminClient,
        KafkaClusterAdmin,
        KafkaMetadataProvider,
    )
    from cruise_control_tpu.kafka.transport import KafkaMetricsConsumer
    from cruise_control_tpu.monitor.reporter_sampler import (
        CruiseControlMetricsReporterSampler,
    )

    sasl = sasl_credentials_from_config(ccfg)
    client = KafkaAdminClient(parse_bootstrap_servers(bootstrap), sasl=sasl)
    client.check_api_support()
    metadata = KafkaMetadataProvider(client)
    admin = KafkaClusterAdmin(client)
    serde = None
    if ccfg.get("cruise.control.metrics.serde.format") == "reference":
        from cruise_control_tpu.reporter.metrics import ReferenceMetricSerde

        serde = ReferenceMetricSerde
    consumer_client = KafkaAdminClient(
        parse_bootstrap_servers(bootstrap), sasl=sasl
    )
    sampler = CruiseControlMetricsReporterSampler(
        KafkaMetricsConsumer(
            consumer_client, ccfg.get("cruise.control.metrics.topic"), serde=serde
        ),
        metadata.topology,
    )
    return (metadata, admin, sampler), [client, consumer_client]


def main(argv=None):  # pragma: no cover — manual entry point
    """Operator entry (reference KafkaCruiseControlMain.java:26-40):
    `python -m cruise_control_tpu.service.main config/cruisecontrol.properties`.

    With `bootstrap.servers` set, runs against the live Kafka cluster over
    the wire-protocol adapters, consuming the metrics-reporter topic in
    the configured serde format; without it, boots the simulated demo
    cluster."""
    argv = argv if argv is not None else sys.argv[1:]
    props = load_properties(argv[0]) if argv else {}
    config = CruiseControlConfig(props)
    if config.fleet_cluster_ids():
        # fleet mode: ONE instance over every cluster in fleet.clusters;
        # each cluster's bootstrap.servers comes from its
        # fleet.<id>.bootstrap.servers override (or the base key)
        backends = {}
        clients = []
        for cid in config.fleet_cluster_ids():
            ccfg = config.cluster_config(cid)
            cluster_bootstrap = ccfg.values().get("bootstrap.servers")
            if not cluster_bootstrap:
                raise SystemExit(
                    f"fleet cluster {cid!r} has no bootstrap.servers "
                    f"(set fleet.{cid}.bootstrap.servers)"
                )
            backends[cid], cluster_clients = _kafka_cluster_backend(
                ccfg, cluster_bootstrap
            )
            clients.extend(cluster_clients)
        app, fleet = build_fleet_service(config, backends)
        fleet.start_up(precompute=True)
        for ctx in fleet.contexts.values():
            ctx.fetcher.start(
                lambda fn=ctx.task_runner.partitions_fn: fn()
            )
        app.start()
        print(
            f"cruise-control-tpu fleet ({len(fleet.contexts)} clusters) "
            f"listening on {app.host}:{app.port}{app.prefix}"
        )
        try:
            import time

            while True:
                time.sleep(60)
        except KeyboardInterrupt:
            fleet.shutdown()
            app.stop()
            for client in clients:
                client.close()
        return
    bootstrap = props.get("bootstrap.servers")
    if bootstrap:
        from cruise_control_tpu.kafka import KafkaAdminClient
        from cruise_control_tpu.kafka.transport import KafkaMetricsConsumer
        from cruise_control_tpu.monitor.reporter_sampler import (
            CruiseControlMetricsReporterSampler,
        )

        serde = None
        if config.get("cruise.control.metrics.serde.format") == "reference":
            from cruise_control_tpu.reporter.metrics import ReferenceMetricSerde

            serde = ReferenceMetricSerde
        # one extra client for the metrics data plane (fetch volume must
        # not contend with admin calls) — authenticated like the admin
        # client; topology comes from the SERVICE's own metadata provider
        # (monitor.metadata), not a third connection pool
        consumer_client = KafkaAdminClient(
            parse_bootstrap_servers(bootstrap),
            sasl=sasl_credentials_from_config(config),
        )
        monitor_meta: list = []
        sampler = CruiseControlMetricsReporterSampler(
            KafkaMetricsConsumer(
                consumer_client,
                config.get("cruise.control.metrics.topic"),
                serde=serde,
            ),
            lambda: monitor_meta[0].topology(),
        )
        app, fetcher, _admin, _client = build_kafka_service(
            config, bootstrap, sampler
        )
        monitor_meta.append(app.cc.monitor.metadata)
        partitions_fn = app.cc.task_runner.partitions_fn
    else:
        app, fetcher, _admin, sim_sampler = build_simulated_service(config)
        partitions_fn = sim_sampler.all_partition_entities
    app.cc.start_up(precompute=True)
    fetcher.start(lambda: partitions_fn())
    app.start()
    print(f"cruise-control-tpu listening on {app.host}:{app.port}{app.prefix}")
    try:
        import time

        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        app.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
