"""Declared response schemas for every REST endpoint.

Reference: servlet/response/JsonResponseField.java:1 annotates every
response class's fields and ResponseTest.java:1 asserts each response
declares its schema — API drift fails a test instead of surprising
clients.  Here the declaration is data (FIELDS per endpoint) and
`validate_response` is the single checker the schema test drives against
a LIVE service (tests/test_schemas.py).

A schema lists top-level fields: (name, types, required).  `item_schema`
validates dict items of list fields one level down.  Endpoints whose
successful body is an operation summary share OPTIMIZATION_RESULT.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    types: tuple
    required: bool = True
    item_schema: "Schema | None" = None  # for list fields holding dicts


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple
    #: False -> unknown top-level keys are schema violations
    allow_extra: bool = False

    def field_names(self):
        return {f.name for f in self.fields}


NUM = (int, float)
STR = (str,)
BOOL = (bool,)
LIST = (list,)
DICT = (dict,)

PROPOSAL_ITEM = Schema((
    Field("topicPartition", DICT),
    Field("oldLeader", NUM),
    Field("oldReplicas", LIST),
    Field("newReplicas", LIST),
))

#: shared summary of every optimization-shaped response
#: (OptimizerResult.summary() + facade additions)
OPTIMIZATION_RESULT = Schema((
    Field("numReplicaMovements", NUM),
    Field("numLeaderMovements", NUM),
    Field("dataToMoveMB", NUM),
    Field("balancednessBefore", NUM),
    Field("balancednessAfter", NUM),
    Field("objectiveBefore", NUM),
    Field("objectiveAfter", NUM),
    Field("violatedGoalsAfter", LIST),
    Field("wallSeconds", NUM),
    # true when the supervisor breaker routed this answer through the CPU
    # greedy fallback (docs/architecture.md "Degraded mode")
    Field("degraded", BOOL),
    # per-phase execution ETA derived from data-to-move over the active
    # caps/throttle (facade._execution_eta); absent on demote (leader-only)
    Field("estimatedExecutionTime", DICT, required=False),
    Field("proposals", LIST, item_schema=PROPOSAL_ITEM),
    Field("execution", DICT, required=False),
    Field("_userTaskId", STR, required=False),
))

BROKER_LOAD_ITEM = Schema((
    Field("Broker", NUM),
    Field("BrokerState", STR),
    Field("Leaders", NUM),
    Field("Replicas", NUM),
    Field("CPU", NUM), Field("CPUPct", NUM),
    Field("DISK", NUM), Field("DISKPct", NUM),
    Field("NW_IN", NUM), Field("NW_INPct", NUM),
    Field("NW_OUT", NUM), Field("NW_OUTPct", NUM),
))

#: one scenario's outcome in the /simulate response
#: (analyzer/scenario_eval.py ScenarioOutcome.to_json)
SCENARIO_OUTCOME_ITEM = Schema((
    Field("name", STR),
    Field("objective", NUM),
    Field("violatedGoals", LIST),
    Field("balancedness", NUM),
    Field("hardGoalsSatisfied", BOOL),
    Field("brokersAlive", NUM),
    # present when optimize=true: OptimizerResult.summary() + hard-goal
    # verdict for the projected post-fix cluster
    Field("fix", DICT, required=False),
))

#: one annealed candidate in the /rightsize response
RIGHTSIZE_CANDIDATE_ITEM = Schema((
    Field("brokers", NUM),
    Field("feasible", BOOL),
    Field("violatedHardGoals", LIST),
    Field("objectiveAfter", NUM),
    Field("numMoves", NUM),
))

RESPONSE_SCHEMAS: dict[str, Schema] = {
    "state": Schema((
        Field("version", NUM, required=False),  # API-version negotiation
        Field("MonitorState", DICT, required=False),
        Field("ExecutorState", DICT, required=False),
        Field("AnalyzerState", DICT, required=False),
        # streaming-controller block (controller/streaming.py), present
        # only when controller.enabled
        Field("ControllerState", DICT, required=False),
        Field("AnomalyDetectorState", DICT, required=False),
        Field("Sensors", DICT, required=False),
    )),
    "kafka_cluster_state": Schema((
        Field("KafkaBrokerState", DICT),
        Field("KafkaPartitionState", DICT),
    )),
    "load": Schema((
        Field("brokers", LIST, item_schema=BROKER_LOAD_ITEM),
        Field("hosts", LIST),
        Field("_userTaskId", STR, required=False),
    )),
    "partition_load": Schema((
        Field("records", LIST),
        Field("resource", STR),
        Field("_userTaskId", STR, required=False),
    )),
    "proposals": OPTIMIZATION_RESULT,
    "rebalance": OPTIMIZATION_RESULT,
    "add_broker": OPTIMIZATION_RESULT,
    "remove_broker": Schema(
        tuple(f for f in OPTIMIZATION_RESULT.fields if f.name != "proposals")
    ),
    "fix_offline_replicas": OPTIMIZATION_RESULT,
    "demote_broker": Schema((
        Field("numLeaderMovements", NUM),
        Field("proposals", LIST, item_schema=PROPOSAL_ITEM),
        Field("execution", DICT, required=False),
        Field("_userTaskId", STR, required=False),
    )),
    "topic_configuration": Schema((
        Field("numProposals", NUM),
        Field("proposals", LIST, item_schema=PROPOSAL_ITEM),
        Field("execution", DICT, required=False),
        Field("_userTaskId", STR, required=False),
    )),
    "user_tasks": Schema((
        Field("userTasks", LIST, item_schema=Schema((
            Field("UserTaskId", STR),
            Field("RequestURL", STR),
            Field("ClientIdentity", STR),
            Field("Status", STR),
            Field("StartMs", NUM),
            # flight-recorder trace id of the operation (empty when
            # tracing is disabled)
            Field("TraceId", STR, required=False),
            # fleet cluster the operation targeted (empty single-cluster)
            Field("Cluster", STR, required=False),
        ))),
    )),
    "review_board": Schema((Field("requestInfo", LIST),)),
    "review": Schema((Field("requestInfo", LIST),)),
    "bootstrap": Schema((
        Field("mode", STR),
        Field("samplesAbsorbed", NUM),
        Field("monitorState", STR),
        Field("bootstrapProgressPct", NUM),
        Field("trainingState", DICT),
        Field("totalSamples", NUM),
        Field("_userTaskId", STR, required=False),
    )),
    "train": Schema((
        Field("trained", BOOL),
        Field("_userTaskId", STR, required=False),
    ), allow_extra=True),  # regression state keys are the model's business
    "stop_proposal_execution": Schema((
        Field("message", STR),
        Field("force", BOOL),
    )),
    "pause_sampling": Schema((Field("message", STR),)),
    "resume_sampling": Schema((Field("message", STR),)),
    "admin": Schema((
        Field("selfHealingEnabled", LIST, required=False),
        Field("recentlyRemovedBrokers", LIST, required=False),
        Field("recentlyDemotedBrokers", LIST, required=False),
        # mid-execution concurrency change acknowledgment
        Field("requestedConcurrency", DICT, required=False),
        Field("ongoingExecution", BOOL, required=False),
    )),
    # --- scenario planner ---
    "simulate": Schema((
        Field("scenarios", LIST, item_schema=SCENARIO_OUTCOME_ITEM),
        # the unmutated cluster scored the same way, for contrast
        Field("baseline", DICT),
        # true when the device breaker routed scoring through the CPU path
        Field("degraded", BOOL),
        Field("wallSeconds", NUM),
        Field("_userTaskId", STR, required=False),
    )),
    "rightsize": Schema((
        Field("provisionStatus", STR),
        Field("currentBrokers", NUM),
        Field("minBrokers", NUM),  # null when the search ended UNDECIDED
        # UNDECIDED only: feasible count the unfinished search proved
        Field("minBrokersUpperBound", NUM),
        Field("searchedRange", LIST),
        Field("annealsRun", NUM),
        Field("undecided", BOOL),
        Field("degraded", BOOL),
        Field("preMoveViolations", DICT),
        Field("candidates", LIST, item_schema=RIGHTSIZE_CANDIDATE_ITEM),
        Field("loadScenario", DICT, required=False),
        # fitted trend scenarios at the planner.forecast.horizons.ms
        # horizons (no extra anneals; empty without enough history)
        Field("forecastOutlook", LIST),
        Field("forecast", DICT, required=False),
        Field("wallSeconds", NUM),
        Field("_userTaskId", STR, required=False),
    )),
    # --- observability ---
    # GET /trace: with ?id= the replayed span forest; without, an index of
    # recent root traces.  Exactly one of the two shapes appears.
    "trace": Schema((
        Field("traceId", STR, required=False),
        Field("spans", LIST, required=False),
        Field("traces", LIST, required=False),
        # ?blackbox=true: the on-disk dispatch spool's state/tail/
        # in-flight view (common/blackbox.py)
        Field("blackbox", DICT, required=False),
    )),
    # GET /metrics is TEXT (Prometheus exposition 0.0.4), not JSON — the
    # schema entry satisfies the full-coverage gate; the body itself is
    # validated by the exposition lint parser (common/exposition.py,
    # scripts/check.sh gate)
    "metrics": Schema((), allow_extra=True),
    # --- fleet controller ---
    # GET /fleet: whole-instance rollup — per-cluster summaries under
    # `clusters` (each carrying an `ownership` block in fleet-HA mode),
    # the shared-core view (engine cache, supervisor, admission control)
    # under `shared`, with ?score=true the batched per-cluster placement
    # scores under `scores`, and in fleet-HA mode the instance's lease
    # view (instanceId, ttl/renew/skew, ownedClusters) under `ha`
    "fleet": Schema((
        Field("numClusters", NUM),
        Field("clusters", DICT),
        Field("shared", DICT),
        Field("scores", DICT, required=False),
        Field("ha", DICT, required=False),
    )),
    # GET /slo: per-cluster SLO registry state (burn rates, compliance,
    # episode status), single-cluster deployments under "default" —
    # common/slo.py
    "slo": Schema((
        Field("numClusters", NUM),
        Field("clusters", DICT),
    )),
    # --- decision ledger (analyzer/ledger.py) ---
    # GET /explain: one ledger episode replayed as a structured
    # explanation — goal deltas, top moves, convergence curve, plus the
    # outcome and calibration records when the episode progressed that far
    "explain": Schema((
        Field("decisionId", STR),
        Field("traceId", STR),
        Field("cluster", STR),
        Field("source", STR),
        Field("workClass", STR),
        Field("computedMs", NUM),
        Field("generation", DICT, required=False),
        Field("bucket", DICT, required=False),
        Field("degraded", BOOL),
        Field("goalDeltas", LIST, item_schema=Schema((
            Field("goal", STR),
            Field("before", NUM),
            Field("after", NUM),
            Field("delta", NUM),
        ))),
        Field("objective", DICT),
        Field("balancedness", DICT),
        Field("numReplicaMovements", NUM),
        Field("numLeaderMovements", NUM),
        Field("dataToMoveMB", NUM),
        Field("topMoves", LIST),
        # engine convergence diagnostics (null when the decision was
        # computed with analyzer.diagnostics.enabled=false)
        Field("convergence", DICT, required=False),
        Field("predictedLoad", DICT, required=False),
        # execution outcome / predicted-vs-measured calibration: null
        # until the episode reaches that stage
        Field("outcome", DICT, required=False),
        Field("calibration", DICT, required=False),
    )),
    # GET /ledger: the raw joined episode stream + the store's state
    "ledger": Schema((
        Field("enabled", BOOL),
        Field("entries", LIST),
        Field("state", DICT, required=False),
    )),
}

#: non-200 body shapes (shared by every endpoint)
ASYNC_PROGRESS_SCHEMA = Schema((  # 202
    Field("progress", LIST),
    Field("_userTaskId", STR),
    Field("_traceId", STR, required=False),
))
ERROR_SCHEMA = Schema((  # 4xx/5xx
    Field("errorMessage", STR),
    Field("_userTaskId", STR, required=False),
), allow_extra=True)


def validate_response(endpoint: str, payload: dict, *, status: int = 200) -> list[str]:
    """-> list of schema violations (empty = conforming)."""
    if status == 202:
        schema = ASYNC_PROGRESS_SCHEMA
    elif status >= 400:
        schema = ERROR_SCHEMA
    else:
        schema = RESPONSE_SCHEMAS.get(endpoint)
        if schema is None:
            return [f"no declared schema for endpoint {endpoint!r}"]
    return _check(schema, payload, where=endpoint)


def _check(schema: Schema, payload, *, where: str) -> list[str]:
    problems: list[str] = []
    if not isinstance(payload, dict):
        return [f"{where}: expected object, got {type(payload).__name__}"]
    for f in schema.fields:
        if f.name not in payload:
            if f.required:
                problems.append(f"{where}: missing required field {f.name!r}")
            continue
        v = payload[f.name]
        if v is not None and not isinstance(v, f.types):
            problems.append(
                f"{where}.{f.name}: expected {'/'.join(t.__name__ for t in f.types)},"
                f" got {type(v).__name__}"
            )
            continue
        if f.item_schema is not None and isinstance(v, list):
            for i, item in enumerate(v[:5]):  # spot-check the head
                problems += _check(f.item_schema, item, where=f"{where}.{f.name}[{i}]")
    if not schema.allow_extra:
        # _userTaskId/_traceId are cross-cutting rider fields every async
        # response carries (poll resume + flight-recorder correlation)
        extra = set(payload) - schema.field_names() - {"_userTaskId", "_traceId"}
        if extra:
            problems.append(f"{where}: undeclared fields {sorted(extra)}")
    return problems
