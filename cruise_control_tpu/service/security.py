"""Pluggable security providers: basic auth, JWT, roles, sessions.

Reference: servlet/security/SecurityProvider.java (SPI),
BasicSecurityProvider.java (credentials file with roles),
jwt/JwtAuthenticator.java + JwtLoginService.java (token auth),
servlet/SessionManager.java (session -> task binding with expiry).

JWT here is HS256 via stdlib hmac — no external dependency; RS256 key
loading can be plugged behind the same provider SPI.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import uuid
from typing import Protocol

# roles (reference DefaultRoleSecurityProvider: VIEWER/USER/ADMIN)
VIEWER = "VIEWER"
USER = "USER"
ADMIN = "ADMIN"

#: minimum role required per endpoint type (reference CruiseControlEndpointType)
ENDPOINT_ROLE = {
    "GET": VIEWER,
    "POST": ADMIN,
}
_ROLE_RANK = {VIEWER: 0, USER: 1, ADMIN: 2}


class SecurityProvider(Protocol):
    """Reference servlet/security/SecurityProvider.java."""

    def authenticate(self, headers) -> tuple[str, str] | None:
        """-> (principal, role) or None if unauthenticated."""

    def authorize(self, role: str, method: str, endpoint: str) -> bool:
        ...


class AllowAllSecurityProvider:
    def authenticate(self, headers):
        return ("anonymous", ADMIN)

    def authorize(self, role, method, endpoint):
        return True


class BasicSecurityProvider:
    """Credentials file: `user:password[:role]` lines
    (reference BasicSecurityProvider + basic-auth.credentials fixture)."""

    def __init__(self, credentials_path: str):
        self._users: dict[str, tuple[str, str]] = {}
        with open(credentials_path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(":", 2)
                if len(parts) < 2:
                    raise ValueError(
                        f"{credentials_path}:{lineno}: expected user:password[:role]"
                    )
                user, pw = parts[0], parts[1]
                role = parts[2].strip().upper() if len(parts) > 2 else ADMIN
                if role not in _ROLE_RANK:
                    raise ValueError(
                        f"{credentials_path}:{lineno}: unknown role {role!r} "
                        f"(expected one of {sorted(_ROLE_RANK)})"
                    )
                self._users[user] = (pw, role)

    def authenticate(self, headers):
        header = headers.get("Authorization", "")
        if not header.startswith("Basic "):
            return None
        try:
            user, _, pw = base64.b64decode(header[6:]).decode().partition(":")
        except Exception:  # noqa: BLE001
            return None
        entry = self._users.get(user)
        if entry is None or not hmac.compare_digest(entry[0], pw):
            return None
        return (user, entry[1])

    def authorize(self, role, method, endpoint):
        return _ROLE_RANK.get(role, -1) >= _ROLE_RANK[ENDPOINT_ROLE.get(method, ADMIN)]


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def jwt_encode(claims: dict, secret: str) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing = f"{header}.{payload}".encode()
    sig = _b64url(hmac.new(secret.encode(), signing, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def jwt_decode(token: str, secret: str) -> dict | None:
    try:
        header, payload, sig = token.split(".")
        signing = f"{header}.{payload}".encode()
        expected = _b64url(hmac.new(secret.encode(), signing, hashlib.sha256).digest())
        if not hmac.compare_digest(expected, sig):
            return None
        claims = json.loads(_b64url_decode(payload))
    except Exception:  # noqa: BLE001
        return None
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        return None
    return claims


def jwt_decode_rs256(token: str, public_key) -> dict | None:
    """Verify an RS256 (RSASSA-PKCS1-v1_5 / SHA-256) JWT against a public
    key — certificate-based tokens, reference
    servlet/security/jwt/JwtAuthenticator.java:1 (shared-secret HS256 across
    services is a deployment blocker; the issuer signs with its private key
    and the service verifies with the cert)."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        if header.get("alg") != "RS256":
            return None
        public_key.verify(
            _b64url_decode(sig_b64),
            f"{header_b64}.{payload_b64}".encode(),
            padding.PKCS1v15(),
            hashes.SHA256(),
        )
        claims = json.loads(_b64url_decode(payload_b64))
    except InvalidSignature:
        return None
    except Exception:  # noqa: BLE001 — malformed token shapes
        return None
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        return None
    return claims


def extract_bearer_token(headers, cookie_name: str | None = None) -> str | None:
    """Token from `Authorization: Bearer ...`, else from the configured
    cookie (reference WebServerConfig jwt.cookie.name; header wins)."""
    header = headers.get("Authorization", "")
    if header.startswith("Bearer "):
        return header[7:]
    if cookie_name:
        from http.cookies import SimpleCookie

        jar = SimpleCookie()
        try:
            jar.load(headers.get("Cookie", ""))
        except Exception:  # noqa: BLE001 — malformed cookie header
            return None
        morsel = jar.get(cookie_name)
        if morsel is not None:
            return morsel.value
    return None


def audience_ok(claims: dict, expected: list[str] | None) -> bool:
    """aud claim must intersect the configured audiences when set
    (reference JwtAuthenticator expected-audiences check)."""
    if not expected:
        return True
    aud = claims.get("aud")
    if aud is None:
        return False
    auds = {aud} if isinstance(aud, str) else set(aud)
    return bool(auds & set(expected))


def load_public_key(pem_path: str):
    """Load an RSA public key from a PEM file holding either a bare public
    key or an X.509 certificate (the reference's JwtLoginService takes a
    certificate)."""
    from cryptography.hazmat.primitives.serialization import load_pem_public_key
    from cryptography.x509 import load_pem_x509_certificate

    with open(pem_path, "rb") as f:
        data = f.read()
    if b"CERTIFICATE" in data:
        return load_pem_x509_certificate(data).public_key()
    return load_pem_public_key(data)


class JwtRs256SecurityProvider:
    """Public-key bearer-token auth (reference servlet/security/jwt/
    JwtAuthenticator.java:1 + JwtLoginService certificate verification).

    The service holds only the PUBLIC key/certificate
    (jwt.authentication.certificate.location); tokens are minted elsewhere
    with the private key — no shared secret crosses service boundaries.
    """

    def __init__(
        self,
        certificate_path: str,
        *,
        default_role: str = USER,
        cookie_name: str | None = None,
        expected_audiences: list[str] | None = None,
    ):
        self.public_key = load_public_key(certificate_path)
        self.default_role = default_role
        self.cookie_name = cookie_name
        self.expected_audiences = expected_audiences or None

    def authenticate(self, headers):
        token = extract_bearer_token(headers, self.cookie_name)
        if token is None:
            return None
        claims = jwt_decode_rs256(token, self.public_key)
        if claims is None or not audience_ok(claims, self.expected_audiences):
            return None
        return (claims.get("sub", "unknown"), claims.get("role", self.default_role))

    def authorize(self, role, method, endpoint):
        return _ROLE_RANK.get(role, -1) >= _ROLE_RANK[ENDPOINT_ROLE.get(method, ADMIN)]


class JwtSecurityProvider:
    """HS256 bearer-token auth (reference servlet/security/jwt/).

    Expects `Authorization: Bearer <jwt>` with claims {sub, role, exp}.
    `issue()` mints tokens for tests/trusted issuers.
    """

    def __init__(
        self,
        secret: str,
        *,
        default_role: str = USER,
        cookie_name: str | None = None,
        expected_audiences: list[str] | None = None,
    ):
        self.secret = secret
        self.default_role = default_role
        self.cookie_name = cookie_name
        self.expected_audiences = expected_audiences or None

    def issue(self, subject: str, role: str = ADMIN, ttl_s: int = 3600) -> str:
        return jwt_encode(
            {"sub": subject, "role": role, "exp": time.time() + ttl_s}, self.secret
        )

    def authenticate(self, headers):
        token = extract_bearer_token(headers, self.cookie_name)
        if token is None:
            return None
        claims = jwt_decode(token, self.secret)
        if claims is None or not audience_ok(claims, self.expected_audiences):
            return None
        return (claims.get("sub", "unknown"), claims.get("role", self.default_role))

    def authorize(self, role, method, endpoint):
        return _ROLE_RANK.get(role, -1) >= _ROLE_RANK[ENDPOINT_ROLE.get(method, ADMIN)]


class SessionManager:
    """Session-key -> in-flight task binding with expiry
    (reference servlet/SessionManager.java): lets a client that lost the
    User-Task-ID header resume its async request by session."""

    def __init__(self, max_expiry_ms: int = 3_600_000, max_sessions: int = 100):
        self._sessions: dict[str, tuple[str, int]] = {}  # key -> (task_id, created)
        self._lock = threading.Lock()
        self.max_expiry_ms = max_expiry_ms
        self.max_sessions = max_sessions

    @staticmethod
    def session_key(client: str, method: str, endpoint: str, query: str) -> str:
        return hashlib.sha256(f"{client}|{method}|{endpoint}|{query}".encode()).hexdigest()

    def get_or_bind(self, key: str, task_id_factory) -> str:
        now = int(time.time() * 1000)
        with self._lock:
            self._expire(now)
            entry = self._sessions.get(key)
            if entry is not None:
                return entry[0]
            if len(self._sessions) >= self.max_sessions:
                raise RuntimeError("too many active sessions")
            task_id = task_id_factory()
            self._sessions[key] = (task_id, now)
            return task_id

    def release(self, key: str):
        with self._lock:
            self._sessions.pop(key, None)

    def release_task(self, task_id: str):
        """Unbind every session pointing at task_id (used when the task's
        response was delivered via the User-Task-ID header path, so a later
        identical request must execute fresh rather than resume it)."""
        with self._lock:
            for k in [k for k, (t, _) in self._sessions.items() if t == task_id]:
                del self._sessions[k]

    def _expire(self, now: int):
        for k in [
            k for k, (_, t) in self._sessions.items() if now - t > self.max_expiry_ms
        ]:
            del self._sessions[k]

    def num_active(self) -> int:
        with self._lock:
            return len(self._sessions)
