"""Service layer: facade, REST API, user tasks, progress, purgatory.

Reference: KafkaCruiseControl.java + servlet/ + async/.
"""

from cruise_control_tpu.service.facade import CruiseControl, SelfHealingAdapter
from cruise_control_tpu.service.progress import OperationProgress
from cruise_control_tpu.service.purgatory import Purgatory, ReviewStatus
from cruise_control_tpu.service.server import (
    GET_ENDPOINTS,
    POST_ENDPOINTS,
    CruiseControlApp,
)
from cruise_control_tpu.service.tasks import USER_TASK_ID_HEADER, UserTask, UserTaskManager
