"""Kafka record-batch v2 (magic 2) encode/decode.

The metrics-reporter stream rides normal Kafka topics
(`__CruiseControlMetrics`, reference CruiseControlMetricsReporter.java;
sample-store topics, KafkaSampleStore.java:117-128), so the produce/fetch
path needs the message format: one RecordBatch per produce, varint-encoded
records inside, CRC-32C (Castagnoli) over the post-CRC bytes.

Layout (public spec, kafka.apache.org/documentation/#recordbatch):

  baseOffset i64 | batchLength i32 | partitionLeaderEpoch i32 | magic i8 |
  crc u32 | attributes i16 | lastOffsetDelta i32 | baseTimestamp i64 |
  maxTimestamp i64 | producerId i64 | producerEpoch i16 | baseSequence i32 |
  recordCount i32 | records...

  record: length zigzag | attributes i8 | timestampDelta zigzag |
  offsetDelta zigzag | keyLen zigzag (-1 null) | key | valueLen zigzag |
  value | headerCount zigzag (0)

No compression (attributes 0) — metric records are tiny and the reporter
defaults to uncompressed.
"""

from __future__ import annotations

import dataclasses
import struct

# ------------------------------------------------------------------ crc32c

_CRC32C_POLY = 0x82F63B78
_crc_table: list[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _crc_table.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli), the record-batch checksum.

    Uses the native slice-by-8 kernel when available (fetch payloads are
    multi-MB; a per-byte Python loop would dominate the consume path the
    native columnar decoder exists to accelerate)."""
    from cruise_control_tpu.native import crc32c_native

    fast = crc32c_native(data, crc)
    if fast is not None:
        return fast
    crc = ~crc & 0xFFFFFFFF
    for b in data:
        crc = _crc_table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


# ----------------------------------------------------------- zigzag varint


def write_zigzag(out: bytearray, v: int) -> None:
    z = (v << 1) ^ (v >> 63)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_zigzag(buf, off: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (result >> 1) ^ -(result & 1), off


# ------------------------------------------------------------------ batches


@dataclasses.dataclass(frozen=True)
class Record:
    offset: int
    timestamp_ms: int
    key: bytes | None
    value: bytes


_HEAD = struct.Struct(">qiibIhiqqqhii")
#        baseOffset batchLen leaderEpoch magic crc attrs lastOffsetDelta
#        baseTs maxTs producerId producerEpoch baseSeq recordCount


def encode_batch(
    records: list[tuple[bytes | None, bytes]],
    *,
    base_offset: int = 0,
    base_timestamp_ms: int = 0,
) -> bytes:
    """Encode [(key, value)] as one uncompressed v2 batch."""
    if not records:
        raise ValueError("empty batch")
    body = bytearray()
    for i, (key, value) in enumerate(records):
        rec = bytearray()
        rec.append(0)  # attributes
        write_zigzag(rec, 0)  # timestampDelta
        write_zigzag(rec, i)  # offsetDelta
        if key is None:
            write_zigzag(rec, -1)
        else:
            write_zigzag(rec, len(key))
            rec += key
        write_zigzag(rec, len(value))
        rec += value
        write_zigzag(rec, 0)  # headers
        write_zigzag(body, len(rec))
        body += rec

    n = len(records)
    # post-crc section: attributes .. records
    post = struct.pack(
        ">hiqqqhii",
        0,                      # attributes (no compression)
        n - 1,                  # lastOffsetDelta
        base_timestamp_ms,      # baseTimestamp
        base_timestamp_ms,      # maxTimestamp
        -1, -1, -1,             # producerId/Epoch, baseSequence
        n,
    ) + bytes(body)
    crc = crc32c(post)
    # batchLength counts bytes after the batchLength field itself
    batch_len = 4 + 1 + 4 + len(post)  # leaderEpoch + magic + crc + post
    return (
        struct.pack(">qii", base_offset, batch_len, -1)
        + b"\x02"  # magic
        + struct.pack(">I", crc)
        + post
    )


def decode_batches(buf: bytes, *, verify_crc: bool = True) -> list[Record]:
    """Decode a concatenation of v2 batches (a fetched record set).

    A trailing partial batch (normal in fetch responses) is ignored.
    """
    out: list[Record] = []
    off = 0
    n = len(buf)
    while off + 12 <= n:
        base_offset, batch_len = struct.unpack_from(">qi", buf, off)
        total = 12 + batch_len
        if off + total > n:
            break  # partial trailing batch
        magic = buf[off + 16]
        if magic != 2:
            raise ValueError(f"unsupported magic {magic}")
        (crc,) = struct.unpack_from(">I", buf, off + 17)
        post = buf[off + 21: off + total]
        if verify_crc and crc32c(post) != crc:
            raise ValueError("record batch CRC mismatch")
        (attrs, _last_delta, base_ts, _max_ts, _pid, _pepoch, _bseq, count) = (
            struct.unpack_from(">hiqqqhii", post, 0)
        )
        if attrs & 0x07:
            raise ValueError("compressed batches not supported")
        p = 40  # past the fixed post-crc header (2+4+8+8+8+2+4+4)
        for _ in range(count):
            rec_len, p = read_zigzag(post, p)
            rec_end = p + rec_len
            p += 1  # record attributes
            ts_delta, p = read_zigzag(post, p)
            off_delta, p = read_zigzag(post, p)
            key_len, p = read_zigzag(post, p)
            key = None
            if key_len >= 0:
                key = bytes(post[p: p + key_len])
                p += key_len
            val_len, p = read_zigzag(post, p)
            # -1 = null value (tombstone on a compacted topic — the
            # reference's sample-store topics are compacted)
            value = b""
            if val_len >= 0:
                value = bytes(post[p: p + val_len])
                p += val_len
            hdr_count, p = read_zigzag(post, p)
            for _h in range(hdr_count):
                klen, p = read_zigzag(post, p)
                p += klen
                vlen, p = read_zigzag(post, p)
                p += max(vlen, 0)
            if p != rec_end:
                raise ValueError("record length mismatch")
            out.append(
                Record(
                    offset=base_offset + off_delta,
                    timestamp_ms=base_ts + ts_delta,
                    key=key,
                    value=value,
                )
            )
        off += total
    return out
