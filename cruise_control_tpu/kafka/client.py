"""Blocking Kafka admin client over raw sockets with controller routing.

Reference roles covered: common/MetadataClient.java:1 (metadata refresh),
executor/ExecutorAdminUtils.java:1 (admin operations).  One connection per
broker, lazily opened; controller-only APIs (reassignments, elections,
configs) are routed to the current controller and retried once after a
metadata refresh if the controller moved (NOT_CONTROLLER).
"""

from __future__ import annotations

import socket
import struct
import threading

from cruise_control_tpu.kafka import protocol as proto

#: Kafka error codes we interpret (public protocol spec)
NONE = 0
NOT_CONTROLLER = 41
NO_REASSIGNMENT_IN_PROGRESS = 85


class KafkaProtocolError(Exception):
    def __init__(self, api: str, code: int, message: str | None = None):
        super().__init__(f"{api}: error_code={code} {message or ''}".strip())
        self.api = api
        self.code = code


class BrokerConnection:
    """One socket to one broker; request/response are strictly serial."""

    def __init__(
        self, host: str, port: int, client_id: str, timeout_s: float, sasl=None
    ):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.timeout_s = timeout_s
        #: optional SaslCredentials — every (re)connected socket
        #: authenticates before it carries any other request
        self.sasl = sasl
        self._sock: socket.socket | None = None
        self._correlation = 0
        self._lock = threading.Lock()

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s
            )
            if self.sasl is not None:
                try:
                    self._authenticate(sock)
                except BaseException:
                    sock.close()
                    raise
            self._sock = sock
        return self._sock

    def _raw_request(self, sock: socket.socket, api: proto.Api, body: dict) -> dict:
        """One framed request on an explicit socket — used during SASL
        setup, before the connection is available to request()."""
        self._correlation += 1
        cid = self._correlation
        sock.sendall(proto.encode_request(api, cid, self.client_id, body))
        (size,) = struct.unpack(">i", self._read_exact(sock, 4))
        got_cid, resp = proto.decode_response(api, self._read_exact(sock, size))
        if got_cid != cid:
            raise ConnectionError(f"correlation mismatch: sent {cid}, got {got_cid}")
        return resp

    def _authenticate(self, sock: socket.socket) -> None:
        """SaslHandshake + SaslAuthenticate exchange (KIP-152 framing)."""
        from cruise_control_tpu.kafka.sasl import ScramClient

        creds = self.sasl
        hs = self._raw_request(sock, proto.SASL_HANDSHAKE, {"mechanism": creds.mechanism})
        if hs["error_code"] != NONE:
            raise KafkaProtocolError(
                "SaslHandshake", hs["error_code"],
                f"mechanism {creds.mechanism} rejected; broker offers "
                f"{hs.get('mechanisms')}",
            )

        def auth_round(payload: bytes) -> bytes:
            resp = self._raw_request(
                sock, proto.SASL_AUTHENTICATE, {"auth_bytes": payload}
            )
            if resp["error_code"] != NONE:
                raise KafkaProtocolError(
                    "SaslAuthenticate", resp["error_code"], resp.get("error_message")
                )
            return resp["auth_bytes"]

        if creds.mechanism == "PLAIN":
            auth_round(f"\0{creds.username}\0{creds.password}".encode())
            return
        scram = ScramClient(creds)
        server_first = auth_round(scram.first())
        server_final = auth_round(scram.final(server_first))
        scram.verify(server_final)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _read_exact(self, sock: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            chunk = sock.recv(n)
            if not chunk:
                raise ConnectionError("broker closed connection")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def request(self, api: proto.Api, body: dict) -> dict:
        """One retry on a fresh socket: brokers close idle connections
        (connections.max.idle.ms), so the first call after an idle window
        hits a dead cached socket — reconnect once, then surface errors.

        Only idempotent APIs retry: an ambiguous failure (e.g. timeout after
        the request was written) may mean the broker already executed it,
        and re-sending a Produce would append duplicate records."""
        with self._lock:
            last_error: Exception | None = None
            attempts = 2 if api.idempotent else 1
            for attempt in range(attempts):
                self._correlation += 1
                cid = self._correlation
                frame = proto.encode_request(api, cid, self.client_id, body)
                try:
                    sock = self._ensure()
                    sock.sendall(frame)
                    (size,) = struct.unpack(">i", self._read_exact(sock, 4))
                    payload = self._read_exact(sock, size)
                except (OSError, ConnectionError) as e:
                    self.close()  # poisoned stream; retry on a fresh socket
                    last_error = e
                    continue
                got_cid, resp = proto.decode_response(api, payload)
                if got_cid != cid:
                    self.close()
                    raise ConnectionError(
                        f"correlation mismatch: sent {cid}, got {got_cid}"
                    )
                return resp
            raise last_error  # type: ignore[misc]


class KafkaAdminClient:
    """Cluster-level operations with broker/controller routing."""

    def __init__(
        self,
        bootstrap: list[tuple[str, int]],
        *,
        client_id: str = "cruise-control-tpu",
        timeout_s: float = 30.0,
        sasl=None,
    ):
        if not bootstrap:
            raise ValueError("bootstrap servers required")
        self.bootstrap = bootstrap
        self.client_id = client_id
        self.timeout_s = timeout_s
        #: optional kafka.sasl.SaslCredentials applied to every connection
        self.sasl = sasl
        self._conns: dict[tuple[str, int], BrokerConnection] = {}
        self._brokers: dict[int, tuple[str, int]] = {}  # node_id -> addr
        self._controller_id: int | None = None
        # routing maps are shared by detector/executor/REST threads; per-
        # connection locks serialize frames but not these dicts
        self._route_lock = threading.Lock()

    # ------------------------------------------------------------- plumbing

    def _conn(self, addr: tuple[str, int]) -> BrokerConnection:
        with self._route_lock:
            conn = self._conns.get(addr)
            if conn is None:
                conn = BrokerConnection(
                    addr[0], addr[1], self.client_id, self.timeout_s, sasl=self.sasl
                )
                self._conns[addr] = conn
            return conn

    def close(self) -> None:
        with self._route_lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()

    def _any_conn(self) -> BrokerConnection:
        errors = []
        with self._route_lock:
            known = list(self._brokers.values())
        for node_addr in known + self.bootstrap:
            try:
                conn = self._conn(node_addr)
                conn._ensure()
                return conn
            except OSError as e:  # try the next seed
                errors.append(f"{node_addr}: {e}")
        raise ConnectionError("no reachable broker: " + "; ".join(errors))

    # ------------------------------------------------------------ metadata

    def metadata(self, topics: list[str] | None = None) -> dict:
        resp = self._any_conn().request(proto.METADATA, {"topics": topics})
        with self._route_lock:
            self._brokers = {
                b["node_id"]: (b["host"], b["port"]) for b in resp["brokers"]
            }
            self._controller_id = resp["controller_id"]
        return resp

    def api_versions(self) -> dict:
        return self._any_conn().request(proto.API_VERSIONS, {})

    def check_api_support(self) -> None:
        """Verify the broker supports every (api, version) this client pins
        (one fixed version per API — see protocol.py).  Raises with the
        full unsupported list, which beats per-operation decode failures
        against an old broker."""
        resp = self.api_versions()
        if resp["error_code"] != NONE:
            raise KafkaProtocolError("ApiVersions", resp["error_code"])
        ranges = {
            a["api_key"]: (a["min_version"], a["max_version"])
            for a in resp["api_keys"] or []
        }
        missing = []
        for api in proto.ALL_APIS:
            lo_hi = ranges.get(api.key)
            if lo_hi is None or not (lo_hi[0] <= api.version <= lo_hi[1]):
                missing.append(f"{api.name} v{api.version} (broker has {lo_hi})")
        if missing:
            raise KafkaProtocolError(
                "ApiVersions", 35,  # UNSUPPORTED_VERSION
                "broker lacks required APIs: " + ", ".join(missing),
            )

    def _controller_conn(self) -> BrokerConnection:
        with self._route_lock:
            cid = self._controller_id
            addr = self._brokers.get(cid) if cid is not None else None
        if addr is None:
            self.metadata()
            with self._route_lock:
                addr = self._brokers.get(self._controller_id)
        if addr is None:
            raise ConnectionError("no controller in metadata")
        return self._conn(addr)

    def _controller_request(self, api: proto.Api, body: dict) -> dict:
        """Route to controller; one retry after refresh on NOT_CONTROLLER."""
        resp = self._controller_conn().request(api, body)
        if resp.get("error_code", NONE) == NOT_CONTROLLER:
            self.metadata()
            resp = self._controller_conn().request(api, body)
        return resp

    def broker_request(self, node_id: int, api: proto.Api, body: dict) -> dict:
        with self._route_lock:
            addr = self._brokers.get(node_id)
        if addr is None:
            self.metadata()
            with self._route_lock:
                addr = self._brokers.get(node_id)
        if addr is None:
            raise ConnectionError(f"unknown broker {node_id}")
        return self._conn(addr).request(api, body)

    # ----------------------------------------------------------- operations

    def alter_partition_reassignments(
        self, assignments: dict[tuple[str, int], list[int] | None],
        timeout_ms: int = 60_000,
    ) -> list[tuple[str, int, int, str | None]]:
        """assignments: (topic, partition) -> target replicas (None cancels).
        Returns per-partition (topic, partition, error_code, message)."""
        by_topic: dict[str, list[dict]] = {}
        for (topic, part), replicas in assignments.items():
            by_topic.setdefault(topic, []).append(
                {"partition_index": part, "replicas": replicas}
            )
        resp = self._controller_request(proto.ALTER_PARTITION_REASSIGNMENTS, {
            "timeout_ms": timeout_ms,
            "topics": [
                {"name": t, "partitions": ps} for t, ps in sorted(by_topic.items())
            ],
        })
        if resp["error_code"] != NONE:
            raise KafkaProtocolError(
                "AlterPartitionReassignments", resp["error_code"],
                resp.get("error_message"),
            )
        out = []
        for t in resp["responses"] or []:
            for p in t["partitions"] or []:
                out.append(
                    (t["name"], p["partition_index"], p["error_code"],
                     p.get("error_message"))
                )
        return out

    def list_partition_reassignments(self) -> set[tuple[str, int]]:
        resp = self._controller_request(proto.LIST_PARTITION_REASSIGNMENTS, {
            "timeout_ms": 30_000, "topics": None,
        })
        if resp["error_code"] not in (NONE, NO_REASSIGNMENT_IN_PROGRESS):
            raise KafkaProtocolError(
                "ListPartitionReassignments", resp["error_code"],
                resp.get("error_message"),
            )
        return {
            (t["name"], p["partition_index"])
            for t in resp["topics"] or []
            for p in t["partitions"] or []
        }

    def elect_preferred_leaders(
        self, partitions: list[tuple[str, int]], timeout_ms: int = 30_000
    ) -> list[tuple[str, int, int]]:
        by_topic: dict[str, list[int]] = {}
        for topic, part in partitions:
            by_topic.setdefault(topic, []).append(part)
        resp = self._controller_request(proto.ELECT_LEADERS, {
            "election_type": 0,  # PREFERRED
            "topic_partitions": [
                {"topic": t, "partition_ids": ps}
                for t, ps in sorted(by_topic.items())
            ],
            "timeout_ms": timeout_ms,
        })
        if resp["error_code"] != NONE:
            raise KafkaProtocolError("ElectLeaders", resp["error_code"])
        return [
            (t["topic"], p["partition_id"], p["error_code"])
            for t in resp["replica_election_results"] or []
            for p in t["partition_results"] or []
        ]

    def incremental_alter_configs(
        self, resources: list[tuple[int, str, list[tuple[str, int, str | None]]]],
    ) -> None:
        """resources: (resource_type, name, [(config, op, value)])."""
        resp = self._any_conn().request(proto.INCREMENTAL_ALTER_CONFIGS, {
            "resources": [
                {
                    "resource_type": rt, "resource_name": name,
                    "configs": [
                        {"name": c, "config_operation": op, "value": v}
                        for c, op, v in configs
                    ],
                }
                for rt, name, configs in resources
            ],
            "validate_only": False,
        })
        for r in resp["responses"] or []:
            if r["error_code"] != NONE:
                raise KafkaProtocolError(
                    "IncrementalAlterConfigs", r["error_code"], r.get("error_message")
                )

    def create_topics(
        self, topics: list[tuple[str, int, int]], timeout_ms: int = 30_000
    ) -> dict[str, int]:
        """[(name, num_partitions, replication_factor)] -> name: error_code.
        36 = TOPIC_ALREADY_EXISTS (callers usually treat it as success)."""
        resp = self._controller_request(proto.CREATE_TOPICS, {
            "topics": [
                {"name": n, "num_partitions": p, "replication_factor": rf,
                 "assignments": [], "configs": []}
                for n, p, rf in topics
            ],
            "timeout_ms": timeout_ms,
        })
        return {t["name"]: t["error_code"] for t in resp["topics"] or []}

    def describe_configs(
        self, resources: list[tuple[int, str]], names: list[str] | None = None,
        *, node_id: int | None = None,
    ) -> dict[tuple[int, str], dict[str, str]]:
        """(resource_type, name) -> {config: value} for non-default configs
        (value None and defaults are omitted).  `node_id` routes the request
        to a specific broker — required for BROKER resources (KIP-226)."""
        body = {
            "resources": [
                {"resource_type": rt, "resource_name": rn,
                 "configuration_keys": names}
                for rt, rn in resources
            ],
        }
        if node_id is not None:
            resp = self.broker_request(node_id, proto.DESCRIBE_CONFIGS, body)
        else:
            resp = self._any_conn().request(proto.DESCRIBE_CONFIGS, body)
        out: dict[tuple[int, str], dict[str, str]] = {}
        for r in resp["results"] or []:
            if r["error_code"] != NONE:
                continue
            out[(r["resource_type"], r["resource_name"])] = {
                c["name"]: c["value"]
                for c in r["configs"] or []
                if c["value"] is not None and not c["is_default"]
            }
        return out

    def alter_replica_logdirs(
        self, node_id: int, moves: dict[str, list[tuple[str, int]]]
    ) -> list[tuple[str, int, int]]:
        """moves: logdir path -> [(topic, partition)] on ONE broker."""
        dirs = []
        for path, tps in sorted(moves.items()):
            by_topic: dict[str, list[int]] = {}
            for topic, part in tps:
                by_topic.setdefault(topic, []).append(part)
            dirs.append({
                "path": path,
                "topics": [
                    {"name": t, "partitions": ps}
                    for t, ps in sorted(by_topic.items())
                ],
            })
        resp = self.broker_request(node_id, proto.ALTER_REPLICA_LOG_DIRS, {"dirs": dirs})
        return [
            (t["topic_name"], p["partition_index"], p["error_code"])
            for t in resp["results"] or []
            for p in t["partitions"] or []
        ]

    def describe_logdirs(self, node_id: int) -> dict[str, dict]:
        """node's logdirs: path -> {"error_code", "replicas": {(t, p): size},
        "future_replicas": {(t, p)}}.

        future_replicas are in-flight AlterReplicaLogDirs targets
        (is_future_key=true): the partition is still copying into this dir
        (reference ExecutorAdminUtils polls these to track intra-broker
        move completion)."""
        resp = self.broker_request(node_id, proto.DESCRIBE_LOG_DIRS, {"topics": None})
        out: dict[str, dict] = {}
        for r in resp["results"] or []:
            replicas = {}
            future = set()
            for t in r["topics"] or []:
                for p in t["partitions"] or []:
                    key = (t["name"], p["partition_index"])
                    if p.get("is_future_key"):
                        future.add(key)
                    else:
                        replicas[key] = p["partition_size"]
            out[r["log_dir"]] = {
                "error_code": r["error_code"],
                "replicas": replicas,
                "future_replicas": future,
            }
        return out
