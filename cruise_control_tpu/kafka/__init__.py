"""Real-cluster adapters: a dependency-free Kafka wire-protocol client.

The reference talks to the cluster through the JVM Kafka clients and a
Scala ZooKeeper bridge (executor/ExecutorUtils.scala:31,
executor/ExecutorAdminUtils.java:1, common/MetadataClient.java:1).  Modern
Kafka exposes every operation the executor needs over the broker wire
protocol itself (KIP-455 reassignment, KIP-460 elections, KIP-113 logdir
moves), so this package implements a minimal binary-protocol AdminClient in
pure Python — no kafka-python/confluent dependency — and adapts it to the
framework's ClusterAdmin / MetadataProvider SPIs.

Modules:
  codec.py     — primitive + schema (classic & compact/flexible) encoding
  protocol.py  — request/response schemas for the 8 APIs the executor uses
  client.py    — blocking socket client with controller routing
  admin.py     — KafkaClusterAdmin / KafkaMetadataProvider SPI adapters

Contract tests (tests/test_kafka_admin.py) run the SAME suite against
SimulatedClusterAdmin and KafkaClusterAdmin-against-a-fake-broker
(cruise_control_tpu/testing/fake_kafka.py), the in-process analog of the
reference's embedded-cluster harness (CCKafkaIntegrationTestHarness).
"""

from cruise_control_tpu.kafka.admin import KafkaClusterAdmin, KafkaMetadataProvider
from cruise_control_tpu.kafka.client import KafkaAdminClient, KafkaProtocolError

__all__ = [
    "KafkaAdminClient",
    "KafkaClusterAdmin",
    "KafkaMetadataProvider",
    "KafkaProtocolError",
]
