"""Metric stream over real Kafka topics: producer + consumer transports.

Completes the real-cluster sampling loop the reference runs
(CruiseControlMetricsReporter produces to `__CruiseControlMetrics`;
CruiseControlMetricsReporterSampler.java:101 polls it):

  * KafkaMetricsTransport — the reporter-side MetricTransport SPI
    (reporter/reporter.py): buffers serialized metric records and produces
    one record-batch per partition leader on flush.
  * KafkaMetricsConsumer — the sampler-side drain: fetches every partition
    from its leader, decodes v2 batches, and exposes `poll_framed()` so the
    native columnar decoder (cruise_control_tpu/native) parses the whole
    batch without per-record objects.

Both route by live Metadata (leader per partition) through the shared
KafkaAdminClient connection pool.
"""

from __future__ import annotations

import random
import threading
import time

from cruise_control_tpu.common.device_watchdog import jittered_backoff_s
from cruise_control_tpu.kafka import protocol as proto
from cruise_control_tpu.kafka.client import KafkaAdminClient, KafkaProtocolError, NONE
from cruise_control_tpu.kafka.records import decode_batches, encode_batch

DEFAULT_TOPIC = "__CruiseControlMetrics"
EARLIEST = -2
LATEST = -1


class _TopicRouter:
    """Partition -> leader routing from live metadata."""

    def __init__(self, client: KafkaAdminClient, topic: str):
        self.client = client
        self.topic = topic
        self._leaders: dict[int, int] = {}

    def refresh(self) -> dict[int, int]:
        md = self.client.metadata([self.topic])
        self._leaders = {}
        for t in md["topics"]:
            if t["name"] != self.topic or t["error_code"] != NONE:
                continue
            for p in t["partitions"]:
                if p["leader_id"] >= 0:
                    self._leaders[p["partition_index"]] = p["leader_id"]
        return self._leaders

    def leaders(self) -> dict[int, int]:
        return self._leaders or self.refresh()


class KafkaMetricsTransport:
    """MetricTransport over Produce v3 (reference reporter's producer)."""

    def __init__(
        self,
        client: KafkaAdminClient,
        topic: str = DEFAULT_TOPIC,
        *,
        acks: int = 1,
        flush_every: int = 1000,
        now_ms=None,
        retry_backoff_s: float = 0.05,
        retry_backoff_cap_s: float = 0.5,
        rng: random.Random | None = None,
        sleep=time.sleep,
    ):
        """retry_backoff_s/cap: full-jitter backoff base/cap applied before
        the NOT_LEADER reroute retry and the transient-connection retry —
        a metadata-lagging or restarting broker answered the instant retry
        with the same error.  rng/sleep injectable for deterministic tests."""
        self.client = client
        self.topic = topic
        self.acks = acks
        self.flush_every = flush_every
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_cap_s = retry_backoff_cap_s
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._router = _TopicRouter(client, topic)
        self._buffer: list[bytes] = []
        self._rr = 0  # round-robin partition cursor
        self._lock = threading.Lock()

        self._now = now_ms or (lambda: int(time.time() * 1000))

    def send(self, payload: bytes) -> None:
        with self._lock:
            self._buffer.append(payload)
            full = len(self._buffer) >= self.flush_every
        if full:
            self.flush()

    def flush(self) -> None:
        # swap the buffer under the lock; network work (metadata + produce)
        # happens OUTSIDE it so concurrent send()s never block on a slow
        # broker.  On any failure the records go back to the buffer — a
        # transient hiccup must not drop metrics.
        with self._lock:
            records, self._buffer = self._buffer, []
            if not records:
                return
            rr = self._rr
            self._rr += 1
        try:
            leaders = self._router.leaders()
            if not leaders:
                raise KafkaProtocolError("Produce", 3, f"no leaders for {self.topic}")
            # spread whole flushes across partitions round-robin (records of
            # one flush stay together: ordering within a batch is preserved)
            parts = sorted(leaders)
            partition = parts[rr % len(parts)]
            batch = encode_batch(
                [(None, r) for r in records], base_timestamp_ms=self._now()
            )
            self._produce(partition, leaders[partition], batch, retry_route=True)
        except Exception:
            with self._lock:
                self._buffer[:0] = records  # restore, preserving order
            raise

    def _backoff(self, attempt: int = 1) -> None:
        self._sleep(
            jittered_backoff_s(
                attempt,
                base_s=self.retry_backoff_s,
                cap_s=self.retry_backoff_cap_s,
                rng=self._rng,
            )
        )

    def _produce(self, partition: int, node: int, batch: bytes, *,
                 retry_route: bool) -> None:
        request = {
            "transactional_id": None,
            "acks": self.acks,
            "timeout_ms": 30_000,
            "topic_data": [{
                "name": self.topic,
                "partition_data": [{"index": partition, "records": batch}],
            }],
        }
        try:
            resp = self.client.broker_request(node, proto.PRODUCE, request)
        except (ConnectionError, TimeoutError, OSError):
            # transient transport error (broker restarting, socket dropped):
            # retry ONCE after a short jittered pause, against fresh routing
            # — the leader may have moved with the restart.  A second
            # failure surfaces to flush(), which restores the buffer.
            if not retry_route:
                raise
            self._backoff()
            node = self._router.refresh().get(partition, node)
            resp = self.client.broker_request(node, proto.PRODUCE, request)
        for t in resp["responses"] or []:
            for p in t["partition_responses"] or []:
                if p["error_code"] == NONE:
                    continue
                if p["error_code"] == 6 and retry_route:
                    # NOT_LEADER_OR_FOLLOWER: re-route ONCE, then surface
                    # whatever the retry returns (a silently-dropped batch is
                    # silent metric loss).  Backoff first — the cluster is
                    # mid-election and instant metadata often still names
                    # the old leader.
                    self._backoff()
                    new_leader = self._router.refresh().get(partition)
                    if new_leader is None:
                        raise KafkaProtocolError(
                            "Produce", 6, f"partition {partition} leaderless"
                        )
                    self._produce(partition, new_leader, batch, retry_route=False)
                else:
                    raise KafkaProtocolError("Produce", p["error_code"])


class KafkaMetricsConsumer:
    """Drains the reporter topic; `poll_framed()` feeds the native decoder.

    Tracks its own per-partition offsets (the reference sampler also manages
    offsets explicitly, seeking by time window) starting from EARLIEST.
    """

    def __init__(
        self,
        client: KafkaAdminClient,
        topic: str = DEFAULT_TOPIC,
        *,
        max_bytes_per_fetch: int = 8 * 1024 * 1024,
        serde=None,
    ):
        """serde: record deserializer — native MetricSerde (default) or
        ReferenceMetricSerde when the topic is fed by the REFERENCE's
        in-broker reporter plugin (drop-in ingestion interop)."""
        from cruise_control_tpu.reporter.metrics import MetricSerde

        self.client = client
        self.topic = topic
        self.max_bytes = max_bytes_per_fetch
        self.serde = serde or MetricSerde
        self.framed_native = self.serde is MetricSerde
        self._router = _TopicRouter(client, topic)
        self._offsets: dict[int, int] = {}
        #: fetched-but-undelivered payloads (a max_records poll must not
        #: drop the tail — offsets advance at fetch time)
        self._pending: list[bytes] = []
        self._lock = threading.Lock()

    def _ensure_offsets(self, leaders: dict[int, int]) -> None:
        missing = [p for p in leaders if p not in self._offsets]
        if not missing:
            return
        by_leader: dict[int, list[int]] = {}
        for p in missing:
            by_leader.setdefault(leaders[p], []).append(p)
        for node, parts in by_leader.items():
            resp = self.client.broker_request(node, proto.LIST_OFFSETS, {
                "replica_id": -1,
                "topics": [{
                    "name": self.topic,
                    "partitions": [
                        {"partition_index": p, "timestamp": EARLIEST} for p in parts
                    ],
                }],
            })
            for t in resp["topics"] or []:
                for p in t["partitions"] or []:
                    if p["error_code"] == NONE:
                        self._offsets[p["partition_index"]] = p["offset"]

    def poll_records(self, max_records: int | None = None) -> list[bytes]:
        """New record payloads across partitions (undelivered ones first)."""
        with self._lock:
            self._pending.extend(self._fetch_all())
            n = len(self._pending) if max_records is None else min(
                max_records, len(self._pending)
            )
            out, self._pending = self._pending[:n], self._pending[n:]
            return out

    def _fetch_all(self) -> list[bytes]:
        """Fetch every partition from its leader, advancing offsets.
        Caller holds the lock."""
        leaders = self._router.refresh()
        self._ensure_offsets(leaders)
        by_leader: dict[int, list[int]] = {}
        for p, node in leaders.items():
            by_leader.setdefault(node, []).append(p)
        out: list[bytes] = []
        for node, parts in sorted(by_leader.items()):
            resp = self.client.broker_request(node, proto.FETCH, {
                "replica_id": -1,
                "max_wait_ms": 0,
                "min_bytes": 0,
                "max_bytes": self.max_bytes,
                "isolation_level": 0,
                "topics": [{
                    "topic": self.topic,
                    "partitions": [
                        {
                            "partition": p,
                            "fetch_offset": self._offsets.get(p, 0),
                            "partition_max_bytes": self.max_bytes,
                        }
                        for p in sorted(parts)
                    ],
                }],
            })
            for t in resp["responses"] or []:
                for pr in t["partitions"] or []:
                    if pr["error_code"] == 1:  # OFFSET_OUT_OF_RANGE
                        # retention passed our offset: drop it so the next
                        # poll re-seeks to EARLIEST instead of stalling the
                        # partition forever
                        self._offsets.pop(pr["partition_index"], None)
                        continue
                    if pr["error_code"] != NONE or not pr["records"]:
                        continue
                    records = decode_batches(pr["records"])
                    part = pr["partition_index"]
                    next_off = self._offsets.get(part, 0)
                    for r in records:
                        if r.offset >= next_off:
                            out.append(r.value)
                            next_off = r.offset + 1
                    self._offsets[part] = next_off
        return out

    def log_end_offsets(self) -> dict[int, int]:
        """Current LATEST offset per partition (fresh ListOffsets round)."""
        leaders = self._router.refresh()
        by_leader: dict[int, list[int]] = {}
        for p, node in leaders.items():
            by_leader.setdefault(node, []).append(p)
        out: dict[int, int] = {}
        for node, parts in by_leader.items():
            resp = self.client.broker_request(node, proto.LIST_OFFSETS, {
                "replica_id": -1,
                "topics": [{
                    "name": self.topic,
                    "partitions": [
                        {"partition_index": p, "timestamp": LATEST} for p in parts
                    ],
                }],
            })
            for t in resp["topics"] or []:
                for p in t["partitions"] or []:
                    if p["error_code"] == NONE:
                        out[p["partition_index"]] = p["offset"]
        return out

    def at_log_end(self) -> bool:
        """True when every reachable partition's offset is at LATEST.

        One empty poll is NOT proof of log end: a transient fetch error
        (leader change, offset re-seek) yields an empty round with data
        still unread — callers draining history (sample-store replay) must
        confirm against ListOffsets.
        """
        with self._lock:
            if self._pending:
                return False
            ends = self.log_end_offsets()
            return all(self._offsets.get(p, 0) >= end for p, end in ends.items())

    def poll_framed(self, max_records: int | None = None) -> bytes:
        from cruise_control_tpu.native import frame_records

        return frame_records(self.poll_records(max_records))

    def poll(self, max_records: int | None = None):
        """Object-path compatibility with the MetricSampler SPI; unknown
        record classes (serde returns None) are skipped."""
        decoded = (self.serde.deserialize(r) for r in self.poll_records(max_records))
        return [m for m in decoded if m is not None]
