"""Request/response schemas for the Kafka APIs the executor stack needs.

Transcribed from the public protocol spec (kafka.apache.org/protocol).
One version per API, chosen as the lowest version that carries what we
need (classic encoding where possible; AlterPartitionReassignments /
ListPartitionReassignments are flexible-only, KIP-455):

  API                              key  ver  encoding  role
  ApiVersions                       18    0  classic   handshake sanity
  Metadata                           3    1  classic   topology + controller
  AlterPartitionReassignments       45    0  flexible  inter-broker moves
  ListPartitionReassignments        46    0  flexible  in-progress poll
  ElectLeaders                      43    1  classic   leadership moves
  IncrementalAlterConfigs           44    0  classic   replication throttles
  AlterReplicaLogDirs               34    1  classic   intra-broker moves
  DescribeLogDirs                   35    0  classic   logdir discovery

Reference parity: ExecutorUtils.scala:31 (reassignments; the znode bridge
is replaced by KIP-455 AlterPartitionReassignments), :95 (preferred-leader
election -> ElectLeaders), ExecutorAdminUtils.java:1 (alterReplicaLogDirs /
describeLogDirs / electLeaders via AdminClient),
ReplicationThrottleHelper.java:32 (throttle configs).
"""

from __future__ import annotations

import dataclasses

from cruise_control_tpu.kafka.codec import (
    Array,
    Boolean,
    CompactArray,
    CompactNullableString,
    CompactString,
    Int8,
    Int16,
    Int32,
    Int64,
    Bytes,
    NullableBytes,
    NullableString,
    String,
    Struct,
    TagBuffer,
)


@dataclasses.dataclass(frozen=True)
class Api:
    name: str
    key: int
    version: int
    flexible: bool
    request: Struct
    response: Struct
    #: safe to re-send after an ambiguous connection failure (the broker may
    #: have executed the first attempt); Produce is NOT — duplicated batches
    #: are silent double-counted metrics
    idempotent: bool = True


# -------------------------------------------------------------- ApiVersions

API_VERSIONS = Api(
    "ApiVersions", 18, 0, False,
    request=Struct(),
    response=Struct(
        ("error_code", Int16),
        ("api_keys", Array(Struct(
            ("api_key", Int16), ("min_version", Int16), ("max_version", Int16),
        ))),
    ),
)

# ----------------------------------------------------------------- Metadata

METADATA = Api(
    "Metadata", 3, 1, False,
    request=Struct(
        ("topics", Array(String, nullable=True)),  # null -> all topics
    ),
    response=Struct(
        ("brokers", Array(Struct(
            ("node_id", Int32), ("host", String), ("port", Int32),
            ("rack", NullableString),
        ))),
        ("controller_id", Int32),
        ("topics", Array(Struct(
            ("error_code", Int16), ("name", String), ("is_internal", Boolean),
            ("partitions", Array(Struct(
                ("error_code", Int16), ("partition_index", Int32),
                ("leader_id", Int32),
                ("replica_nodes", Array(Int32)),
                ("isr_nodes", Array(Int32)),
            ))),
        ))),
    ),
)

# -------------------------------------------------------------- CreateTopics

#: used for the sample-store + reporter topics (reference auto-creates its
#: topics: CruiseControlMetricsReporter topic bootstrap, KafkaSampleStore
#: ensureTopicsCreated)
CREATE_TOPICS = Api(
    "CreateTopics", 19, 0, False,
    request=Struct(
        ("topics", Array(Struct(
            ("name", String),
            ("num_partitions", Int32),
            ("replication_factor", Int16),
            ("assignments", Array(Struct(
                ("partition_index", Int32),
                ("broker_ids", Array(Int32)),
            ))),
            ("configs", Array(Struct(
                ("name", String),
                ("value", NullableString),
            ))),
        ))),
        ("timeout_ms", Int32),
    ),
    response=Struct(
        ("topics", Array(Struct(
            ("name", String),
            ("error_code", Int16),
        ))),
    ),
)

# ---------------------------------------- AlterPartitionReassignments (KIP-455)

ALTER_PARTITION_REASSIGNMENTS = Api(
    "AlterPartitionReassignments", 45, 0, True,
    request=Struct(
        ("timeout_ms", Int32),
        ("topics", CompactArray(Struct(
            ("name", CompactString),
            ("partitions", CompactArray(Struct(
                ("partition_index", Int32),
                # null replicas = cancel the in-progress reassignment
                ("replicas", CompactArray(Int32, nullable=True)),
                ("_tags", TagBuffer),
            ))),
            ("_tags", TagBuffer),
        ))),
        ("_tags", TagBuffer),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("error_code", Int16),
        ("error_message", CompactNullableString),
        ("responses", CompactArray(Struct(
            ("name", CompactString),
            ("partitions", CompactArray(Struct(
                ("partition_index", Int32),
                ("error_code", Int16),
                ("error_message", CompactNullableString),
                ("_tags", TagBuffer),
            ))),
            ("_tags", TagBuffer),
        ))),
        ("_tags", TagBuffer),
    ),
)

LIST_PARTITION_REASSIGNMENTS = Api(
    "ListPartitionReassignments", 46, 0, True,
    request=Struct(
        ("timeout_ms", Int32),
        ("topics", CompactArray(Struct(
            ("name", CompactString),
            ("partition_indexes", CompactArray(Int32)),
            ("_tags", TagBuffer),
        ), nullable=True)),  # null -> every in-progress reassignment
        ("_tags", TagBuffer),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("error_code", Int16),
        ("error_message", CompactNullableString),
        ("topics", CompactArray(Struct(
            ("name", CompactString),
            ("partitions", CompactArray(Struct(
                ("partition_index", Int32),
                ("replicas", CompactArray(Int32)),
                ("adding_replicas", CompactArray(Int32)),
                ("removing_replicas", CompactArray(Int32)),
                ("_tags", TagBuffer),
            ))),
            ("_tags", TagBuffer),
        ))),
        ("_tags", TagBuffer),
    ),
)

# ------------------------------------------------------------- ElectLeaders

#: election_type 0 = PREFERRED (KIP-460)
ELECT_LEADERS = Api(
    "ElectLeaders", 43, 1, False,
    request=Struct(
        ("election_type", Int8),
        ("topic_partitions", Array(Struct(
            ("topic", String),
            ("partition_ids", Array(Int32)),
        ), nullable=True)),
        ("timeout_ms", Int32),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("error_code", Int16),  # top-level error added in v1 (protocol spec)
        ("replica_election_results", Array(Struct(
            ("topic", String),
            ("partition_results", Array(Struct(
                ("partition_id", Int32),
                ("error_code", Int16),
                ("error_message", NullableString),
            ))),
        ))),
    ),
)

# -------------------------------------------------- IncrementalAlterConfigs

#: resource_type 2 = TOPIC, 4 = BROKER; op 0 = SET, 1 = DELETE (KIP-339)
INCREMENTAL_ALTER_CONFIGS = Api(
    "IncrementalAlterConfigs", 44, 0, False,
    request=Struct(
        ("resources", Array(Struct(
            ("resource_type", Int8),
            ("resource_name", String),
            ("configs", Array(Struct(
                ("name", String),
                ("config_operation", Int8),
                ("value", NullableString),
            ))),
        ))),
        ("validate_only", Boolean),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("responses", Array(Struct(
            ("error_code", Int16),
            ("error_message", NullableString),
            ("resource_type", Int8),
            ("resource_name", String),
        ))),
    ),
)

# ------------------------------------------------------------ Produce/Fetch

#: data-plane APIs for the reporter topic + sample-store topics (reference
#: CruiseControlMetricsReporter producer, KafkaSampleStore.java:117-128,
#: CruiseControlMetricsReporterSampler.java:101 consumer poll loop)
PRODUCE = Api(
    "Produce", 0, 3, False, idempotent=False,
    request=Struct(
        ("transactional_id", NullableString),
        ("acks", Int16),
        ("timeout_ms", Int32),
        ("topic_data", Array(Struct(
            ("name", String),
            ("partition_data", Array(Struct(
                ("index", Int32),
                ("records", NullableBytes),  # one v2 record batch
            ))),
        ))),
    ),
    response=Struct(
        ("responses", Array(Struct(
            ("name", String),
            ("partition_responses", Array(Struct(
                ("index", Int32),
                ("error_code", Int16),
                ("base_offset", Int64),
                ("log_append_time_ms", Int64),
            ))),
        ))),
        ("throttle_time_ms", Int32),
    ),
)

FETCH = Api(
    "Fetch", 1, 4, False,
    request=Struct(
        ("replica_id", Int32),  # -1 = consumer
        ("max_wait_ms", Int32),
        ("min_bytes", Int32),
        ("max_bytes", Int32),
        ("isolation_level", Int8),
        ("topics", Array(Struct(
            ("topic", String),
            ("partitions", Array(Struct(
                ("partition", Int32),
                ("fetch_offset", Int64),
                ("partition_max_bytes", Int32),
            ))),
        ))),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("responses", Array(Struct(
            ("topic", String),
            ("partitions", Array(Struct(
                ("partition_index", Int32),
                ("error_code", Int16),
                ("high_watermark", Int64),
                ("last_stable_offset", Int64),
                ("aborted_transactions", Array(Struct(
                    ("producer_id", Int64), ("first_offset", Int64),
                ), nullable=True)),
                ("records", NullableBytes),
            ))),
        ))),
    ),
)

LIST_OFFSETS = Api(
    "ListOffsets", 2, 1, False,
    request=Struct(
        ("replica_id", Int32),
        ("topics", Array(Struct(
            ("name", String),
            ("partitions", Array(Struct(
                ("partition_index", Int32),
                ("timestamp", Int64),  # -1 latest, -2 earliest
            ))),
        ))),
    ),
    response=Struct(
        ("topics", Array(Struct(
            ("name", String),
            ("partitions", Array(Struct(
                ("partition_index", Int32),
                ("error_code", Int16),
                ("timestamp", Int64),
                ("offset", Int64),
            ))),
        ))),
    ),
)

# ----------------------------------------------------------- DescribeConfigs

DESCRIBE_CONFIGS = Api(
    "DescribeConfigs", 32, 0, False,
    request=Struct(
        ("resources", Array(Struct(
            ("resource_type", Int8),
            ("resource_name", String),
            ("configuration_keys", Array(String, nullable=True)),
        ))),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("results", Array(Struct(
            ("error_code", Int16),
            ("error_message", NullableString),
            ("resource_type", Int8),
            ("resource_name", String),
            ("configs", Array(Struct(
                ("name", String),
                ("value", NullableString),
                ("read_only", Boolean),
                ("is_default", Boolean),
                ("is_sensitive", Boolean),
            ))),
        ))),
    ),
)

# ------------------------------------------------------ AlterReplicaLogDirs

ALTER_REPLICA_LOG_DIRS = Api(
    "AlterReplicaLogDirs", 34, 1, False,
    request=Struct(
        ("dirs", Array(Struct(
            ("path", String),
            ("topics", Array(Struct(
                ("name", String),
                ("partitions", Array(Int32)),
            ))),
        ))),
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("results", Array(Struct(
            ("topic_name", String),
            ("partitions", Array(Struct(
                ("partition_index", Int32),
                ("error_code", Int16),
            ))),
        ))),
    ),
)

DESCRIBE_LOG_DIRS = Api(
    "DescribeLogDirs", 35, 0, False,
    request=Struct(
        ("topics", Array(Struct(
            ("topic", String),
            ("partitions", Array(Int32)),
        ), nullable=True)),  # null -> all
    ),
    response=Struct(
        ("throttle_time_ms", Int32),
        ("results", Array(Struct(
            ("error_code", Int16),
            ("log_dir", String),
            ("topics", Array(Struct(
                ("name", String),
                ("partitions", Array(Struct(
                    ("partition_index", Int32),
                    ("partition_size", Int64),
                    ("offset_lag", Int64),
                    ("is_future_key", Boolean),
                ))),
            ))),
        ))),
    ),
)

# ------------------------------------------------------------------- SASL

#: SaslHandshake v1 + SaslAuthenticate v0 (KIP-152 framed authentication;
#: the reference rides the JVM client's identical exchange via JAAS,
#: config/cruise_control_jaas.conf_template)
SASL_HANDSHAKE = Api(
    "SaslHandshake", 17, 1, False,
    request=Struct(("mechanism", String)),
    response=Struct(
        ("error_code", Int16),
        ("mechanisms", Array(String)),
    ),
)

SASL_AUTHENTICATE = Api(
    "SaslAuthenticate", 36, 0, False,
    request=Struct(("auth_bytes", Bytes)),
    response=Struct(
        ("error_code", Int16),
        ("error_message", NullableString),
        ("auth_bytes", Bytes),
    ),
)

ALL_APIS = [
    PRODUCE, FETCH, LIST_OFFSETS, CREATE_TOPICS,
    API_VERSIONS, METADATA, ALTER_PARTITION_REASSIGNMENTS,
    LIST_PARTITION_REASSIGNMENTS, ELECT_LEADERS, INCREMENTAL_ALTER_CONFIGS,
    DESCRIBE_CONFIGS, ALTER_REPLICA_LOG_DIRS, DESCRIBE_LOG_DIRS,
]

#: negotiated only when SASL is configured — deliberately NOT part of the
#: check_api_support sweep (a PLAINTEXT listener does not advertise them)
SASL_APIS = [SASL_HANDSHAKE, SASL_AUTHENTICATE]

BY_KEY_VERSION = {(a.key, a.version): a for a in ALL_APIS + SASL_APIS}


# ------------------------------------------------------------------ headers

REQUEST_HEADER_V1 = Struct(  # classic APIs
    ("api_key", Int16), ("api_version", Int16),
    ("correlation_id", Int32), ("client_id", NullableString),
)
REQUEST_HEADER_V2 = Struct(  # flexible APIs (KIP-482)
    ("api_key", Int16), ("api_version", Int16),
    ("correlation_id", Int32), ("client_id", NullableString),
    ("_tags", TagBuffer),
)
RESPONSE_HEADER_V0 = Struct(("correlation_id", Int32))
RESPONSE_HEADER_V1 = Struct(("correlation_id", Int32), ("_tags", TagBuffer))


def encode_request(api: Api, correlation_id: int, client_id: str, body: dict) -> bytes:
    header = REQUEST_HEADER_V2 if api.flexible else REQUEST_HEADER_V1
    out = bytearray()
    header.write(out, {
        "api_key": api.key, "api_version": api.version,
        "correlation_id": correlation_id, "client_id": client_id,
    })
    api.request.write(out, body)
    framed = bytearray()
    Int32.write(framed, len(out))
    framed += out
    return bytes(framed)


def decode_response(api: Api, payload: bytes) -> tuple[int, dict]:
    """payload excludes the length frame; returns (correlation_id, body)."""
    header = RESPONSE_HEADER_V1 if api.flexible else RESPONSE_HEADER_V0
    h, off = header.read(payload, 0)
    body, off = api.response.read(payload, off)
    return h["correlation_id"], body


def decode_request(payload: bytes) -> tuple[Api, int, str, dict]:
    """Server side (fake broker): payload excludes the length frame."""
    # api_key/api_version determine the header+body schema
    api_key, _ = Int16.read(payload, 0)
    api_version, _ = Int16.read(payload, 2)
    api = BY_KEY_VERSION.get((api_key, api_version))
    if api is None:
        raise ValueError(f"unsupported api {api_key} v{api_version}")
    header = REQUEST_HEADER_V2 if api.flexible else REQUEST_HEADER_V1
    h, off = header.read(payload, 0)
    body, off = api.request.read(payload, off)
    return api, h["correlation_id"], h["client_id"], body


def encode_response(api: Api, correlation_id: int, body: dict) -> bytes:
    header = RESPONSE_HEADER_V1 if api.flexible else RESPONSE_HEADER_V0
    out = bytearray()
    header.write(out, {"correlation_id": correlation_id})
    api.response.write(out, body)
    framed = bytearray()
    Int32.write(framed, len(out))
    framed += out
    return bytes(framed)
