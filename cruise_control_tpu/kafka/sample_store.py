"""Sample store persisted to Kafka topics — the warm-restart path.

Reference: monitor/sampling/KafkaSampleStore.java:117-128 persists
partition/broker metric samples to two Kafka topics
(`partition.metric.sample.store.topic` / broker variant) and replays them
on startup (SampleLoadingTask.java) so a restarted service regains its
windowed load model without waiting num.windows sampling rounds.

This implementation rides the same wire-protocol data plane as the metric
stream (kafka/transport.py): samples are packed into a compact binary
record (one per MetricSample) and produced in record batches; `load()`
fetches every partition from offset 0.

Topic identity: partition samples are keyed by topic NAME on the wire —
the in-memory dense topic ids are interned per process in first-seen order
(monitor builder / reporter sampler), so a raw id persisted before a
restart could point at a different topic afterwards.  `topic_name_fn` /
`topic_id_fn` translate id <-> name at the store boundary; the monitor
catalog's `ClusterCatalog.topic_id` is the natural `topic_id_fn` (O(1),
dict-backed — it is called once per replayed sample).

Record layout (little-endian):
  kind u8 (0=partition, 1=broker) | id i32 | partition i32 | time_ms i64 |
  n_values u16 | name_len u16 | topic_name utf8 | values f32[n]
"""

from __future__ import annotations

import struct
import time
from typing import Callable

import numpy as np

from cruise_control_tpu.kafka.client import KafkaAdminClient
from cruise_control_tpu.kafka.transport import KafkaMetricsConsumer, KafkaMetricsTransport
from cruise_control_tpu.monitor.sampling import (
    BrokerEntity,
    MetricSample,
    PartitionEntity,
    SamplingResult,
)

_HEAD = struct.Struct("<BiiqHH")

PARTITION_SAMPLE_TOPIC = "__KafkaCruiseControlPartitionMetricSamples"
BROKER_SAMPLE_TOPIC = "__KafkaCruiseControlModelTrainingSamples"


class KafkaSampleStore:
    """SampleStore SPI over the two reference sample topics.

    topic_name_fn: dense topic id -> topic name (used at store time);
    topic_id_fn: topic name -> dense topic id in THIS process (load time).
    Both default to numeric passthrough, which is only safe when the
    process's topic interning is stable across restarts — pass real
    mappings (e.g. from the monitor's catalog) in production.
    """

    def __init__(
        self,
        client: KafkaAdminClient,
        *,
        partition_topic: str = PARTITION_SAMPLE_TOPIC,
        broker_topic: str = BROKER_SAMPLE_TOPIC,
        topic_name_fn: Callable[[int], str] | None = None,
        topic_id_fn: Callable[[str], int] | None = None,
        metric_def=None,
    ):
        from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF

        self.client = client
        self.topic_name_fn = topic_name_fn or str
        self.topic_id_fn = topic_id_fn or int
        self.metric_def = metric_def or KAFKA_METRIC_DEF
        # ensure the store topics exist (reference ensureTopicsCreated;
        # 36 = TOPIC_ALREADY_EXISTS is the normal warm-restart case)
        codes = client.create_topics(
            [(partition_topic, 4, 2), (broker_topic, 4, 2)]
        )
        bad = {t: c for t, c in codes.items() if c not in (0, 36)}
        if bad:
            raise RuntimeError(f"sample-store topic creation failed: {bad}")
        self._p_out = KafkaMetricsTransport(client, partition_topic, flush_every=5000)
        self._b_out = KafkaMetricsTransport(client, broker_topic, flush_every=5000)
        self._p_topic = partition_topic
        self._b_topic = broker_topic

    # ---- wire format ----

    def _pack(self, kind: int, a: int, b: int, time_ms: int, name: str, values) -> bytes:
        vals = np.asarray(values, np.float32)
        raw = name.encode()
        return (
            _HEAD.pack(kind, a, b, time_ms, vals.size, len(raw))
            + raw
            + vals.tobytes()
        )

    def _unpack(self, payload: bytes) -> MetricSample:
        kind, a, b, time_ms, n, name_len = _HEAD.unpack_from(payload)
        name = payload[_HEAD.size: _HEAD.size + name_len].decode()
        vals = np.frombuffer(
            payload, np.float32, count=n, offset=_HEAD.size + name_len
        )
        # samples persisted before a metric-def extension replay with the
        # OLD vector width — pad new metrics with zeros (and tolerate a
        # future shrink by truncating) so a warm restart survives upgrades
        m = self.metric_def.num_metrics
        if vals.size < m:
            vals = np.concatenate([vals, np.zeros(m - vals.size, np.float32)])
        elif vals.size > m:
            vals = vals[:m]
        if kind == 0:
            entity = PartitionEntity(self.topic_id_fn(name), b)
        else:
            entity = BrokerEntity(a)
        return MetricSample(entity, time_ms, vals)

    # ---- SampleStore SPI ----

    def store(self, result: SamplingResult) -> None:
        for s in result.partition_samples:
            self._p_out.send(self._pack(
                0, s.entity.topic, s.entity.partition, s.time_ms,
                self.topic_name_fn(s.entity.topic), s.values,
            ))
        for s in result.broker_samples:
            self._b_out.send(
                self._pack(1, s.entity.broker_id, -1, s.time_ms, "", s.values)
            )
        self._p_out.flush()
        self._b_out.flush()

    def load(self) -> list[SamplingResult]:
        """Replay everything persisted (reference SampleLoadingTask).

        Each poll issues one Fetch round (bounded bytes per partition), so a
        history larger than one round needs repeated polls — the reference's
        SampleLoadingTask likewise consumes to the log end, not one batch.
        """

        def drain(topic: str) -> list[MetricSample]:
            consumer = KafkaMetricsConsumer(self.client, topic)
            out: list[MetricSample] = []
            stalled = 0
            while True:
                batch = consumer.poll_records()
                if not batch:
                    # an empty round is log-end only if ListOffsets agrees —
                    # transient fetch errors (leader change, offset re-seek)
                    # also yield empty rounds mid-stream.  A partition that
                    # stays unreadable must not hang startup forever: after
                    # 10 stalled rounds return the partial history (the
                    # monitor re-samples what replay missed).
                    stalled += 1
                    if consumer.at_log_end() or stalled > 10:
                        return out
                    time.sleep(0.1 * stalled)
                    continue
                stalled = 0
                out.extend(self._unpack(r) for r in batch)

        parts = drain(self._p_topic)
        brokers = drain(self._b_topic)
        if not parts and not brokers:
            return []
        # one SamplingResult per distinct sample time window keeps the
        # aggregator's per-window sample counts faithful on replay
        by_time: dict[int, tuple[list, list]] = {}
        for s in parts:
            by_time.setdefault(s.time_ms, ([], []))[0].append(s)
        for s in brokers:
            by_time.setdefault(s.time_ms, ([], []))[1].append(s)
        return [
            SamplingResult(ps, bs) for _, (ps, bs) in sorted(by_time.items())
        ]

    def close(self) -> None:
        self._p_out.flush()
        self._b_out.flush()
