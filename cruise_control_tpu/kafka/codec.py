"""Kafka wire-format primitives + declarative schemas.

Implements both encodings of the Kafka protocol (public spec,
kafka.apache.org/protocol):
  * classic: big-endian fixed-width ints, INT16-length strings,
    INT32-length arrays (null = -1);
  * compact/flexible (KIP-482): unsigned-varint length+1 strings/arrays and
    tagged-field buffers.

A schema is a list of (field_name, type) pairs; `Struct.encode` /
`Struct.decode` map dicts <-> bytes.  Types are tiny singletons with
`write(out: bytearray, v)` and `read(buf, off) -> (v, off)`.
"""

from __future__ import annotations

import struct


class CodecError(Exception):
    pass


# ---------------------------------------------------------------- varints


def write_uvarint(out: bytearray, v: int) -> None:
    if v < 0:
        raise CodecError(f"uvarint must be >= 0, got {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_uvarint(buf, off: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if off >= len(buf):
            raise CodecError("truncated uvarint")
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7
        if shift > 63:
            raise CodecError("uvarint too long")


# ---------------------------------------------------------------- primitives


class _Fixed:
    def __init__(self, fmt: str):
        self._s = struct.Struct(fmt)

    def write(self, out: bytearray, v) -> None:
        out += self._s.pack(v)

    def read(self, buf, off: int):
        (v,) = self._s.unpack_from(buf, off)
        return v, off + self._s.size


Int8 = _Fixed(">b")
Int16 = _Fixed(">h")
Int32 = _Fixed(">i")
Int64 = _Fixed(">q")


class _Boolean:
    def write(self, out: bytearray, v) -> None:
        out.append(1 if v else 0)

    def read(self, buf, off: int):
        return buf[off] != 0, off + 1


Boolean = _Boolean()


class _String:
    """Classic STRING / NULLABLE_STRING (INT16 length, -1 = null)."""

    def __init__(self, nullable: bool = False):
        self.nullable = nullable

    def write(self, out: bytearray, v) -> None:
        if v is None:
            if not self.nullable:
                raise CodecError("null for non-nullable string")
            Int16.write(out, -1)
            return
        raw = v.encode()
        Int16.write(out, len(raw))
        out += raw

    def read(self, buf, off: int):
        n, off = Int16.read(buf, off)
        if n == -1:
            return None, off
        return bytes(buf[off: off + n]).decode(), off + n


String = _String()
NullableString = _String(nullable=True)


class _CompactString:
    """COMPACT_STRING / COMPACT_NULLABLE_STRING (uvarint length+1, 0 = null)."""

    def __init__(self, nullable: bool = False):
        self.nullable = nullable

    def write(self, out: bytearray, v) -> None:
        if v is None:
            if not self.nullable:
                raise CodecError("null for non-nullable compact string")
            write_uvarint(out, 0)
            return
        raw = v.encode()
        write_uvarint(out, len(raw) + 1)
        out += raw

    def read(self, buf, off: int):
        n, off = read_uvarint(buf, off)
        if n == 0:
            return None, off
        n -= 1
        return bytes(buf[off: off + n]).decode(), off + n


CompactString = _CompactString()
CompactNullableString = _CompactString(nullable=True)


class _Bytes:
    """Classic BYTES / NULLABLE_BYTES (INT32 length, -1 = null)."""

    def __init__(self, nullable: bool = False):
        self.nullable = nullable

    def write(self, out: bytearray, v) -> None:
        if v is None:
            if not self.nullable:
                raise CodecError("null for non-nullable bytes")
            Int32.write(out, -1)
            return
        Int32.write(out, len(v))
        out += v

    def read(self, buf, off: int):
        n, off = Int32.read(buf, off)
        if n == -1:
            return None, off
        return bytes(buf[off: off + n]), off + n


Bytes = _Bytes()
NullableBytes = _Bytes(nullable=True)


class Array:
    """Classic ARRAY (INT32 count, -1 = null)."""

    def __init__(self, inner, nullable: bool = False):
        self.inner = inner
        self.nullable = nullable

    def write(self, out: bytearray, v) -> None:
        if v is None:
            if not self.nullable:
                raise CodecError("null for non-nullable array")
            Int32.write(out, -1)
            return
        Int32.write(out, len(v))
        for item in v:
            self.inner.write(out, item)

    def read(self, buf, off: int):
        n, off = Int32.read(buf, off)
        if n == -1:
            return None, off
        items = []
        for _ in range(n):
            item, off = self.inner.read(buf, off)
            items.append(item)
        return items, off


class CompactArray:
    """COMPACT_ARRAY (uvarint count+1, 0 = null)."""

    def __init__(self, inner, nullable: bool = False):
        self.inner = inner
        self.nullable = nullable

    def write(self, out: bytearray, v) -> None:
        if v is None:
            if not self.nullable:
                raise CodecError("null for non-nullable compact array")
            write_uvarint(out, 0)
            return
        write_uvarint(out, len(v) + 1)
        for item in v:
            self.inner.write(out, item)

    def read(self, buf, off: int):
        n, off = read_uvarint(buf, off)
        if n == 0:
            return None, off
        items = []
        for _ in range(n - 1):
            item, off = self.inner.read(buf, off)
            items.append(item)
        return items, off


class _TagBuffer:
    """Flexible-version tagged fields; we never send or interpret any."""

    def write(self, out: bytearray, v=None) -> None:
        write_uvarint(out, 0)

    def read(self, buf, off: int):
        n, off = read_uvarint(buf, off)
        for _ in range(n):
            _tag, off = read_uvarint(buf, off)
            size, off = read_uvarint(buf, off)
            off += size  # skip unknown tagged field
        return None, off


TagBuffer = _TagBuffer()


class Struct:
    """Named-field record: encodes/decodes dicts by schema order.

    Fields named "_tags" (TagBuffer) are emitted/consumed but not surfaced
    in the dict.
    """

    def __init__(self, *fields: tuple[str, object]):
        self.fields = fields

    def write(self, out: bytearray, v: dict) -> None:
        for name, typ in self.fields:
            if name.startswith("_tags"):
                typ.write(out)
            else:
                typ.write(out, v[name])

    def read(self, buf, off: int):
        out = {}
        for name, typ in self.fields:
            val, off = typ.read(buf, off)
            if not name.startswith("_tags"):
                out[name] = val
        return out, off

    def encode(self, v: dict) -> bytes:
        out = bytearray()
        self.write(out, v)
        return bytes(out)

    def decode(self, buf) -> dict:
        v, off = self.read(buf, 0)
        if off != len(buf):
            raise CodecError(f"{len(buf) - off} trailing bytes after decode")
        return v
