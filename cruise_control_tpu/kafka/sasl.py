"""SASL/SCRAM-SHA-256 + SCRAM-SHA-512 (RFC 5802/7677) for the wire client.

Reference parity: the reference service gets SASL for free from the JVM
clients via JAAS (config/cruise_control_jaas.conf_template); this client
speaks the SaslHandshake (key 17) + SaslAuthenticate (key 36) exchange
itself.  Both halves of SCRAM live here: the client exchange used by
BrokerConnection, and the server-side verifier used by the fake broker so
the contract can be tested end to end over live sockets.

PLAIN (RFC 4616) is also provided — some clusters still terminate SASL
PLAIN over TLS.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import os


_HASHES = {
    "SCRAM-SHA-256": hashlib.sha256,
    "SCRAM-SHA-512": hashlib.sha512,
}


@dataclasses.dataclass(frozen=True)
class SaslCredentials:
    """What the operator configures (sasl.mechanism/username/password)."""

    username: str
    password: str
    mechanism: str = "SCRAM-SHA-256"

    def __post_init__(self):
        if self.mechanism not in (*_HASHES, "PLAIN"):
            raise ValueError(
                f"unsupported sasl.mechanism {self.mechanism!r}; "
                f"supported: PLAIN, {', '.join(_HASHES)}"
            )


def _hm(h, key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, h).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def salted_password(mechanism: str, password: str, salt: bytes, iterations: int) -> bytes:
    h = _HASHES[mechanism]
    return hashlib.pbkdf2_hmac(h().name, password.encode(), salt, iterations)


def _escape(username: str) -> str:
    return username.replace("=", "=3D").replace(",", "=2C")


class ScramClient:
    """Client half of one SCRAM conversation.

    first() -> client-first-message; final(server_first) -> client-final;
    verify(server_final) checks the server signature (mutual auth).
    """

    def __init__(self, creds: SaslCredentials, nonce: str | None = None):
        self.creds = creds
        self.h = _HASHES[creds.mechanism]
        self.cnonce = nonce or base64.b64encode(os.urandom(18)).decode()
        self._client_first_bare = f"n={_escape(creds.username)},r={self.cnonce}"
        self._server_sig: bytes | None = None

    def first(self) -> bytes:
        return f"n,,{self._client_first_bare}".encode()

    def final(self, server_first: bytes) -> bytes:
        sf = server_first.decode()
        attrs = dict(kv.split("=", 1) for kv in sf.split(","))
        rnonce, salt, iters = attrs["r"], base64.b64decode(attrs["s"]), int(attrs["i"])
        if not rnonce.startswith(self.cnonce):
            raise ValueError("server nonce does not extend client nonce")
        salted = salted_password(self.creds.mechanism, self.creds.password, salt, iters)
        client_key = _hm(self.h, salted, b"Client Key")
        stored_key = self.h(client_key).digest()
        channel = base64.b64encode(b"n,,").decode()
        auth_msg = f"{self._client_first_bare},{sf},c={channel},r={rnonce}".encode()
        client_sig = _hm(self.h, stored_key, auth_msg)
        proof = base64.b64encode(_xor(client_key, client_sig)).decode()
        server_key = _hm(self.h, salted, b"Server Key")
        self._server_sig = _hm(self.h, server_key, auth_msg)
        return f"c={channel},r={rnonce},p={proof}".encode()

    def verify(self, server_final: bytes) -> None:
        attrs = dict(kv.split("=", 1) for kv in server_final.decode().split(","))
        if "e" in attrs:
            raise PermissionError(f"SASL authentication failed: {attrs['e']}")
        if self._server_sig is None or not hmac.compare_digest(
            base64.b64decode(attrs["v"]), self._server_sig
        ):
            raise PermissionError("server signature mismatch (not the real broker?)")


class ScramServer:
    """Server half, for the fake broker: verifies a client conversation
    against a username -> password table (a real broker stores the derived
    StoredKey/ServerKey in ZK/KRaft; deriving from the password here keeps
    the fake simple while exercising the same math)."""

    def __init__(self, mechanism: str, users: dict[str, str], *, iterations: int = 4096):
        self.mechanism = mechanism
        self.h = _HASHES[mechanism]
        self.users = users
        self.iterations = iterations
        self._state: dict = {}

    def respond(self, client_msg: bytes) -> tuple[bytes, bool, bool]:
        """-> (server_msg, done, ok).  First call handles client-first,
        second client-final."""
        if not self._state:
            text = client_msg.decode()
            if not text.startswith("n,,"):
                return b"e=channel-binding-not-supported", True, False
            bare = text[3:]
            attrs = dict(kv.split("=", 1) for kv in bare.split(","))
            user = attrs["n"].replace("=2C", ",").replace("=3D", "=")
            password = self.users.get(user)
            if password is None:
                return b"e=unknown-user", True, False
            salt = os.urandom(16)
            rnonce = attrs["r"] + base64.b64encode(os.urandom(12)).decode()
            server_first = (
                f"r={rnonce},s={base64.b64encode(salt).decode()},i={self.iterations}"
            )
            self._state = dict(
                bare=bare, rnonce=rnonce, salt=salt, server_first=server_first,
                password=password,
            )
            return server_first.encode(), False, True
        st = self._state
        attrs = dict(kv.split("=", 1) for kv in client_msg.decode().split(","))
        if attrs.get("r") != st["rnonce"]:
            return b"e=other-error", True, False
        salted = salted_password(
            self.mechanism, st["password"], st["salt"], self.iterations
        )
        client_key = _hm(self.h, salted, b"Client Key")
        stored_key = self.h(client_key).digest()
        auth_msg = (
            f"{st['bare']},{st['server_first']},c={attrs['c']},r={attrs['r']}".encode()
        )
        client_sig = _hm(self.h, stored_key, auth_msg)
        expected = _xor(client_key, client_sig)
        try:
            got = base64.b64decode(attrs["p"])
        except Exception:  # noqa: BLE001
            return b"e=invalid-proof", True, False
        if not hmac.compare_digest(expected, got):
            return b"e=invalid-proof", True, False
        server_key = _hm(self.h, salted, b"Server Key")
        server_sig = _hm(self.h, server_key, auth_msg)
        return b"v=" + base64.b64encode(server_sig), True, True
