"""ClusterAdmin / MetadataProvider adapters over the wire-protocol client.

KafkaClusterAdmin implements the exact SPI the executor drives
(executor/admin.py ClusterAdmin) against a live cluster:

  reassign_partitions        -> AlterPartitionReassignments (KIP-455; replaces
                                the reference's ZK znode writes,
                                ExecutorUtils.scala:31)
  in_progress_reassignments  -> ListPartitionReassignments
                                (ExecutorUtils.scala:103)
  cancel_reassignments       -> AlterPartitionReassignments with null targets
                                (replaces ZK node deletion, Executor.java:1145)
  elect_leaders              -> ElectLeaders PREFERRED (ExecutorUtils.scala:95)
  alter_replica_logdirs      -> AlterReplicaLogDirs per broker
                                (ExecutorAdminUtils.java:1, KIP-113)
  set/clear throttle         -> IncrementalAlterConfigs broker + topic configs
                                (ReplicationThrottleHelper.java:32-47)
  topology                   -> Metadata (+ DescribeLogDirs for logdir axes)

Disk indices: the framework models JBOD logdirs as dense per-broker disk
indices; the adapter maps index <-> path by sorting each broker's logdir
paths (stable across calls because brokers report a fixed logdir set).
"""

from __future__ import annotations

from cruise_control_tpu.executor.admin import LeadershipSpec, ReassignmentSpec
from cruise_control_tpu.kafka.client import KafkaAdminClient, KafkaProtocolError
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
)

_BROKER = 4  # config resource types (public protocol spec)
_TOPIC = 2
_SET = 0
_DELETE = 1

_THROTTLE_RATE_CONFIGS = (
    "leader.replication.throttled.rate",
    "follower.replication.throttled.rate",
)
_THROTTLE_REPLICA_CONFIGS = (
    "leader.replication.throttled.replicas",
    "follower.replication.throttled.replicas",
)


class KafkaClusterAdmin:
    """Real-cluster ClusterAdmin over the Kafka wire protocol."""

    def __init__(self, client: KafkaAdminClient):
        self.client = client
        self._throttled_brokers: set[int] = set()
        self._throttled_topics: set[str] = set()
        #: brokers with possibly in-flight AlterReplicaLogDirs copies —
        #: bounds the DescribeLogDirs polling set
        self._logdir_move_brokers: set[int] = set()
        #: last successfully observed future-replica set per broker — a
        #: transient DescribeLogDirs failure must NOT look like "no copies
        #: pending" (the executor treats absence as completion)
        self._last_futures: dict[int, set[tuple[str, int, int]]] = {}
        #: consecutive DescribeLogDirs failures per broker; past the cap the
        #: broker is only PROBED every _probe_every polls (bounded timeout
        #: cost, but a recovered broker is re-observed — its landed copies
        #: must not be reported dead)
        self._describe_failures: dict[int, int] = {}
        self._max_describe_failures = 5
        self._probe_every = 5
        self._probe_countdown: dict[int, int] = {}
        #: brokers described successfully in the CURRENT poll round — a
        #: cache miss for these means "replica not present anywhere", no
        #: redial needed
        self._described_ok: set[int] = set()
        #: replica -> dense dir index placement from the poll's describes,
        #: so landed-verification is cache-served instead of one RPC per
        #: verified partition
        self._last_placement: dict[tuple[str, int, int], int] = {}

    # --- ClusterAdmin SPI ---

    def reassign_partitions(self, specs: list[ReassignmentSpec]) -> None:
        results = self.client.alter_partition_reassignments({
            (s.topic, s.partition): list(s.new_replicas) for s in specs
        })
        errors = [(t, p, c) for t, p, c, _ in results if c != 0]
        if errors:
            raise KafkaProtocolError(
                "AlterPartitionReassignments", errors[0][2],
                f"{len(errors)} partitions rejected, first: {errors[0][:2]}",
            )

    def in_progress_reassignments(self) -> set[tuple[str, int]]:
        return self.client.list_partition_reassignments()

    def cancel_reassignments(self) -> None:
        in_progress = self.client.list_partition_reassignments()
        if in_progress:
            self.client.alter_partition_reassignments(
                {key: None for key in in_progress}
            )

    def cancel_partition_reassignments(self, keys) -> None:
        """Cancel INDIVIDUAL reassignments (KIP-455 null-replicas form):
        each partition rolls back to its original replica set — the
        stuck-move reaper's rollback path.  A move that completed between
        observation and cancellation (NO_REASSIGNMENT_IN_PROGRESS) is not
        an error: there is nothing left to cancel."""
        self.client.alter_partition_reassignments(
            {(k[0], k[1]): None for k in keys}
        )

    def elect_leaders(self, specs: list[LeadershipSpec]) -> None:
        """Realize leadership moves: make the target the PREFERRED (first)
        replica, then run a preferred election (ExecutorUtils.scala:95).

        A PREFERRED election elects the broker-side replica list's head — so
        when the target is not already first, the assignment must be
        reordered via AlterPartitionReassignments first.  A same-set reorder
        moves no data (every replica is already in ISR) and completes
        immediately on the broker.
        """
        md = self.client.metadata(sorted({s.topic for s in specs}))
        current: dict[tuple[str, int], list[int]] = {
            (t["name"], p["partition_index"]): list(p["replica_nodes"])
            for t in md["topics"]
            for p in t["partitions"]
        }
        reorders: dict[tuple[str, int], list[int]] = {}
        for s in specs:
            key = (s.topic, s.partition)
            replicas = current.get(key)
            if replicas is None or s.preferred_leader not in replicas:
                raise KafkaProtocolError(
                    "ElectLeaders", 3,
                    f"{key}: target {s.preferred_leader} not in assignment {replicas}",
                )
            if replicas[0] != s.preferred_leader:
                reorders[key] = [s.preferred_leader] + [
                    b for b in replicas if b != s.preferred_leader
                ]
        if reorders:
            results = self.client.alter_partition_reassignments(reorders)
            bad = [(t, p, c) for t, p, c, _ in results if c != 0]
            if bad:
                raise KafkaProtocolError(
                    "ElectLeaders", bad[0][2],
                    f"preferred-replica reorder rejected, first: {bad[0][:2]}",
                )
        results = self.client.elect_preferred_leaders(
            [(s.topic, s.partition) for s in specs]
        )
        # 84 = ELECTION_NOT_NEEDED (preferred replica already leads) is success
        errors = [(t, p, c) for t, p, c in results if c not in (0, 84)]
        if errors:
            raise KafkaProtocolError(
                "ElectLeaders", errors[0][2],
                f"{len(errors)} elections failed, first: {errors[0][:2]}",
            )

    def alter_replica_logdirs(self, moves: list[tuple[str, int, int, int]]) -> None:
        """(topic, partition, broker, target_disk_index) intra-broker moves."""
        by_broker: dict[int, dict[str, list[tuple[str, int]]]] = {}
        paths_cache: dict[int, list[str]] = {}
        for topic, part, broker, disk_idx in moves:
            paths = paths_cache.get(broker)
            if paths is None:
                paths = paths_cache[broker] = self._logdir_paths(broker)
            if disk_idx >= len(paths):
                raise ValueError(
                    f"broker {broker} has {len(paths)} logdirs, wanted index {disk_idx}"
                )
            by_broker.setdefault(broker, {}).setdefault(paths[disk_idx], []).append(
                (topic, part)
            )
        for broker, dir_moves in sorted(by_broker.items()):
            results = self.client.alter_replica_logdirs(broker, dir_moves)
            errors = [r for r in results if r[2] != 0]
            if errors:
                raise KafkaProtocolError(
                    "AlterReplicaLogDirs", errors[0][2],
                    f"{len(errors)} moves rejected on broker {broker}",
                )
            self._logdir_move_brokers.add(broker)
            # the submitted copies ARE pending until a describe says
            # otherwise: seed the last-known set so a transient describe
            # failure right after submit cannot read as "nothing pending",
            # drop any stale placement for the moved replicas, and give the
            # broker a fresh failure budget
            keys = {
                (t, p, broker) for tps in dir_moves.values() for (t, p) in tps
            }
            self._last_futures.setdefault(broker, set()).update(keys)
            for key in keys:
                self._last_placement.pop(key, None)
            self._describe_failures.pop(broker, None)

    def in_progress_logdir_moves(self) -> set[tuple[str, int, int]]:
        """(topic, partition, broker) triples whose intra-broker copy is
        still in flight — DescribeLogDirs reports the copying replica under
        the target dir with is_future_key=true (reference ExecutorAdminUtils
        polls log dirs to track AlterReplicaLogDirs completion)."""
        out: set[tuple[str, int, int]] = set()
        # placement cache + described-ok set are scoped to ONE poll round:
        # verification reads what this round's describes observed, never an
        # older execution's stale placements (and both stay bounded)
        self._last_placement.clear()
        self._described_ok.clear()
        for broker in sorted(self._logdir_move_brokers):
            failures = self._describe_failures.get(broker, 0)
            if failures > self._max_describe_failures:
                # past the cap, back off to probing every Nth poll — a
                # permanently-skipped broker could never recover, and a
                # recovered broker's landed copies must not be killed as
                # unverifiable (rolling restarts bounce brokers routinely)
                self._probe_countdown[broker] = (
                    self._probe_countdown.get(broker, 0) - 1
                )
                if self._probe_countdown[broker] > 0:
                    out |= self._last_futures.get(broker, set())
                    continue
                self._probe_countdown[broker] = self._probe_every
            try:
                dirs = self.client.describe_logdirs(broker)
            except (OSError, ConnectionError):
                self._describe_failures[broker] = failures + 1
                if failures + 1 > self._max_describe_failures:
                    # arm the probe backoff the moment the cap is crossed
                    self._probe_countdown[broker] = self._probe_every
                # transient (or probed-and-still-down): report the LAST
                # KNOWN pending copies as still pending — absence here
                # means completion to the executor, and a socket timeout
                # is not completion
                out |= self._last_futures.get(broker, set())
                continue
            self._describe_failures.pop(broker, None)
            self._probe_countdown.pop(broker, None)
            self._described_ok.add(broker)
            futures = set()
            for i, path in enumerate(sorted(dirs)):
                info = dirs[path]
                for t, p in info.get("future_replicas", ()):
                    futures.add((t, p, broker))
                for (t, p) in info.get("replicas", {}):
                    self._last_placement[(t, p, broker)] = i
            self._last_futures[broker] = futures
            out |= futures
            if not futures:
                self._logdir_move_brokers.discard(broker)
                self._last_futures.pop(broker, None)
        return out

    def logdir_of(self, topic: str, partition: int, broker: int) -> int | None:
        """Dense disk index currently hosting (topic, partition) on broker,
        or None if unknown — the executor verifies a finished
        AlterReplicaLogDirs actually LANDED on the target dir.

        Served from the placement observed by the poll's own describes when
        possible (a batch of completions would otherwise cost one full
        DescribeLogDirs round trip per verified partition)."""
        cached = self._last_placement.get((topic, partition, broker))
        if cached is not None:
            return cached
        if broker in self._described_ok:
            # this poll round ALREADY described the broker successfully and
            # the replica was in no dir (e.g. mid log recovery) — redialing
            # would return the same answer for another round trip
            return None
        if self._describe_failures.get(broker, 0) > self._max_describe_failures:
            # backed off (persistently unreachable): answering "unknown"
            # immediately avoids one socket timeout per verification; the
            # poll loop's periodic probe discovers recovery
            return None
        try:
            dirs = self.client.describe_logdirs(broker)
        except (OSError, ConnectionError):
            self._describe_failures[broker] = (
                self._describe_failures.get(broker, 0) + 1
            )
            return None
        self._describe_failures.pop(broker, None)
        out = None
        for i, path in enumerate(sorted(dirs)):
            for (t, p) in dirs[path]["replicas"]:
                self._last_placement[(t, p, broker)] = i
                if (t, p) == (topic, partition):
                    out = i
        return out

    def set_replication_throttle(self, rate_bytes_per_s: float, topics: set[str]) -> None:
        """Reference ReplicationThrottleHelper.java:32-47: per-broker rates +
        per-topic throttled-replica wildcards around an execution."""
        md = self.client.metadata()
        brokers = sorted(b["node_id"] for b in md["brokers"])
        rate = str(int(rate_bytes_per_s))
        resources = [
            (_BROKER, str(b), [(c, _SET, rate) for c in _THROTTLE_RATE_CONFIGS])
            for b in brokers
        ] + [
            (_TOPIC, t, [(c, _SET, "*") for c in _THROTTLE_REPLICA_CONFIGS])
            for t in sorted(topics)
        ]
        self.client.incremental_alter_configs(resources)
        self._throttled_brokers = set(brokers)
        self._throttled_topics = set(topics)

    def clear_replication_throttle(self) -> None:
        """Remove throttles discovered from CLUSTER state, not just this
        process's memory — a crash between set and clear must not leave the
        cluster capped forever (reference ReplicationThrottleHelper removes
        what it finds in the configs)."""
        md = self.client.metadata()
        broker_ids = sorted(b["node_id"] for b in md["brokers"])
        topic_names = sorted(
            t["name"] for t in md["topics"] if t["error_code"] == 0
        )
        # broker-resource describes must be routed TO that broker (dynamic
        # per-broker configs, KIP-226); topic describes may go anywhere
        throttled_brokers = set(self._throttled_brokers)
        for b in broker_ids:
            cfg = self.client.describe_configs(
                [(_BROKER, str(b))],
                names=list(_THROTTLE_RATE_CONFIGS),
                node_id=b,
            ).get((_BROKER, str(b)), {})
            if any(c in cfg for c in _THROTTLE_RATE_CONFIGS):
                throttled_brokers.add(b)
        described = self.client.describe_configs(
            [(_TOPIC, t) for t in topic_names],
            names=list(_THROTTLE_REPLICA_CONFIGS),
        )
        # only clear topic throttles bearing OUR signature (the "*"
        # wildcard set_replication_throttle writes) — an operator's static
        # per-replica throttle list is not ours to delete (reference
        # ReplicationThrottleHelper removes what it set)
        throttled_topics = {
            name for (rt, name), cfg in described.items()
            if rt == _TOPIC
            and any(cfg.get(c) == "*" for c in _THROTTLE_REPLICA_CONFIGS)
        } | self._throttled_topics
        resources = [
            (_BROKER, str(b), [(c, _DELETE, None) for c in _THROTTLE_RATE_CONFIGS])
            for b in sorted(throttled_brokers)
        ] + [
            (_TOPIC, t, [(c, _DELETE, None) for c in _THROTTLE_REPLICA_CONFIGS])
            for t in sorted(throttled_topics)
        ]
        if resources:
            self.client.incremental_alter_configs(resources)
        self._throttled_brokers = set()
        self._throttled_topics = set()

    def topology(self) -> ClusterTopology:
        return _topology_from_metadata(self.client, with_logdirs=True)

    # --- helpers ---

    def _logdir_paths(self, broker: int) -> list[str]:
        """Dense disk index -> logdir path (sorted for stability)."""
        return sorted(self.client.describe_logdirs(broker))


class KafkaMetadataProvider:
    """MetadataProvider over the wire protocol (reference MetadataClient)."""

    def __init__(self, client: KafkaAdminClient):
        self.client = client
        self._generation = 0
        self._topology: ClusterTopology | None = None

    def topology(self) -> ClusterTopology:
        if self._topology is None:
            return self.refresh()
        return self._topology

    def refresh(self) -> ClusterTopology:
        self._generation += 1
        topo = _topology_from_metadata(self.client, with_logdirs=False)
        self._topology = ClusterTopology(
            brokers=topo.brokers, partitions=topo.partitions,
            generation=self._generation,
        )
        return self._topology


def _topology_from_metadata(
    client: KafkaAdminClient, *, with_logdirs: bool
) -> ClusterTopology:
    md = client.metadata()
    brokers = []
    live_ids = set()
    for b in md["brokers"]:
        live_ids.add(b["node_id"])
        logdirs: tuple[str, ...] = ()
        offline: tuple[str, ...] = ()
        if with_logdirs:
            try:
                dirs = client.describe_logdirs(b["node_id"])
                logdirs = tuple(sorted(dirs))
                offline = tuple(
                    sorted(d for d, info in dirs.items() if info["error_code"] != 0)
                )
            except (OSError, ConnectionError):
                pass
        brokers.append(
            BrokerNode(
                broker_id=b["node_id"],
                rack=b["rack"] or "",
                host=b["host"],
                alive=True,
                logdirs=logdirs,
                offline_logdirs=offline,
            )
        )
    # brokers hosting replicas but absent from metadata = failed brokers:
    # surface them as dead BrokerNodes (the BrokerFailureDetector's signal —
    # replaces the reference's ZK /brokers/ids watch,
    # detector/BrokerFailureDetector.java:88)
    partitions = []
    referenced: set[int] = set()
    for t in md["topics"]:
        if t["error_code"] != 0 or t["is_internal"]:
            continue
        for p in t["partitions"]:
            replicas = tuple(p["replica_nodes"])
            referenced.update(replicas)
            partitions.append(
                PartitionInfo(
                    topic=t["name"],
                    partition=p["partition_index"],
                    leader=p["leader_id"],
                    replicas=replicas,
                )
            )
    for dead in sorted(referenced - live_ids):
        brokers.append(BrokerNode(broker_id=dead, rack="", host="", alive=False))
    brokers.sort(key=lambda b: b.broker_id)
    return ClusterTopology(brokers=tuple(brokers), partitions=tuple(partitions))
