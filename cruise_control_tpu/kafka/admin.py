"""ClusterAdmin / MetadataProvider adapters over the wire-protocol client.

KafkaClusterAdmin implements the exact SPI the executor drives
(executor/admin.py ClusterAdmin) against a live cluster:

  reassign_partitions        -> AlterPartitionReassignments (KIP-455; replaces
                                the reference's ZK znode writes,
                                ExecutorUtils.scala:31)
  in_progress_reassignments  -> ListPartitionReassignments
                                (ExecutorUtils.scala:103)
  cancel_reassignments       -> AlterPartitionReassignments with null targets
                                (replaces ZK node deletion, Executor.java:1145)
  elect_leaders              -> ElectLeaders PREFERRED (ExecutorUtils.scala:95)
  alter_replica_logdirs      -> AlterReplicaLogDirs per broker
                                (ExecutorAdminUtils.java:1, KIP-113)
  set/clear throttle         -> IncrementalAlterConfigs broker + topic configs
                                (ReplicationThrottleHelper.java:32-47)
  topology                   -> Metadata (+ DescribeLogDirs for logdir axes)

Disk indices: the framework models JBOD logdirs as dense per-broker disk
indices; the adapter maps index <-> path by sorting each broker's logdir
paths (stable across calls because brokers report a fixed logdir set).
"""

from __future__ import annotations

from cruise_control_tpu.executor.admin import LeadershipSpec, ReassignmentSpec
from cruise_control_tpu.kafka.client import KafkaAdminClient, KafkaProtocolError
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
)

_BROKER = 4  # config resource types (public protocol spec)
_TOPIC = 2
_SET = 0
_DELETE = 1

_THROTTLE_RATE_CONFIGS = (
    "leader.replication.throttled.rate",
    "follower.replication.throttled.rate",
)
_THROTTLE_REPLICA_CONFIGS = (
    "leader.replication.throttled.replicas",
    "follower.replication.throttled.replicas",
)


class KafkaClusterAdmin:
    """Real-cluster ClusterAdmin over the Kafka wire protocol."""

    def __init__(self, client: KafkaAdminClient):
        self.client = client
        self._throttled_brokers: set[int] = set()
        self._throttled_topics: set[str] = set()

    # --- ClusterAdmin SPI ---

    def reassign_partitions(self, specs: list[ReassignmentSpec]) -> None:
        results = self.client.alter_partition_reassignments({
            (s.topic, s.partition): list(s.new_replicas) for s in specs
        })
        errors = [(t, p, c) for t, p, c, _ in results if c != 0]
        if errors:
            raise KafkaProtocolError(
                "AlterPartitionReassignments", errors[0][2],
                f"{len(errors)} partitions rejected, first: {errors[0][:2]}",
            )

    def in_progress_reassignments(self) -> set[tuple[str, int]]:
        return self.client.list_partition_reassignments()

    def cancel_reassignments(self) -> None:
        in_progress = self.client.list_partition_reassignments()
        if in_progress:
            self.client.alter_partition_reassignments(
                {key: None for key in in_progress}
            )

    def elect_leaders(self, specs: list[LeadershipSpec]) -> None:
        # the executor encodes the target leader as the preferred (first)
        # replica; PREFERRED election realizes it (ExecutorUtils.scala:95)
        results = self.client.elect_preferred_leaders(
            [(s.topic, s.partition) for s in specs]
        )
        # 84 = ELECTION_NOT_NEEDED (preferred replica already leads) is success
        errors = [(t, p, c) for t, p, c in results if c not in (0, 84)]
        if errors:
            raise KafkaProtocolError(
                "ElectLeaders", errors[0][2],
                f"{len(errors)} elections failed, first: {errors[0][:2]}",
            )

    def alter_replica_logdirs(self, moves: list[tuple[str, int, int, int]]) -> None:
        """(topic, partition, broker, target_disk_index) intra-broker moves."""
        by_broker: dict[int, dict[str, list[tuple[str, int]]]] = {}
        paths_cache: dict[int, list[str]] = {}
        for topic, part, broker, disk_idx in moves:
            paths = paths_cache.get(broker)
            if paths is None:
                paths = paths_cache[broker] = self._logdir_paths(broker)
            if disk_idx >= len(paths):
                raise ValueError(
                    f"broker {broker} has {len(paths)} logdirs, wanted index {disk_idx}"
                )
            by_broker.setdefault(broker, {}).setdefault(paths[disk_idx], []).append(
                (topic, part)
            )
        for broker, dir_moves in sorted(by_broker.items()):
            results = self.client.alter_replica_logdirs(broker, dir_moves)
            errors = [r for r in results if r[2] != 0]
            if errors:
                raise KafkaProtocolError(
                    "AlterReplicaLogDirs", errors[0][2],
                    f"{len(errors)} moves rejected on broker {broker}",
                )

    def set_replication_throttle(self, rate_bytes_per_s: float, topics: set[str]) -> None:
        """Reference ReplicationThrottleHelper.java:32-47: per-broker rates +
        per-topic throttled-replica wildcards around an execution."""
        self.client.metadata()
        brokers = sorted(self.client._brokers)
        rate = str(int(rate_bytes_per_s))
        resources = [
            (_BROKER, str(b), [(c, _SET, rate) for c in _THROTTLE_RATE_CONFIGS])
            for b in brokers
        ] + [
            (_TOPIC, t, [(c, _SET, "*") for c in _THROTTLE_REPLICA_CONFIGS])
            for t in sorted(topics)
        ]
        self.client.incremental_alter_configs(resources)
        self._throttled_brokers = set(brokers)
        self._throttled_topics = set(topics)

    def clear_replication_throttle(self) -> None:
        resources = [
            (_BROKER, str(b), [(c, _DELETE, None) for c in _THROTTLE_RATE_CONFIGS])
            for b in sorted(self._throttled_brokers)
        ] + [
            (_TOPIC, t, [(c, _DELETE, None) for c in _THROTTLE_REPLICA_CONFIGS])
            for t in sorted(self._throttled_topics)
        ]
        if resources:
            self.client.incremental_alter_configs(resources)
        self._throttled_brokers = set()
        self._throttled_topics = set()

    def topology(self) -> ClusterTopology:
        return _topology_from_metadata(self.client, with_logdirs=True)

    # --- helpers ---

    def _logdir_paths(self, broker: int) -> list[str]:
        """Dense disk index -> logdir path (sorted for stability)."""
        return sorted(self.client.describe_logdirs(broker))


class KafkaMetadataProvider:
    """MetadataProvider over the wire protocol (reference MetadataClient)."""

    def __init__(self, client: KafkaAdminClient):
        self.client = client
        self._generation = 0
        self._topology: ClusterTopology | None = None

    def topology(self) -> ClusterTopology:
        if self._topology is None:
            return self.refresh()
        return self._topology

    def refresh(self) -> ClusterTopology:
        self._generation += 1
        topo = _topology_from_metadata(self.client, with_logdirs=False)
        self._topology = ClusterTopology(
            brokers=topo.brokers, partitions=topo.partitions,
            generation=self._generation,
        )
        return self._topology


def _topology_from_metadata(
    client: KafkaAdminClient, *, with_logdirs: bool
) -> ClusterTopology:
    md = client.metadata()
    brokers = []
    live_ids = set()
    for b in md["brokers"]:
        live_ids.add(b["node_id"])
        logdirs: tuple[str, ...] = ()
        offline: tuple[str, ...] = ()
        if with_logdirs:
            try:
                dirs = client.describe_logdirs(b["node_id"])
                logdirs = tuple(sorted(dirs))
                offline = tuple(
                    sorted(d for d, info in dirs.items() if info["error_code"] != 0)
                )
            except (OSError, ConnectionError):
                pass
        brokers.append(
            BrokerNode(
                broker_id=b["node_id"],
                rack=b["rack"] or "",
                host=b["host"],
                alive=True,
                logdirs=logdirs,
                offline_logdirs=offline,
            )
        )
    # brokers hosting replicas but absent from metadata = failed brokers:
    # surface them as dead BrokerNodes (the BrokerFailureDetector's signal —
    # replaces the reference's ZK /brokers/ids watch,
    # detector/BrokerFailureDetector.java:88)
    partitions = []
    referenced: set[int] = set()
    for t in md["topics"]:
        if t["error_code"] != 0 or t["is_internal"]:
            continue
        for p in t["partitions"]:
            replicas = tuple(p["replica_nodes"])
            referenced.update(replicas)
            partitions.append(
                PartitionInfo(
                    topic=t["name"],
                    partition=p["partition_index"],
                    leader=p["leader_id"],
                    replicas=replicas,
                )
            )
    for dead in sorted(referenced - live_ids):
        brokers.append(BrokerNode(broker_id=dead, rack="", host="", alive=False))
    brokers.sort(key=lambda b: b.broker_id)
    return ClusterTopology(brokers=tuple(brokers), partitions=tuple(partitions))
