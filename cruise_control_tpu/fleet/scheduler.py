"""QoS-aware device scheduler: fleet overload protection for the shared TPU.

The fleet controller (fleet/manager.py) multiplexes N clusters' control
cycles onto ONE device with no arbitration: a broker-failure re-anneal
queues FIFO behind a hundred steady-state drift cycles, so the component
that exists to react to failures is the one starved by background load
exactly when fleet density grows.  Learned cluster schedulers make the
fix explicit — work classes with priorities and deadline-aware placement
onto the contended resource (PAPERS.md arXiv:2603.10545); this module is
that scheduler for the engine dispatch path:

  * three WORK CLASSES — URGENT (detector fix pipelines: broker failure,
    EXECUTION_STUCK, lease-takeover re-anneals), INTERACTIVE (REST-path
    proposals / simulate / rightsize), BACKGROUND (streaming drift
    cycles, fleet batched scoring, speculative prewarm);
  * a DEADLINE per request derived from the per-cluster proposal-
    freshness SLO (`fleet.scheduler.freshness.slo.s`): BACKGROUND gets
    the full SLO, INTERACTIVE a quarter of it, URGENT one slice budget —
    grants are earliest-deadline-first within a class and misses are
    counted per class;
  * AGING so BACKGROUND can never starve: a background ticket that has
    waited `fleet.scheduler.aging.s` is ranked with the interactive
    class, where its (older) deadline eventually wins the EDF tiebreak;
  * BOUNDED-WALL PREEMPTION: a granted non-urgent anneal runs SEGMENTED
    (analyzer/engine.py `segmented_execution`) — the fused schedule is
    dispatched in slices bounded by `fleet.scheduler.slice.budget.s`,
    and the between-slices checkpoint pauses the holder whenever an
    URGENT ticket is waiting, so an urgent request's queue-to-dispatch
    wait is at most ONE slice of background work (byte parity of the
    segmented run is pinned by tests/test_scheduler.py);
  * a SHED/BROWNOUT ladder wired into the existing per-tenant admission
    control: past the queue-depth/deadline-miss threshold, BACKGROUND
    submissions shed first (counted in `fleet.scheduler.shed-total.*`,
    never silently), then INTERACTIVE admissions 429 with a Retry-After
    computed from the tenant queue's drain rate — URGENT is never shed.
    Overload SUSTAINED past `fleet.scheduler.brownout.after.s` switches
    background from shed to BROWNOUT: re-anneals run with a reduced
    candidate/restart width (`brownout_config`) instead of being
    skipped, so proposal freshness degrades gracefully instead of going
    dark.  Each overload episode fires ONE `FLEET_OVERLOAD` alert-only
    anomaly through the detector/notifier.

Default OFF (`fleet.scheduler.enabled=false`): no scheduler object
exists and every dispatch path is byte-for-byte today's order.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import enum
import logging
import threading
import time
from collections import deque

from cruise_control_tpu.common.blackbox import (
    RECORDER as _BLACKBOX,
    blackbox_context,
)

log = logging.getLogger(__name__)


class WorkClass(enum.IntEnum):
    """Priority order: lower value is granted first (before aging)."""

    URGENT = 0
    INTERACTIVE = 1
    BACKGROUND = 2

    @property
    def label(self) -> str:
        return self.name.lower()


class BackgroundShedError(RuntimeError):
    """A BACKGROUND submission was shed by overload protection — the
    caller (controller cycle, fleet scoring, speculative prewarm) skips
    this cycle; the shed is already counted, never silent."""


class SchedulerOverloadError(RuntimeError):
    """INTERACTIVE admission rejected under severe overload — surfaces as
    429 with the carried Retry-After (server.py), exactly like the
    per-tenant admission cap."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


#: the ambient grant: set while a thread (or a supervisor worker running
#: with the caller's context copied in) executes under a scheduler slot.
#: Nested run() calls execute inline under the outer grant — an URGENT
#: fix pipeline's inner proposals() call must not deadlock waiting on
#: the slot its own pipeline holds.
_HELD: contextvars.ContextVar = contextvars.ContextVar(
    "device_scheduler_held", default=None
)

#: ambient work-class tag: a pipeline-level classification (the detector
#: tags its whole fix pipeline URGENT) that the device-adjacent sections
#: pick up when they acquire the slot.  Tagging instead of holding the
#: slot across the pipeline matters: a fix includes minutes of EXECUTOR
#: work that dispatches nothing — holding the device through it would
#: starve every other tenant for no reason.
_CLASS_TAG: contextvars.ContextVar = contextvars.ContextVar(
    "device_scheduler_class_tag", default=None
)


@contextlib.contextmanager
def tagged(work_class: WorkClass):
    """Tag the enclosed pipeline's device dispatches with (at least) this
    work class; a more urgent ambient tag always wins over the dispatch
    site's default (see `effective_class`)."""
    token = _CLASS_TAG.set(work_class)
    try:
        yield
    finally:
        _CLASS_TAG.reset(token)


def effective_class(default: WorkClass) -> WorkClass:
    """The dispatch site's class, upgraded by any more-urgent ambient
    pipeline tag (never downgraded: a BACKGROUND tag cannot demote an
    interactive request that happens to run inside it)."""
    tag = _CLASS_TAG.get()
    if tag is None:
        return default
    return tag if tag < default else default




@dataclasses.dataclass
class _Ticket:
    work_class: WorkClass
    cluster_id: str
    op: str
    enqueued: float
    deadline: float
    seq: int
    granted: bool = False
    #: a preempted holder waiting to resume: ranked after URGENT but
    #: before every queued ticket, so the paused anneal continues the
    #: moment the urgent work drains (its slot wait is already paid)
    resuming: bool = False
    #: the caller's run() has exited (fn returned OR raised — e.g. the
    #: DeviceSupervisor abandoning a timed-out dispatch while its worker
    #: sits paused in a checkpoint): the ticket must never be granted
    #: again, and a paused worker stops waiting for the slot
    cancelled: bool = False
    #: cumulative wall this ticket spent PAUSED at preemption
    #: checkpoints — read (cross-thread, via the scheduler's pause
    #: clock) by the DeviceSupervisor's bounded wait so
    #: scheduler-imposed pauses do not bill against the device-hang
    #: budget
    paused_s: float = 0.0
    #: clock stamp of a pause currently IN PROGRESS (None otherwise):
    #: the pause clock must include it, or a single pause longer than
    #: the remaining hang budget would still trip DeviceHangError —
    #: the exact failure the clock exists to prevent
    pause_started: float | None = None


class DeviceScheduler:
    """One per service instance (AnalyzerCore): owns the single device
    slot every engine dispatch runs under.  Thread-safe throughout; all
    waits ride one Condition."""

    #: rank of a preempted holder waiting to resume (between URGENT=0
    #: and INTERACTIVE=1)
    _RESUME_RANK = 0.5
    #: sliding window of recent grants feeding the deadline-miss ratio
    _MISS_WINDOW = 16

    def __init__(
        self,
        *,
        slice_budget_s: float = 1.0,
        freshness_slo_s: float = 60.0,
        aging_s: float = 30.0,
        shed_queue_depth: int = 8,
        brownout_after_s: float = 20.0,
        brownout_factor: float = 0.5,
        fast_path_enabled: bool = True,
        sensors=None,
        clock=time.monotonic,
        anomaly_sink=None,
    ):
        if slice_budget_s <= 0:
            raise ValueError(f"slice_budget_s must be > 0, got {slice_budget_s}")
        if not 0.0 < brownout_factor <= 1.0:
            raise ValueError(
                f"brownout_factor must be in (0, 1], got {brownout_factor}"
            )
        if shed_queue_depth < 1:
            raise ValueError(
                f"shed_queue_depth must be >= 1, got {shed_queue_depth}"
            )
        self.slice_budget_s = slice_budget_s
        self.freshness_slo_s = freshness_slo_s
        self.aging_s = aging_s
        self.shed_queue_depth = shed_queue_depth
        self.brownout_after_s = brownout_after_s
        self.brownout_factor = brownout_factor
        #: grant INTERACTIVE dispatches unsegmented when no other tenant
        #: is waiting (config fleet.scheduler.fast.path.enabled) — the
        #: streaming re-anneal's p99 path
        self.fast_path_enabled = fast_path_enabled
        self.sensors = sensors
        self.clock = clock
        #: anomaly callable (detector.AnomalyDetector.add_anomaly) the
        #: FLEET_OVERLOAD episode alert rides; the first facade built
        #: over the core claims it (service/facade.py)
        self.anomaly_sink = anomaly_sink
        #: SloRegistry (common/slo.py) fed one good/bad sample per URGENT
        #: grant — good when the queue-to-dispatch wait met the class
        #: deadline (one slice budget); claimed by the first facade over
        #: the core, exactly like the anomaly sink
        self.slo_registry = None
        self._cond = threading.Condition()
        self._waiting: list[_Ticket] = []
        self._holder: _Ticket | None = None
        self._seq = 0
        #: recent (granted) tickets' deadline-miss booleans
        self._recent_misses: deque[bool] = deque(maxlen=self._MISS_WINDOW)
        #: EWMA of grant->release hold walls (Retry-After estimation)
        self._hold_ewma_s: float | None = None
        #: overload episode state: an episode starts when overload is
        #: first observed and ends once the queue has drained below half
        #: the shed depth with no recent misses (hysteresis, so a queue
        #: hovering at the threshold is ONE episode, not a storm of them)
        self._episode_started: float | None = None
        self.stats = dict(
            sheds={c.label: 0 for c in WorkClass},
            deadline_misses={c.label: 0 for c in WorkClass},
            preemptions=0,
            overload_episodes=0,
            brownout_cycles=0,
            fast_path_grants=0,
            dispatches={c.label: 0 for c in WorkClass},
        )
        if sensors is not None:
            sensors.gauge("fleet.scheduler.queue-depth", self._queue_depth)
            sensors.gauge(
                "fleet.scheduler.brownout-active",
                lambda: 1.0 if self.brownout_active else 0.0,
            )

    # ------------------------------------------------------------ helpers

    def _queue_depth(self) -> int:
        with self._cond:
            return len(self._waiting)

    def deadline_for(
        self, work_class: WorkClass, *, freshness_slo_s: float | None = None
    ) -> float:
        """Relative deadline per class, derived from the (per-cluster)
        proposal-freshness SLO: BACKGROUND must land within the SLO,
        INTERACTIVE within a quarter of it (an operator is watching),
        URGENT within one slice budget (the preemption bound)."""
        slo = freshness_slo_s if freshness_slo_s is not None else self.freshness_slo_s
        if work_class is WorkClass.URGENT:
            return self.slice_budget_s
        if work_class is WorkClass.INTERACTIVE:
            return max(self.slice_budget_s, slo / 4.0)
        return max(self.slice_budget_s, slo)

    def _rank(self, t: _Ticket, now: float):
        if t.resuming:
            cls = self._RESUME_RANK
        elif (
            t.work_class is WorkClass.BACKGROUND
            and now - t.enqueued >= self.aging_s
        ):
            # aged background ranks WITH interactive: its older deadline
            # then wins the EDF tiebreak, so sustained interactive load
            # can delay but never starve it
            cls = float(WorkClass.INTERACTIVE)
        else:
            cls = float(t.work_class)
        return (cls, t.deadline, t.seq)

    def _grant_next_locked(self, now: float) -> None:
        if self._holder is not None or not self._waiting:
            return
        best = min(self._waiting, key=lambda t: self._rank(t, now))
        self._waiting.remove(best)
        best.granted = True
        self._holder = best
        self._cond.notify_all()

    # ---------------------------------------------------------- overload

    def _miss_ratio_locked(self) -> float:
        if len(self._recent_misses) < self._MISS_WINDOW // 2:
            return 0.0
        return sum(self._recent_misses) / len(self._recent_misses)

    def _overloaded_locked(self, now: float) -> bool:
        raw = (
            len(self._waiting) >= self.shed_queue_depth
            or self._miss_ratio_locked() >= 0.5
        )
        if raw:
            if self._episode_started is None:
                self._episode_started = now
                self.stats["overload_episodes"] += 1
                if self.sensors is not None:
                    self.sensors.counter(
                        "fleet.scheduler.overload-episodes"
                    ).inc()
                self._fire_overload_anomaly(now)
            return True
        # hysteresis: the episode ends only once the queue genuinely
        # drained, not on one lucky sample at the threshold
        if (
            self._episode_started is not None
            and len(self._waiting) <= self.shed_queue_depth // 2
            and self._miss_ratio_locked() < 0.5
        ):
            self._episode_started = None
        return self._episode_started is not None

    def _fire_overload_anomaly(self, now: float) -> None:
        """FLEET_OVERLOAD, exactly once per overload episode (alert-only
        — the fix IS this scheduler's shed/brownout ladder; operators
        hear that it engaged)."""
        sink = self.anomaly_sink
        if sink is None:
            return
        try:
            from cruise_control_tpu.detector.anomalies import FleetOverload

            sink(FleetOverload(
                queue_depth=len(self._waiting),
                deadline_miss_ratio=round(self._miss_ratio_locked(), 3),
                episode=self.stats["overload_episodes"],
            ))
        except Exception:  # noqa: BLE001 — alerting must not block scheduling
            log.warning("FLEET_OVERLOAD anomaly delivery failed", exc_info=True)

    @property
    def brownout_active(self) -> bool:
        with self._cond:
            started = self._episode_started
            return (
                started is not None
                and self.clock() - started >= self.brownout_after_s
            )

    def brownout_config(self, cfg):
        """The browned-out twin of an OptimizerConfig: candidate and
        restart width scaled by `fleet.scheduler.brownout.candidate.factor`
        (floored so the engine keeps a working candidate split).  ONE
        quantized step per base config — the reduced config is a stable
        engine-cache key, so brownout costs at most one extra compiled
        program per bucket, not a compile per cycle."""
        f = self.brownout_factor
        self.stats["brownout_cycles"] += 1
        if self.sensors is not None:
            self.sensors.counter("fleet.scheduler.brownout-cycles").inc()
        return dataclasses.replace(
            cfg,
            num_candidates=max(64, int(cfg.num_candidates * f)),
            leadership_candidates=max(8, int(cfg.leadership_candidates * f)),
            swap_candidates=max(0, int(cfg.swap_candidates * f)),
        )

    # --------------------------------------------------------- admission

    def retry_after_s(self, *, default_s: float = 5.0) -> float:
        """Estimated time until the queue has room: depth x the recent
        mean hold wall; the config default when nothing has run yet."""
        with self._cond:
            depth = len(self._waiting) + (1 if self._holder is not None else 0)
            hold = self._hold_ewma_s
        if hold is None:
            return max(1.0, default_s)
        return float(min(300.0, max(1.0, depth * hold)))

    def _count_background_shed_locked(self) -> None:
        """ONE accounting site for background sheds (run()'s overload
        branch and voluntary shed_background callers): the stat and the
        sensor must never diverge."""
        self.stats["sheds"][WorkClass.BACKGROUND.label] += 1
        if self.sensors is not None:
            self.sensors.counter("fleet.scheduler.shed-total.background").inc()

    def should_shed_background(self) -> bool:
        """Whether a BACKGROUND submission made now would shed — the
        cheap pre-check callers with an expensive PRELUDE (the precompute
        loop's full model build) use to skip the work the dispatch would
        throw away.  Observing overload here starts the episode exactly
        like a real submission would."""
        with self._cond:
            now = self.clock()
            return self._overloaded_locked(now) and not self._brownout_locked(now)

    def shed_background(self, *, op: str = "") -> None:
        """Count one voluntarily shed BACKGROUND cycle (a caller that
        decided to skip work under overload/brownout — e.g. speculative
        prewarm, which must never add pressure during an episode).  Sheds
        are never silent: every skipped cycle lands in
        `fleet.scheduler.shed-total.background`."""
        with self._cond:
            self._count_background_shed_locked()
        log.debug("background dispatch %s shed", op or "?")

    def admit_interactive(
        self, *, cluster_id: str = "", default_retry_after_s: float = 5.0
    ) -> None:
        """The INTERACTIVE rung of the shed ladder, checked at REST
        admission BEFORE a user task is created: only SEVERE overload
        (queue at twice the background-shed depth) rejects, and the 429
        carries a drain-rate Retry-After.  URGENT work never passes
        through here — it can never be shed."""
        with self._cond:
            severe = len(self._waiting) >= 2 * self.shed_queue_depth
            if severe:
                self.stats["sheds"][WorkClass.INTERACTIVE.label] += 1
                if self.sensors is not None:
                    self.sensors.counter(
                        "fleet.scheduler.shed-total.interactive"
                    ).inc()
        if severe:
            ra = self.retry_after_s(default_s=default_retry_after_s)
            who = f" for cluster {cluster_id!r}" if cluster_id else ""
            raise SchedulerOverloadError(
                f"device scheduler overloaded ({self._queue_depth()} dispatches "
                f"queued); new work{who} rejected, retry in {ra:.0f}s",
                retry_after_s=ra,
            )

    # ------------------------------------------------------------- run

    def run(
        self,
        work_class: WorkClass,
        fn,
        *,
        cluster_id: str = "",
        op: str = "",
        freshness_slo_s: float | None = None,
        preemptible: bool | None = None,
    ):
        """Execute fn() holding the device slot, honoring class priority,
        deadlines, aging, preemption and the shed ladder.

        Runs INLINE on the caller's thread (the scheduler arbitrates, it
        does not own worker threads — a supervised dispatch still rides
        the DeviceSupervisor's bounded worker underneath).  Reentrant: a
        call made while this context already holds the slot executes
        immediately under the outer grant.  BACKGROUND submissions raise
        BackgroundShedError under overload (unless brownout is active, in
        which case they run — browned out by the caller via
        `brownout_config`).  Non-urgent grants execute under a
        SegmentContext so the engine's fused anneal runs preemptibly."""
        if _HELD.get() is not None:
            return fn()
        now = self.clock()
        with self._cond:
            overloaded = self._overloaded_locked(now)
            if (
                work_class is WorkClass.BACKGROUND
                and overloaded
                and not self._brownout_locked(now)
            ):
                self._count_background_shed_locked()
                raise BackgroundShedError(
                    f"background dispatch {op or '?'} shed under overload "
                    f"(queue depth {len(self._waiting)})"
                )
            ticket = _Ticket(
                work_class=work_class,
                cluster_id=cluster_id,
                op=op,
                enqueued=now,
                deadline=now + self.deadline_for(
                    work_class, freshness_slo_s=freshness_slo_s
                ),
                seq=self._seq,
            )
            self._seq += 1
            self._waiting.append(ticket)
            self._grant_next_locked(now)
            while not ticket.granted:
                self._cond.wait(0.05)
                self._grant_next_locked(self.clock())
            granted_at = self.clock()
            wait = max(0.0, granted_at - ticket.enqueued)
            missed = granted_at > ticket.deadline
            self._recent_misses.append(missed)
            self.stats["dispatches"][work_class.label] += 1
            if missed:
                self.stats["deadline_misses"][work_class.label] += 1
            # fast-path eligibility is decided UNDER the lock: granted
            # with nobody else queued means segmentation would buy no
            # responsiveness — there is no one to preempt for
            alone = not self._waiting
        cls = work_class.label
        if self.sensors is not None:
            self.sensors.timer(f"fleet.scheduler.wait-timer.{cls}").update(wait)
            if missed:
                self.sensors.counter(
                    f"fleet.scheduler.deadline-misses.{cls}"
                ).inc()
        if work_class is WorkClass.URGENT and self.slo_registry is not None:
            # the urgent queue-wait SLO: one sample per grant, good when
            # the wait landed inside the class deadline (one slice budget
            # — the preemption bound the scheduler promises)
            self.slo_registry.record("urgent-queue-wait", not missed)
        # black-box instant: the grant's class/wait/deadline verdict land
        # in the durable spool, and the context stamps them onto every
        # device record this grant dispatches (common/blackbox.py)
        if _BLACKBOX.enabled:
            _BLACKBOX.event(
                "sched-grant", work_class=cls, op=op, cluster=cluster_id,
                queue_wait_s=round(wait, 4), deadline_missed=missed,
            )
        if preemptible is None:
            preemptible = work_class is not WorkClass.URGENT
            if (
                preemptible
                and self.fast_path_enabled
                and work_class is WorkClass.INTERACTIVE
                and alone
            ):
                # fast-path grant: an INTERACTIVE dispatch granted with an
                # empty queue runs UNSEGMENTED — segmented mode's per-slice
                # blocking syncs exist to bound URGENT wait, and with no
                # other tenant waiting they only cut into the streaming
                # re-anneal's p99.  Callers that pass an explicit
                # `preemptible` keep exactly what they asked for.
                preemptible = False
                self.stats["fast_path_grants"] += 1
                if self.sensors is not None:
                    self.sensors.counter(
                        "fleet.scheduler.fast-path-grants"
                    ).inc()
        token = _HELD.set(ticket)
        try:
            with blackbox_context(
                work_class=cls, queue_wait_s=round(wait, 4)
            ):
                if preemptible and self.slice_budget_s > 0:
                    from cruise_control_tpu.analyzer.engine import (
                        SegmentContext,
                        segmented_execution,
                    )
                    from cruise_control_tpu.common.device_watchdog import (
                        pause_clock_scope,
                    )

                    ctx = SegmentContext(
                        self.slice_budget_s,
                        checkpoint=lambda t=ticket: self._checkpoint(t),
                    )
                    # the supervisor's hang budget must exclude time WE
                    # pause this dispatch at preemption checkpoints —
                    # including a pause still in progress
                    with pause_clock_scope(
                        lambda t=ticket: self._ticket_pause_s(t)
                    ):
                        with segmented_execution(ctx):
                            return fn()
                return fn()
        finally:
            _HELD.reset(token)
            self._release(ticket, granted_at)

    def _brownout_locked(self, now: float) -> bool:
        started = self._episode_started
        return started is not None and now - started >= self.brownout_after_s

    def _release(self, ticket: _Ticket, granted_at: float) -> None:
        """End of a grant: run() exited (fn returned or RAISED).  The
        ticket may be the live holder, or — when the DeviceSupervisor
        abandoned a timed-out dispatch whose worker sits paused in a
        checkpoint — still queued at resume rank: it must be pulled from
        the queue and cancelled, or the zombie worker would later
        re-acquire the slot with nobody left to release it and wedge the
        scheduler forever (every later run() would wait on a holder that
        never clears)."""
        with self._cond:
            # hold wall EXCLUDES checkpoint pauses: the paused time is
            # the preempting urgent grant's hold, already recorded on its
            # own ticket — double-counting it would inflate the drain
            # estimate behind every Retry-After
            hold = max(0.0, self.clock() - granted_at - ticket.paused_s)
            self._hold_ewma_s = (
                hold if self._hold_ewma_s is None
                else 0.7 * self._hold_ewma_s + 0.3 * hold
            )
            ticket.cancelled = True
            if self._holder is ticket:
                self._holder = None
            elif ticket in self._waiting:
                self._waiting.remove(ticket)
            self._cond.notify_all()
            self._grant_next_locked(self.clock())

    def _checkpoint(self, ticket: _Ticket) -> None:
        """Between-slices preemption point (engine SegmentContext): when
        an URGENT ticket is waiting, the holder yields the slot HERE —
        the device is idle at a slice boundary — and blocks until
        re-granted at resume rank.  An urgent request therefore waits at
        most one slice of background wall, never a whole anneal.

        The pause wall accrues on `ticket.paused_s` so the supervisor's
        hang budget can exclude it (`current_pause_s`), and a ticket
        cancelled while paused (its run() already exited) stops waiting
        — the abandoned worker finishes unslotted, exactly like any
        other supervisor-abandoned dispatch."""
        with self._cond:
            if self._holder is not ticket:
                return  # not the active holder (nested/stale checkpoint)
            if not any(
                t.work_class is WorkClass.URGENT for t in self._waiting
            ):
                return
            self.stats["preemptions"] += 1
            if self.sensors is not None:
                self.sensors.counter("fleet.scheduler.preemptions").inc()
            self._holder = None
            ticket.granted = False
            ticket.resuming = True
            self._waiting.append(ticket)
            self._grant_next_locked(self.clock())
            ticket.pause_started = self.clock()
            while not ticket.granted and not ticket.cancelled:
                self._cond.wait(0.05)
                self._grant_next_locked(self.clock())
            ticket.paused_s += max(0.0, self.clock() - ticket.pause_started)
            ticket.pause_started = None

    def _ticket_pause_s(self, ticket: _Ticket) -> float:
        """Scheduler-imposed pause of one grant, INCLUDING a pause
        currently in progress — the DeviceSupervisor's hang budget reads
        this live (cond.wait releases the lock, so the read never blocks
        behind a paused checkpoint)."""
        with self._cond:
            extra = (
                max(0.0, self.clock() - ticket.pause_started)
                if ticket.pause_started is not None
                else 0.0
            )
            return ticket.paused_s + extra

    # ------------------------------------------------------------- state

    def state_json(self) -> dict:
        """The `/fleet` scheduler block."""
        with self._cond:
            waiting = list(self._waiting)
            holder = self._holder
            episode = self._episode_started
            now = self.clock()
            out = {
                "enabled": True,
                "queueDepth": len(waiting),
                "queuedByClass": {
                    c.label: sum(1 for t in waiting if t.work_class is c)
                    for c in WorkClass
                },
                "holder": (
                    {"class": holder.work_class.label, "op": holder.op,
                     "cluster": holder.cluster_id}
                    if holder is not None else None
                ),
                "sliceBudgetS": self.slice_budget_s,
                "freshnessSloS": self.freshness_slo_s,
                "overloaded": episode is not None,
                "brownoutActive": (
                    episode is not None
                    and now - episode >= self.brownout_after_s
                ),
                "shedTotal": dict(self.stats["sheds"]),
                "deadlineMisses": dict(self.stats["deadline_misses"]),
                "dispatches": dict(self.stats["dispatches"]),
                "preemptions": self.stats["preemptions"],
                "overloadEpisodes": self.stats["overload_episodes"],
                "brownoutCycles": self.stats["brownout_cycles"],
                "fastPathGrants": self.stats["fast_path_grants"],
            }
        if self.sensors is not None:
            out["waitSeconds"] = {
                c.label: self.sensors.timer(
                    f"fleet.scheduler.wait-timer.{c.label}"
                ).quantiles()
                for c in WorkClass
            }
        return out
