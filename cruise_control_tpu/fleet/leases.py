"""Lease-sharded cluster ownership — the fleet HA coordination layer.

M tpu-cruise instances jointly serve one `fleet.clusters` set; a cluster
is only ever EXECUTED AGAINST by the instance currently holding its
lease.  The reference Cruise Control's core promise is that the
rebalancer never makes the cluster worse — two executors racing the same
Kafka cluster after a network partition or a stalled process breaks
exactly that, so every mutation is fenced by the lease's epoch.

Three pieces:

  * `FileLeaseStore` — the pluggable `LeaseStore` contract's file-backed
    implementation, living in the executor journal directory (the one
    piece of shared durable state a fleet already has).  Same primitives
    as the prewarm manifest merge (PR 10): an OS file lock (`flock`)
    around every read-modify-write, atomic `os.replace` publication.
    Each lease carries a monotonically increasing `epoch` — the fencing
    token — and every grant/renewal/release lands in an append-only
    audit trail (`audit.jsonl`) from which the single-holder invariant
    is mechanically checkable (`single_holder_violations`).
  * `Fence` — the per-(cluster, instance) validity token the execution
    path consults.  `check()` is TIME-BASED, not event-based: even when
    the renewal thread itself is the thing that stalled (the zombie
    scenario), a late journal append or admin mutation hits
    `now > deadline - skew_slack` and raises `FencedError` — the fence
    steps down strictly BEFORE the store would grant a takeover at
    `deadline + skew_slack`, so bounded clock skew cannot create two
    writers.
  * `LeaseManager` — one per instance: acquisition, renewal heartbeats
    on a background thread, expiry-based takeover of unowned clusters,
    and loss detection, all on an injected clock (`testing/faults.py
    clock_skew` swaps it per instance).

Safety argument (why at most one holder per cluster at any instant):
the store only re-grants a cluster once `now > deadline + skew_slack`
on the ACQUIRER's clock; the holder's fence self-revokes once
`now > deadline - skew_slack` on the HOLDER's clock.  With per-instance
clock error bounded by `skew_slack/2` each (config
`fleet.ha.skew.slack.s`), the fence is dead before the takeover is
possible, and the epoch bump fences any write that raced the handover.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time

log = logging.getLogger(__name__)


class FencedError(RuntimeError):
    """A journal append or cluster mutation carried a stale (or absent)
    lease epoch: this instance no longer owns the cluster.  The executor
    aborts its batch cleanly — the try/finally throttle guard makes the
    abort leak-free, and the NEW holder's restart reconciliation adopts
    whatever was in flight."""


@dataclasses.dataclass(frozen=True)
class Lease:
    """One cluster's ownership grant.  `epoch` is the fencing token: it
    increases monotonically across every grant, so any write stamped
    with an older epoch is provably from a deposed holder.  `deadline`
    is in the granting instance's clock (seconds); readers compare it
    against their own clock plus/minus the configured skew slack."""

    cluster_id: str
    holder_id: str
    epoch: int
    deadline: float
    #: this grant displaced another holder's expired, unreleased lease
    #: (accounting only; set by the store, which decides under its lock)
    takeover: bool = False


class LeaseStore:
    """Pluggable lease persistence contract.  Implementations must make
    `acquire` exclusive (no grant while another holder's lease is live
    within skew slack) and `epoch` monotonic per cluster."""

    def acquire(self, cluster_id: str, holder_id: str, ttl_s: float) -> Lease | None:
        raise NotImplementedError

    def renew(self, lease: Lease, ttl_s: float) -> Lease | None:
        raise NotImplementedError

    def release(self, lease: Lease) -> None:
        raise NotImplementedError

    def read(self, cluster_id: str) -> Lease | None:
        raise NotImplementedError


class FileLeaseStore(LeaseStore):
    """Lease files in a shared directory (the executor journal dir):
    one `<cluster_id>.lease.json` per cluster, every read-modify-write
    under ONE `flock`'d lock file, every publication an atomic
    `os.replace` — the exact primitives the prewarm manifest merge
    already relies on, so the durability story is the journal dir's.

    The audit trail (`audit.jsonl`, appended under the same lock) records
    every grant with the displaced lease's deadline, which makes the
    single-holder invariant checkable after the fact without trusting
    the instances themselves (`single_holder_violations`).
    """

    def __init__(self, directory: str, *, skew_slack_s: float = 2.0, clock=None):
        self.dir = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.dir, exist_ok=True)
        self.skew_slack_s = float(skew_slack_s)
        #: injected clock (seconds float) — testing/faults.py clock_skew
        #: swaps this attribute per instance
        self.clock = clock or time.time
        self._lock_path = os.path.join(self.dir, ".lock")
        self._audit_path = os.path.join(self.dir, "audit.jsonl")
        self._thread_lock = threading.Lock()
        #: once-per-store warning state for a failed/unavailable flock
        self._flock_warn = {"warned": False}

    # ------------------------------------------------------------ files

    def _lease_path(self, cluster_id: str) -> str:
        return os.path.join(self.dir, f"{cluster_id}.lease.json")

    def _read_raw(self, cluster_id: str) -> dict | None:
        try:
            with open(self._lease_path(cluster_id), encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            # missing file = never leased; corrupt cannot happen from our
            # own writes (atomic replace) — treat as absent
            return None
        return d if isinstance(d, dict) and "epoch" in d else None

    def _write_raw(self, cluster_id: str, d: dict) -> None:
        path = self._lease_path(cluster_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(d, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    #: rotate the audit trail past this size (renewal heartbeats append
    #: forever; one rotated generation is kept, so the invariant checker
    #: still sees a deep recent history without unbounded growth)
    AUDIT_MAX_BYTES = 4 * 1024 * 1024

    def _audit(self, event: str, cluster_id: str, d: dict) -> None:
        rec = dict(d, event=event, cluster=cluster_id, t=self.clock())
        with open(self._audit_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
            size = f.tell()
        if size > self.AUDIT_MAX_BYTES:
            # runs under the store lock (every _audit caller holds it)
            try:
                os.replace(self._audit_path, self._audit_path + ".1")
            except OSError:
                pass

    def _locked(self):
        """Cross-process + cross-thread exclusion around one
        read-modify-write (flock where available, like the prewarm
        manifest merge; a platform without flock degrades to
        thread-level exclusion — logged LOUDLY once, because on the
        shared mount HA targets that degradation means cross-process
        exclusion is gone)."""
        return _StoreLock(self._lock_path, self._thread_lock,
                          self._flock_warn)

    # --------------------------------------------------------- contract

    def acquire(self, cluster_id: str, holder_id: str, ttl_s: float) -> Lease | None:
        with self._locked():
            now = self.clock()
            cur = self._read_raw(cluster_id)
            live = (
                cur is not None
                and not cur.get("released")
                and now <= cur["deadline"] + self.skew_slack_s
            )
            if live and cur["holder"] != holder_id:
                return None
            # a missing/corrupt lease file must not reset the fencing
            # token: fall back to the audit trail's highest epoch
            epoch = (cur["epoch"] if cur else self._epoch_floor(cluster_id)) + 1
            takeover = bool(cur and not cur.get("released")
                            and cur["holder"] != holder_id)
            d = {"holder": holder_id, "epoch": epoch, "deadline": now + ttl_s}
            self._write_raw(cluster_id, d)
            self._audit(
                "acquired", cluster_id,
                dict(
                    d,
                    takeover=takeover,
                    slack=self.skew_slack_s,
                    prev_holder=cur["holder"] if cur else None,
                    prev_deadline=cur["deadline"] if cur else None,
                    prev_released=bool(cur.get("released")) if cur else True,
                ),
            )
            return Lease(cluster_id, holder_id, epoch, d["deadline"],
                         takeover=takeover)

    def renew(self, lease: Lease, ttl_s: float) -> Lease | None:
        with self._locked():
            cur = self._read_raw(lease.cluster_id)
            if (
                cur is None
                or cur.get("released")
                or cur["holder"] != lease.holder_id
                or cur["epoch"] != lease.epoch
            ):
                return None  # fenced: the cluster moved on without us
            d = {
                "holder": lease.holder_id,
                "epoch": lease.epoch,
                "deadline": self.clock() + ttl_s,
            }
            self._write_raw(lease.cluster_id, d)
            self._audit("renewed", lease.cluster_id, d)
            return Lease(lease.cluster_id, lease.holder_id, lease.epoch,
                         d["deadline"])

    def release(self, lease: Lease) -> None:
        with self._locked():
            cur = self._read_raw(lease.cluster_id)
            if (
                cur is None
                or cur["holder"] != lease.holder_id
                or cur["epoch"] != lease.epoch
            ):
                return  # already superseded; nothing of ours to release
            d = dict(cur, released=True)
            self._write_raw(lease.cluster_id, d)
            self._audit("released", lease.cluster_id, d)

    def read(self, cluster_id: str) -> Lease | None:
        cur = self._read_raw(cluster_id)
        if cur is None or cur.get("released"):
            return None
        return Lease(cluster_id, cur["holder"], cur["epoch"], cur["deadline"])

    # ------------------------------------------------------------ audit

    def audit_events(self) -> list[dict]:
        """Decode the audit trail — the rotated generation first, then
        the live file (torn tails tolerated, like the journal)."""
        events: list[dict] = []
        for path in (self._audit_path + ".1", self._audit_path):
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            events.append(json.loads(line))
                        except ValueError:
                            break
            except OSError:
                continue
        return events

    def _epoch_floor(self, cluster_id: str) -> int:
        """Highest epoch the audit trail remembers for a cluster — the
        fencing floor when the lease file itself is missing/corrupt.  A
        lost lease file must not reset epochs below records already
        stamped into execution journals (replay's high-water filter
        would then drop the NEW holder's legitimate writes as zombie
        writes)."""
        floor = 0
        for e in self.audit_events():
            if e.get("cluster") == cluster_id and isinstance(e.get("epoch"), int):
                floor = max(floor, e["epoch"])
        return floor


class _StoreLock:
    """flock(lock file) + thread lock; releases both on exit.  A failed
    flock (ENOLCK on an NFS mount without lockd, unopenable lock file)
    degrades to thread-level exclusion and WARNS once per store: losing
    cross-process exclusion silently would be losing the single-holder
    guarantee silently."""

    def __init__(self, path: str, thread_lock: threading.Lock, warn_state: dict):
        self.path = path
        self.thread_lock = thread_lock
        self.warn_state = warn_state
        self._f = None

    def _warn_once(self, why: str):
        if not self.warn_state.get("warned"):
            self.warn_state["warned"] = True
            log.warning(
                "lease store %s: cross-process file lock unavailable (%s) — "
                "falling back to thread-level exclusion; multiple instances "
                "sharing this directory are NOT mutually excluded during "
                "lease read-modify-writes", self.path, why,
            )

    def __enter__(self):
        self.thread_lock.acquire()
        try:
            self._f = open(self.path, "a+")  # noqa: SIM115 — held for the flock
            try:
                import fcntl

                fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
            except Exception as e:  # noqa: BLE001 — no flock: thread-level only
                self._warn_once(repr(e))
        except OSError as e:
            self._f = None
            self._warn_once(repr(e))
        return self

    def __exit__(self, *exc):
        if self._f is not None:
            self._f.close()  # closing releases the flock
            self._f = None
        self.thread_lock.release()
        return False


def single_holder_violations(events: list[dict], *, skew_slack_s: float = 0.0) -> list[str]:
    """Check the at-most-one-holder invariant against a store's audit
    trail: per cluster, every grant that displaces a DIFFERENT unreleased
    holder must happen strictly after that holder's last granted deadline
    PLUS the skew slack (the zone where the two-sided safety argument
    still allows the old fence to be live), and epochs must be strictly
    increasing.  The slack comes from each acquire event's recorded
    `slack` (the store stamps its configured value); `skew_slack_s` is
    the fallback for trails written before the stamp existed.  Returns
    human-readable violations (empty = invariant held)."""
    out: list[str] = []
    last_epoch: dict[str, int] = {}
    for e in events:
        cid = e.get("cluster")
        if e.get("event") == "acquired":
            if cid in last_epoch and e["epoch"] <= last_epoch[cid]:
                out.append(
                    f"{cid}: epoch {e['epoch']} not above {last_epoch[cid]}"
                )
            slack = e.get("slack", skew_slack_s)
            if (
                e.get("takeover")
                and e.get("prev_deadline") is not None
                and e["t"] <= e["prev_deadline"] + slack
            ):
                out.append(
                    f"{cid}: takeover by {e['holder']} at t={e['t']:.3f} while "
                    f"{e.get('prev_holder')}'s lease ran to "
                    f"{e['prev_deadline']:.3f} (+{slack:.3f} slack)"
                )
        if "epoch" in e and cid is not None:
            last_epoch[cid] = max(last_epoch.get(cid, 0), e["epoch"])
    return out


class Fence:
    """Per-(cluster, instance) fencing token the execution path consults.

    `check()` gates every journal append and admin mutation; it is valid
    only while (a) a lease epoch is granted AND (b) the instance clock
    has not run past `deadline - skew_slack` — so a stalled renewal
    thread revokes the fence by TIME, not by code that may never run."""

    def __init__(self, cluster_id: str, manager: "LeaseManager"):
        self.cluster_id = cluster_id
        self.manager = manager
        self._lock = threading.Lock()
        self._epoch: int | None = None
        self._valid_until = float("-inf")

    @property
    def epoch(self) -> int | None:
        with self._lock:
            return self._epoch

    @property
    def held(self) -> bool:
        with self._lock:
            return (
                self._epoch is not None
                and self.manager.clock() <= self._valid_until
            )

    def check(self, op: str = "") -> int:
        """Raise FencedError unless this instance currently owns the
        cluster; returns the live epoch for stamping."""
        with self._lock:
            if self._epoch is None:
                raise FencedError(
                    f"{self.cluster_id}: no lease held"
                    + (f" (op={op})" if op else "")
                )
            if self.manager.clock() > self._valid_until:
                raise FencedError(
                    f"{self.cluster_id}: lease epoch {self._epoch} expired "
                    f"past skew slack" + (f" (op={op})" if op else "")
                )
            return self._epoch

    def _grant(self, epoch: int, deadline: float) -> None:
        with self._lock:
            self._epoch = epoch
            self._valid_until = deadline - self.manager.skew_slack_s

    def _revoke(self) -> None:
        with self._lock:
            self._epoch = None
            self._valid_until = float("-inf")


class LeaseManager:
    """One per service instance: owns this instance's view of every
    cluster's lease — acquisition, renewal heartbeats, expiry-based
    takeover, loss detection — and the fences the execution path checks.

    Callbacks (`on_acquired(cluster_id, lease, takeover)`,
    `on_lost(cluster_id, lease)`) run on the heartbeat thread AFTER the
    fence state has changed, so activation code runs fenced-in and
    step-down code runs fenced-out."""

    def __init__(
        self,
        store: LeaseStore,
        cluster_ids,
        *,
        holder_id: str,
        ttl_s: float = 30.0,
        renew_s: float = 10.0,
        skew_slack_s: float = 2.0,
        clock=None,
        sensors=None,
        on_acquired=None,
        on_lost=None,
    ):
        if skew_slack_s >= ttl_s / 2:
            raise ValueError(
                f"fleet.ha.skew.slack.s={skew_slack_s} must be below half "
                f"the ttl ({ttl_s}) — the fence window would be empty"
            )
        if renew_s >= ttl_s - skew_slack_s:
            # the fence self-revokes at deadline - slack: a heartbeat
            # slower than that window guarantees the RIGHTFUL holder's
            # fence expires between successful renewals, turning every
            # mid-batch append into a spurious fenced abort
            raise ValueError(
                f"fleet.ha.renew.s={renew_s} must be below "
                f"fleet.ha.lease.ttl.s - fleet.ha.skew.slack.s "
                f"({ttl_s} - {skew_slack_s}): the fence is only valid to "
                "deadline - slack, so renewals must land inside that window"
            )
        self.store = store
        self.holder_id = holder_id
        self.ttl_s = float(ttl_s)
        self.renew_s = float(renew_s)
        self.skew_slack_s = float(skew_slack_s)
        #: injected clock (seconds float) — clock_skew patches this
        self.clock = clock or time.time
        self.sensors = sensors
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.fences: dict[str, Fence] = {
            cid: Fence(cid, self) for cid in cluster_ids
        }
        self._leases: dict[str, Lease] = {}
        #: last peer holder observed per cluster ((holder_id, epoch)) —
        #: refreshed by the HEARTBEAT thread so the /fleet request path
        #: never blocks on the (possibly partitioned) store
        self._peer_view: dict[str, tuple[str, int]] = {}
        #: per-cluster re-acquisition cooldown deadlines (instance clock)
        #: set by relinquish() so a flapping activation backs off
        self._cooldown_until: dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if sensors is not None:
            sensors.gauge("fleet.ha.owned-clusters",
                          lambda: len(self.owned_clusters()))

    # ------------------------------------------------------------ state

    def fence(self, cluster_id: str) -> Fence:
        return self.fences[cluster_id]

    def lease(self, cluster_id: str) -> Lease | None:
        with self._lock:
            return self._leases.get(cluster_id)

    def owns(self, cluster_id: str) -> bool:
        return self.fences[cluster_id].held

    def owned_clusters(self) -> list[str]:
        return [cid for cid, f in self.fences.items() if f.held]

    def _count(self, name: str, n: int = 1) -> None:
        if self.sensors is not None:
            self.sensors.counter(name).inc(n)

    # -------------------------------------------------------- heartbeat

    def poll_once(self) -> None:
        """One heartbeat pass: renew held leases, attempt takeover of
        unowned clusters, detect losses.  Runs on the background thread;
        tests drive it directly with injected clocks."""
        for cid, fence in self.fences.items():
            with self._lock:
                lease = self._leases.get(cid)
            if lease is not None:
                self._renew_one(cid, fence, lease)
            else:
                self._acquire_one(cid, fence)

    def _renew_one(self, cid: str, fence: Fence, lease: Lease) -> None:
        if self._stop.is_set():
            return  # shutting down: stop() owns the lease's fate now
        try:
            renewed = self.store.renew(lease, self.ttl_s)
        except Exception:  # noqa: BLE001 — store partition: keep the lease
            # until the fence window closes; the next poll retries
            self._count("fleet.ha.renewal-failures")
            if self.clock() > lease.deadline - self.skew_slack_s:
                self._lose(cid, fence, lease)
            return
        if renewed is None:
            # the store moved on without us (takeover won the race) —
            # capture who took it for the request path's ownership view
            self._count("fleet.ha.renewal-failures")
            try:
                cur = self.store.read(cid)
                if cur is not None:
                    with self._lock:
                        self._peer_view[cid] = (cur.holder_id, cur.epoch)
            except Exception:  # noqa: BLE001 — view refresh is best-effort
                pass
            self._lose(cid, fence, lease)
            return
        if self._stop.is_set():
            # stop() raced us while we were blocked in the store (its
            # join timeout elapsed and it already revoked/released):
            # re-granting the fence here would resurrect a lease a peer
            # may hold by now — hand the renewal straight back instead
            try:
                self.store.release(renewed)
            except Exception:  # noqa: BLE001 — the TTL expires it anyway
                pass
            return
        self._count("fleet.ha.renewals")
        with self._lock:
            self._leases[cid] = renewed
        fence._grant(renewed.epoch, renewed.deadline)

    def _acquire_one(self, cid: str, fence: Fence) -> None:
        if self._stop.is_set():
            return  # shutting down: must not re-acquire a released lease
        with self._lock:
            cooldown = self._cooldown_until.get(cid, 0.0)
        if self.clock() < cooldown:
            return  # backing off after a failed activation (relinquish)
        try:
            lease = self.store.acquire(cid, self.holder_id, self.ttl_s)
        except Exception:  # noqa: BLE001 — store partition: retry next poll
            self._count("fleet.ha.renewal-failures")
            return
        if lease is None:
            # someone else's live lease: refresh the cached peer view the
            # request path (/fleet ownership) reads instead of the store
            try:
                cur = self.store.read(cid)
                if cur is not None:
                    with self._lock:
                        self._peer_view[cid] = (cur.holder_id, cur.epoch)
            except Exception:  # noqa: BLE001 — view refresh is best-effort
                pass
            return
        if self._stop.is_set():
            # stop() raced us while we were blocked in the store (its
            # 5s join timeout elapsed): hand the grant straight back so
            # a peer never waits out a TTL nobody is renewing
            try:
                self.store.release(lease)
            except Exception:  # noqa: BLE001 — the TTL expires it anyway
                pass
            return
        # the store decides takeover-ness under its own lock (a racing
        # pre-read here would misclassify a release-then-grant)
        takeover = lease.takeover
        with self._lock:
            self._leases[cid] = lease
        # fence BEFORE the callback: activation (journal reconciliation,
        # resume) runs its admin calls already fenced-in
        fence._grant(lease.epoch, lease.deadline)
        self._count("fleet.ha.acquired")
        if takeover:
            self._count("fleet.ha.takeovers")
        log.info(
            "lease acquired: cluster=%s holder=%s epoch=%d%s",
            cid, self.holder_id, lease.epoch,
            " (takeover)" if takeover else "",
        )
        if self.on_acquired is not None:
            try:
                self.on_acquired(cid, lease, takeover)
            except Exception:  # noqa: BLE001 — a failed activation must not
                # wedge the heartbeat for the other clusters
                log.warning("lease activation of %s failed", cid, exc_info=True)

    def _lose(self, cid: str, fence: Fence, lease: Lease) -> None:
        # revoke FIRST: by the time step-down code runs, any concurrent
        # append/mutation already raises FencedError
        fence._revoke()
        with self._lock:
            self._leases.pop(cid, None)
        self._count("fleet.ha.lost")
        log.warning(
            "lease LOST: cluster=%s holder=%s epoch=%d — stepping down to "
            "read-only degraded mode", cid, self.holder_id, lease.epoch,
        )
        if self.on_lost is not None:
            try:
                self.on_lost(cid, lease)
            except Exception:  # noqa: BLE001
                log.warning("lease step-down of %s failed", cid, exc_info=True)

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — heartbeat must keep beating
                    log.warning("lease heartbeat pass failed", exc_info=True)
                self._stop.wait(self.renew_s)

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"lease-heartbeat-{self.holder_id}"
        )
        self._thread.start()

    def stop(self, *, release: bool = True) -> None:
        """Graceful shutdown: stop heartbeats and (by default) release
        every held lease so a peer can take over immediately instead of
        waiting out the TTL."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        with self._lock:
            leases = dict(self._leases)
            self._leases.clear()
        for cid, lease in leases.items():
            self.fences[cid]._revoke()
            if release:
                try:
                    self.store.release(lease)
                except Exception:  # noqa: BLE001 — the TTL expires it anyway
                    pass

    def kill(self) -> None:
        """Test/bench seam: die like a crashed process — heartbeats stop,
        NOTHING is released (peers must wait out the TTL), and the local
        fences revoke (a dead process runs no more code; revoking models
        exactly that for in-process harnesses)."""
        self.stop(release=False)

    def relinquish(self, cluster_id: str, *, cooldown_s: float = 0.0) -> None:
        """Voluntarily give one cluster's lease back (fence revoked
        first): a failed activation hands the cluster to whoever's
        heartbeat wins it next — possibly a healthy peer — instead of
        squatting on a lease it cannot serve.  `cooldown_s` keeps THIS
        instance from instantly re-acquiring and re-failing (flap
        backoff); peers are unaffected."""
        fence = self.fences[cluster_id]
        fence._revoke()
        with self._lock:
            lease = self._leases.pop(cluster_id, None)
            if cooldown_s > 0:
                self._cooldown_until[cluster_id] = self.clock() + cooldown_s
        if lease is not None:
            try:
                self.store.release(lease)
            except Exception:  # noqa: BLE001 — the TTL expires it anyway
                pass

    # ------------------------------------------------------------ views

    def ownership_json(self, cluster_id: str) -> dict:
        """Ownership view for /fleet.  Never touches the store: the
        request path must keep serving during a store partition (the
        degraded read-only promise), so the non-owned holder info comes
        from the heartbeat-refreshed peer view."""
        fence = self.fences[cluster_id]
        out: dict = {"owned": fence.held, "instanceId": self.holder_id}
        lease = self.lease(cluster_id)
        if fence.held and lease is not None:
            out["holderId"] = lease.holder_id
            out["epoch"] = lease.epoch
            out["deadlineInS"] = round(lease.deadline - self.clock(), 3)
        else:
            with self._lock:
                peer = self._peer_view.get(cluster_id)
            if peer is not None:
                out["holderId"], out["epoch"] = peer
        return out

    def state_json(self) -> dict:
        return {
            "instanceId": self.holder_id,
            "ttlS": self.ttl_s,
            "renewS": self.renew_s,
            "skewSlackS": self.skew_slack_s,
            "ownedClusters": self.owned_clusters(),
            "clusters": {
                cid: self.ownership_json(cid) for cid in self.fences
            },
        }
