"""Fleet controller — ONE tpu-cruise instance over N Kafka clusters."""

from cruise_control_tpu.fleet.manager import ClusterContext, FleetManager

__all__ = ["ClusterContext", "FleetManager"]
