"""Fleet controller — ONE tpu-cruise instance over N Kafka clusters."""

from cruise_control_tpu.fleet.leases import (
    FencedError,
    FileLeaseStore,
    Lease,
    LeaseManager,
    LeaseStore,
)
from cruise_control_tpu.fleet.manager import ClusterContext, FleetManager

__all__ = [
    "ClusterContext",
    "FencedError",
    "FileLeaseStore",
    "FleetManager",
    "Lease",
    "LeaseManager",
    "LeaseStore",
]
