"""Fleet controller: one service instance monitoring, rebalancing and
rightsizing N Kafka clusters.

The economics (ROADMAP item 4): the EXPENSIVE resources — the TPU, the
compiled engines, the DeviceSupervisor's breaker — are shared through one
`service.facade.AnalyzerCore`; the CHEAP ones — load monitors, executors
with their durable journals, detectors, sample streams — multiply per
cluster.  Shape buckets (PR 2) make the sharing real: clusters whose
bucketed model shapes coincide rebind the SAME compiled engine
(`analyzer.engine-cache-*` counters on the core registry prove it), and
same-bucket clusters are scored in one batched device dispatch through
the ScenarioEvaluator (`score_clusters`).

Ownership map:

  shared (AnalyzerCore, one per instance)    per cluster (ClusterContext)
  ----------------------------------------  ---------------------------------
  GoalChain + BalancingConstraint            LoadMonitor + aggregators
  GoalOptimizer (compiled-engine LRU)        Executor (+ journal under
  DeviceSupervisor (one circuit breaker)       <executor.journal.dir>/<id>/)
  ScenarioEvaluator / Rightsizer             AnomalyDetector + notifier
  Tracer store (per-cluster component        proposal cache + precompute loop
    namespaces ride Tracer.scoped)           SensorRegistry({cluster: <id>})

Admission control: the REST layer enforces `fleet.tenant.max.pending.tasks`
per cluster on the async user-task purgatory (429 + the cluster's
`fleet.tenant-rejections` counter on breach), so one noisy cluster cannot
starve the other clusters' proposal refreshes out of the shared pool.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)


def shared_core_rollup(core, *, tenant_max_pending: int = 0) -> dict:
    """The `shared` block of the GET /fleet payload — one builder for the
    fleet and the single-cluster synthetic rollup (service/server.py), so
    the two deployments can't drift apart field by field."""
    opt = core.optimizer
    out: dict = {
        "compiledEngines": opt.cache_size,
        "engineCacheHits": opt.engine_cache_hits,
        "engineCacheMisses": opt.engine_cache_misses,
        "degraded": core.supervisor is not None and core.supervisor.is_degraded,
        "tenantMaxPendingTasks": tenant_max_pending,
    }
    if core.supervisor is not None:
        out["supervisor"] = core.supervisor.state_json()
    return out


class ClusterContext:
    """Everything ONE cluster owns inside a fleet: its facade (which holds
    the monitor, executor, journal, detector) plus the sampling stack that
    feeds it."""

    def __init__(self, cluster_id: str, cc, *, fetcher=None, task_runner=None):
        self.cluster_id = cluster_id
        self.cc = cc
        self.fetcher = fetcher
        self.task_runner = task_runner

    def rollup(self) -> dict:
        """Cheap per-cluster state summary for the GET /fleet rollup (no
        model build, no device work)."""
        cc = self.cc
        out = {
            "proposalReady": cc._valid_cache() is not None,
            "hasOngoingExecution": cc.executor.has_ongoing_execution,
            "executorState": cc.executor.executor_state().get("state"),
            "modelGeneration": str(cc.monitor.model_generation()),
            "selfHealingBusy": cc.actions.is_busy,
        }
        if cc.controller is not None:
            # per-cluster streaming controller (each facade builds its own
            # from its cluster config; the fleet start_up fans them out)
            out["controller"] = {
                "running": cc.controller.running,
                "windowRolls": cc.controller.state_json()["windowRolls"],
            }
        recovery = cc.executor.recovery_info()
        if recovery is not None:
            out["recovered"] = True
        return out


class FleetManager:
    """Owns the cluster contexts and the shared core; the REST layer
    resolves `cluster=` through it and serves GET /fleet from it."""

    def __init__(self, core, contexts: dict[str, ClusterContext], *,
                 sensors, config):
        """core: the shared service.facade.AnalyzerCore every context's
        facade was built over; sensors: the fleet-level (unlabeled)
        registry — normally the same one the core registers into."""
        self.core = core
        self.contexts = dict(contexts)
        self.sensors = sensors
        self.config = config
        self.tenant_max_pending = config.get("fleet.tenant.max.pending.tasks")
        sensors.gauge("fleet.clusters", lambda: len(self.contexts))

    # ------------------------------------------------------------- lookup

    def cluster_ids(self) -> list[str]:
        return list(self.contexts)

    def cluster(self, cluster_id: str) -> ClusterContext:
        ctx = self.contexts.get(cluster_id)
        if ctx is None:
            raise KeyError(
                f"unknown cluster {cluster_id!r}; clusters: {self.cluster_ids()}"
            )
        return ctx

    def facade(self, cluster_id: str):
        return self.cluster(cluster_id).cc

    def registries(self) -> list:
        """Every sensor registry of the instance, shared core first — the
        `/metrics` exposition renders them together, each cluster's
        samples labeled by its registry's base_labels."""
        regs = [self.sensors]
        if self.core.sensors is not self.sensors:
            regs.append(self.core.sensors)
        regs.extend(ctx.cc.sensors for ctx in self.contexts.values())
        return regs

    # ---------------------------------------------------------- lifecycle

    def start_up(self, *, detection_interval_s: float | None = None,
                 precompute: bool = False) -> None:
        """Start every cluster's monitor/detector (and recovery resume +
        precompute loop) — the fleet twin of CruiseControl.start_up."""
        for ctx in self.contexts.values():
            ctx.cc.start_up(
                detection_interval_s=detection_interval_s, precompute=precompute
            )

    def shutdown(self) -> None:
        for ctx in self.contexts.values():
            try:
                ctx.cc.shutdown()
            except Exception:  # noqa: BLE001 — one cluster must not wedge the rest
                log.warning(
                    "shutdown of cluster %s failed", ctx.cluster_id, exc_info=True
                )

    # ------------------------------------------------------------ rollups

    def fleet_state(self, cluster_id: str | None = None) -> dict:
        """The GET /fleet payload: per-cluster summaries + the shared-core
        view (engine cache, supervisor, admission control)."""
        ids = [cluster_id] if cluster_id else self.cluster_ids()
        clusters = {cid: self.cluster(cid).rollup() for cid in ids}
        return {
            "numClusters": len(self.contexts),
            "clusters": clusters,
            "shared": shared_core_rollup(
                self.core, tenant_max_pending=self.tenant_max_pending
            ),
        }

    def score_clusters(self, *, allow_capacity_estimation: bool = True) -> dict:
        """Score every cluster's CURRENT placement on the shared goal
        chain, batching same-bucket clusters through the ScenarioEvaluator's
        one-dispatch path: clusters are grouped by their (bucketed) model
        shape, and each group rides one batched device program instead of
        N sequential evaluations.  Returns {cluster_id: score dict}."""
        from cruise_control_tpu.analyzer.objective import balancedness_score
        from cruise_control_tpu.service.progress import OperationProgress
        from cruise_control_tpu.analyzer.scenario_eval import VIOLATION_TOL

        states: dict[str, object] = {}
        out: dict[str, dict] = {}
        for cid, ctx in self.contexts.items():
            try:
                states[cid] = ctx.cc._cluster_model(
                    OperationProgress(),
                    allow_capacity_estimation=allow_capacity_estimation,
                )
            except Exception as e:  # noqa: BLE001 — a cluster without a
                # valid model yet (still sampling) must not sink the rollup
                out[cid] = {"error": repr(e)}
        groups: dict[object, list[str]] = {}
        for cid, state in states.items():
            groups.setdefault(state.shape, []).append(cid)
        ev = self.core.scenario_evaluator
        chain = self.core.chain
        names = chain.names()
        pw, sw = self.core.balancedness_weights
        for shape, cids in groups.items():
            objs, viols, degraded = ev.evaluate_states([states[c] for c in cids])
            for i, cid in enumerate(cids):
                v = viols[i]
                out[cid] = {
                    "objective": float(objs[i]),
                    "balancedness": balancedness_score(
                        v, chain, priority_weight=pw, strictness_weight=sw
                    ),
                    "violatedGoals": [
                        n for n, x in zip(names, v) if x > VIOLATION_TOL
                    ],
                    "degraded": bool(degraded),
                    #: how many clusters shared this batch's device
                    #: dispatch (same bucketed shape) — 1 means this
                    #: cluster scored alone
                    "batchedWith": len(cids),
                }
        if groups:
            self.sensors.counter("fleet.batched-score-runs").inc(len(groups))
            self.sensors.counter("fleet.batched-score-clusters").inc(
                sum(len(c) for c in groups.values())
            )
        return out
