"""Fleet controller: one service instance monitoring, rebalancing and
rightsizing N Kafka clusters.

The economics (ROADMAP item 4): the EXPENSIVE resources — the TPU, the
compiled engines, the DeviceSupervisor's breaker — are shared through one
`service.facade.AnalyzerCore`; the CHEAP ones — load monitors, executors
with their durable journals, detectors, sample streams — multiply per
cluster.  Shape buckets (PR 2) make the sharing real: clusters whose
bucketed model shapes coincide rebind the SAME compiled engine
(`analyzer.engine-cache-*` counters on the core registry prove it), and
same-bucket clusters are scored in one batched device dispatch through
the ScenarioEvaluator (`score_clusters`).

Ownership map:

  shared (AnalyzerCore, one per instance)    per cluster (ClusterContext)
  ----------------------------------------  ---------------------------------
  GoalChain + BalancingConstraint            LoadMonitor + aggregators
  GoalOptimizer (compiled-engine LRU)        Executor (+ journal under
  DeviceSupervisor (one circuit breaker)       <executor.journal.dir>/<id>/)
  ScenarioEvaluator / Rightsizer             AnomalyDetector + notifier
  Tracer store (per-cluster component        proposal cache + precompute loop
    namespaces ride Tracer.scoped)           SensorRegistry({cluster: <id>})

Admission control: the REST layer enforces `fleet.tenant.max.pending.tasks`
per cluster on the async user-task purgatory (429 + the cluster's
`fleet.tenant-rejections` counter on breach), so one noisy cluster cannot
starve the other clusters' proposal refreshes out of the shared pool.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)


def shared_core_rollup(core, *, tenant_max_pending: int = 0) -> dict:
    """The `shared` block of the GET /fleet payload — one builder for the
    fleet and the single-cluster synthetic rollup (service/server.py), so
    the two deployments can't drift apart field by field."""
    opt = core.optimizer
    out: dict = {
        "compiledEngines": opt.cache_size,
        "engineCacheHits": opt.engine_cache_hits,
        "engineCacheMisses": opt.engine_cache_misses,
        "degraded": core.supervisor is not None and core.supervisor.is_degraded,
        "tenantMaxPendingTasks": tenant_max_pending,
    }
    if core.supervisor is not None:
        out["supervisor"] = core.supervisor.state_json()
    if getattr(core, "scheduler", None) is not None:
        # QoS-aware device scheduler (fleet/scheduler.py): queue depths,
        # shed/brownout ladder state, per-class wait quantiles
        out["scheduler"] = core.scheduler.state_json()
    return out


class ClusterContext:
    """Everything ONE cluster owns inside a fleet: its facade (which holds
    the monitor, executor, journal, detector) plus the sampling stack that
    feeds it."""

    def __init__(self, cluster_id: str, cc, *, fetcher=None, task_runner=None):
        self.cluster_id = cluster_id
        self.cc = cc
        self.fetcher = fetcher
        self.task_runner = task_runner
        #: lifecycle flags (fleet HA): `started` once cc.start_up ran
        #: (gated on lease acquisition when HA is on); `degraded` while
        #: the cluster serves read-only after a lease loss
        self.started = False
        self.degraded = False
        #: serializes activations (acquire -> activate runs off the
        #: heartbeat thread; a rapid lose/re-acquire must not interleave
        #: two activations of the same cluster)
        import threading

        self.lifecycle_lock = threading.Lock()
        #: consecutive activation failures (drives the relinquish
        #: backoff so a persistently failing activation flaps slowly)
        self.activation_failures = 0

    def rollup(self) -> dict:
        """Cheap per-cluster state summary for the GET /fleet rollup (no
        model build, no device work)."""
        cc = self.cc
        out = {
            "proposalReady": cc._valid_cache() is not None,
            # age of the published proposal (seconds; -1 = none) — the
            # observable the scheduler's freshness SLO
            # (fleet.scheduler.freshness.slo.s) is enforced against
            "proposalAgeS": cc.proposal_age_s(),
            "hasOngoingExecution": cc.executor.has_ongoing_execution,
            "executorState": cc.executor.executor_state().get("state"),
            "modelGeneration": str(cc.monitor.model_generation()),
            "selfHealingBusy": cc.actions.is_busy,
        }
        if cc.controller is not None:
            # per-cluster streaming controller (each facade builds its own
            # from its cluster config; the fleet start_up fans them out)
            out["controller"] = {
                "running": cc.controller.running,
                "windowRolls": cc.controller.state_json()["windowRolls"],
            }
            hist = cc.sensors.get(
                "controller.window-roll-to-publish-seconds"
            )
            if hist is not None and hist.count:
                # the streaming hot path's headline latency (ROADMAP item
                # 4's p99 target), estimated from the exportable buckets
                out["controller"]["windowRollToPublishSeconds"] = {
                    "count": hist.count,
                    "p50": round(hist.quantile(0.5), 6),
                    "p99": round(hist.quantile(0.99), 6),
                }
        if cc.slo_registry is not None:
            # burn-rate summary per SLO (full detail on GET /slo)
            out["slo"] = cc.slo_registry.summary_json()
        if cc.ledger is not None:
            # decision ledger + predicted-vs-measured calibration
            # (analyzer/ledger.py; raw episodes on GET /ledger)
            out["ledger"] = cc.ledger.state_json()
            out["calibration"] = cc.calibration_state()
        recovery = cc.executor.recovery_info()
        if recovery is not None:
            out["recovered"] = True
        return out


class FleetManager:
    """Owns the cluster contexts and the shared core; the REST layer
    resolves `cluster=` through it and serves GET /fleet from it."""

    def __init__(self, core, contexts: dict[str, ClusterContext], *,
                 sensors, config, lease_manager=None):
        """core: the shared service.facade.AnalyzerCore every context's
        facade was built over; sensors: the fleet-level (unlabeled)
        registry — normally the same one the core registers into.

        lease_manager (fleet HA, fleet/leases.py): when set, cluster
        contexts start ONLY after this instance acquires their lease —
        monitor, controller, detector, executor and the PR-4 recovery
        resume all gate on ownership — and a lost lease steps the
        cluster down to read-only degraded mode (executor force-stopped,
        FLEET_LEASE_LOST raised through the detector/notifier)."""
        self.core = core
        self.contexts = dict(contexts)
        self.sensors = sensors
        self.config = config
        self.tenant_max_pending = config.get("fleet.tenant.max.pending.tasks")
        self.lease_manager = lease_manager
        self._start_kwargs: dict = {}
        if lease_manager is not None:
            lease_manager.on_acquired = self._on_lease_acquired
            lease_manager.on_lost = self._on_lease_lost
        sensors.gauge("fleet.clusters", lambda: len(self.contexts))

    # ------------------------------------------------------------- lookup

    def cluster_ids(self) -> list[str]:
        return list(self.contexts)

    def cluster(self, cluster_id: str) -> ClusterContext:
        ctx = self.contexts.get(cluster_id)
        if ctx is None:
            raise KeyError(
                f"unknown cluster {cluster_id!r}; clusters: {self.cluster_ids()}"
            )
        return ctx

    def facade(self, cluster_id: str):
        return self.cluster(cluster_id).cc

    def registries(self) -> list:
        """Every sensor registry of the instance, shared core first — the
        `/metrics` exposition renders them together, each cluster's
        samples labeled by its registry's base_labels."""
        regs = [self.sensors]
        if self.core.sensors is not self.sensors:
            regs.append(self.core.sensors)
        regs.extend(ctx.cc.sensors for ctx in self.contexts.values())
        return regs

    # ---------------------------------------------------------- lifecycle

    def start_up(self, *, detection_interval_s: float | None = None,
                 precompute: bool = False) -> None:
        """Start every cluster's monitor/detector (and recovery resume +
        precompute loop) — the fleet twin of CruiseControl.start_up.

        With a lease manager attached (fleet HA) nothing starts here:
        the heartbeat acquires leases in the background and
        _on_lease_acquired activates each cluster the moment this
        instance owns it."""
        self._start_kwargs = dict(
            detection_interval_s=detection_interval_s, precompute=precompute
        )
        if self.lease_manager is not None:
            self.lease_manager.start()
            return
        for ctx in self.contexts.values():
            ctx.cc.start_up(
                detection_interval_s=detection_interval_s, precompute=precompute
            )
            ctx.started = True

    def shutdown(self) -> None:
        if self.lease_manager is not None:
            # release held leases FIRST so a peer can take over without
            # waiting out the TTL
            self.lease_manager.stop()
        for ctx in self.contexts.values():
            try:
                ctx.cc.shutdown()
            except Exception:  # noqa: BLE001 — one cluster must not wedge the rest
                log.warning(
                    "shutdown of cluster %s failed", ctx.cluster_id, exc_info=True
                )

    # ------------------------------------------------------ fleet HA

    def _on_lease_acquired(self, cluster_id: str, lease, takeover: bool) -> None:
        """Lease heartbeat callback: this instance now owns the cluster.
        Activation runs on its OWN thread — reconciliation against a
        slow/unreachable admin must not stall the heartbeat and cost the
        instance its OTHER clusters' renewals."""
        import threading

        threading.Thread(
            target=self._activate_cluster,
            args=(cluster_id, lease, takeover),
            daemon=True,
            name=f"fleet-activate-{cluster_id}",
        ).start()

    def _activate_cluster(self, cluster_id: str, lease, takeover: bool) -> None:
        """Runs PR-4 restart reconciliation against the (shared)
        namespaced journal — on a takeover that is the DEAD holder's
        journal — then starts (or, after a loss/re-acquire cycle,
        resumes) the cluster's subsystems.  The fence was granted before
        this runs, so every admin call here is already fenced-in."""
        import time as _time

        ctx = self.cluster(cluster_id)
        cc = ctx.cc
        lm = self.lease_manager
        with ctx.lifecycle_lock:
            # a same-holder re-acquire can land while the previous fenced
            # abort is still winding down (the force-stopped loop exits on
            # its next tick) — wait it out so reconciliation is never
            # silently skipped, leaving the abort's throttle unswept
            deadline = _time.monotonic() + 60.0
            while (
                cc.executor.has_ongoing_execution
                and _time.monotonic() < deadline
                and lm.owns(cluster_id)
            ):
                _time.sleep(0.1)
            try:
                if not cc.executor.has_ongoing_execution:
                    # replays the journal, sweeps leaked throttles,
                    # reconciles in-flight moves; prunes journal archives
                    cc.executor.reconcile_journal()
                else:
                    log.warning(
                        "skipping journal reconciliation of %s: an "
                        "execution is still winding down", cluster_id,
                    )
            except Exception:  # noqa: BLE001 — reconciliation failure must
                # not forfeit the lease; the executor stays idle and logs
                log.warning(
                    "journal reconciliation of %s failed on lease "
                    "acquisition", cluster_id, exc_info=True,
                )
            try:
                if not ctx.started:
                    cc.start_up(**self._start_kwargs)  # resumes recovery
                    ctx.started = True
                elif cc.executor.has_recovered_execution:
                    cc.resume_recovered_async()
                ctx.activation_failures = 0
            except Exception:  # noqa: BLE001 — an activation failure must
                # not strand the cluster leased-but-unserved forever: give
                # the lease back so the next heartbeat (ours or a healthy
                # peer's) acquires and retries activation, backing OUR
                # retries off exponentially so a persistent failure flaps
                # slowly instead of every renew beat
                ctx.activation_failures += 1
                cooldown = min(300.0, lm.renew_s * 2 ** ctx.activation_failures)
                log.warning(
                    "activation of %s failed (attempt %d) — relinquishing "
                    "its lease, retrying in >= %.1fs",
                    cluster_id, ctx.activation_failures, cooldown,
                    exc_info=True,
                )
                ctx.degraded = True
                lm.relinquish(cluster_id, cooldown_s=cooldown)
                return
            # the lease may have been lost again while activation ran —
            # degraded must reflect the CURRENT ownership, not the state
            # at acquisition
            ctx.degraded = not lm.owns(cluster_id)
        log.info(
            "cluster %s activated (epoch %d%s)",
            cluster_id, lease.epoch, ", takeover" if takeover else "",
        )

    def _on_lease_lost(self, cluster_id: str, lease) -> None:
        """Lease heartbeat callback: ownership is gone (missed renewals
        past skew slack, or a peer took over).  Step the cluster down to
        read-only degraded mode: the executor halts mid-batch via the
        existing force-stop path (its fenced admin/journal calls raise
        anyway — this just makes the halt immediate), proposals//state//
        /fleet keep serving, and FLEET_LEASE_LOST flows through the
        detector/notifier so operators hear about it."""
        ctx = self.cluster(cluster_id)
        ctx.degraded = True
        cc = ctx.cc
        try:
            if cc.executor.has_ongoing_execution:
                cc.executor.stop_execution(force=True)
        except Exception:  # noqa: BLE001
            log.warning("force-stop of %s failed on lease loss",
                        cluster_id, exc_info=True)
        from cruise_control_tpu.detector.anomalies import FleetLeaseLost

        try:
            cc.anomaly_detector.add_anomaly(FleetLeaseLost(
                cluster_id=cluster_id,
                instance_id=self.lease_manager.holder_id,
                epoch=lease.epoch,
            ))
        except Exception:  # noqa: BLE001 — anomaly delivery is best-effort
            pass

    # ------------------------------------------------------------ rollups

    def fleet_state(self, cluster_id: str | None = None) -> dict:
        """The GET /fleet payload: per-cluster summaries + the shared-core
        view (engine cache, supervisor, admission control).  With fleet
        HA on, every cluster entry carries its `ownership` (owned/holder/
        epoch/degraded) and the payload an `ha` block (instance id, lease
        timings, owned set)."""
        ids = [cluster_id] if cluster_id else self.cluster_ids()
        clusters = {cid: self.cluster(cid).rollup() for cid in ids}
        lm = self.lease_manager
        if lm is not None:
            for cid in ids:
                ownership = lm.ownership_json(cid)
                ownership["degraded"] = self.cluster(cid).degraded
                clusters[cid]["ownership"] = ownership
        out = {
            "numClusters": len(self.contexts),
            "clusters": clusters,
            "shared": shared_core_rollup(
                self.core, tenant_max_pending=self.tenant_max_pending
            ),
        }
        if lm is not None:
            out["ha"] = lm.state_json()
        return out

    def score_clusters(self, *, allow_capacity_estimation: bool = True) -> dict:
        """Score every cluster's CURRENT placement on the shared goal
        chain, batching same-bucket clusters through the ScenarioEvaluator's
        one-dispatch path: clusters are grouped by their (bucketed) model
        shape, and each group rides one batched device program instead of
        N sequential evaluations.  Returns {cluster_id: score dict}."""
        from cruise_control_tpu.analyzer.objective import balancedness_score
        from cruise_control_tpu.service.progress import OperationProgress
        from cruise_control_tpu.analyzer.scenario_eval import VIOLATION_TOL

        states: dict[str, object] = {}
        out: dict[str, dict] = {}
        for cid, ctx in self.contexts.items():
            try:
                states[cid] = ctx.cc._cluster_model(
                    OperationProgress(),
                    allow_capacity_estimation=allow_capacity_estimation,
                )
            except Exception as e:  # noqa: BLE001 — a cluster without a
                # valid model yet (still sampling) must not sink the rollup
                out[cid] = {"error": repr(e)}
        groups: dict[object, list[str]] = {}
        for cid, state in states.items():
            groups.setdefault(state.shape, []).append(cid)
        ev = self.core.scenario_evaluator
        chain = self.core.chain
        names = chain.names()
        pw, sw = self.core.balancedness_weights
        sched = getattr(self.core, "scheduler", None)
        for shape, cids in groups.items():
            if sched is None:
                objs, viols, degraded = ev.evaluate_states(
                    [states[c] for c in cids]
                )
            else:
                # fleet-wide batched scoring is BACKGROUND work: under
                # overload the whole group's dispatch sheds (reported,
                # never silent) rather than delaying an urgent re-anneal
                from cruise_control_tpu.fleet.scheduler import (
                    BackgroundShedError,
                    WorkClass,
                )

                try:
                    objs, viols, degraded = sched.run(
                        WorkClass.BACKGROUND,
                        lambda cs=[states[c] for c in cids]: (
                            ev.evaluate_states(cs)
                        ),
                        op="fleet-score",
                    )
                except BackgroundShedError:
                    for cid in cids:
                        out[cid] = {"shed": True}
                    continue
            for i, cid in enumerate(cids):
                v = viols[i]
                out[cid] = {
                    "objective": float(objs[i]),
                    "balancedness": balancedness_score(
                        v, chain, priority_weight=pw, strictness_weight=sw
                    ),
                    "violatedGoals": [
                        n for n, x in zip(names, v) if x > VIOLATION_TOL
                    ],
                    "degraded": bool(degraded),
                    #: how many clusters shared this batch's device
                    #: dispatch (same bucketed shape) — 1 means this
                    #: cluster scored alone
                    "batchedWith": len(cids),
                }
        if groups:
            self.sensors.counter("fleet.batched-score-runs").inc(len(groups))
            self.sensors.counter("fleet.batched-score-clusters").inc(
                sum(len(c) for c in groups.values())
            )
        return out
