"""Config layer: typed ConfigDef + domain-grouped application config.

Reference: cruise-control-core common/config/ + config/KafkaCruiseControlConfig.java.
"""

from cruise_control_tpu.config.app_config import (
    CruiseControlConfig,
    cruise_control_config_def,
    load_properties,
)
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.config.config_def import (
    AbstractConfig,
    ConfigDef,
    ConfigException,
    ConfigType,
    Importance,
)
