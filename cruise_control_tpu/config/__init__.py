from cruise_control_tpu.config.balancing import DEFAULT_CONSTRAINT, BalancingConstraint

__all__ = ["DEFAULT_CONSTRAINT", "BalancingConstraint"]
