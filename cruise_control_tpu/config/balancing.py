"""Balancing thresholds (reference: analyzer/BalancingConstraint.java:22-54).

Defaults mirror reference config/constants/AnalyzerConfig.java:
  {cpu,disk,nw-in,nw-out}.balance.threshold        = 1.10   (:47,:56,:65,:74)
  replica.count.balance.threshold                  = 1.10   (:83)
  leader.replica.count.balance.threshold           = 1.10   (:92)
  topic.replica.count.balance.threshold            = 3.00   (:101)
  {cpu,disk,nw-in,nw-out}.capacity.threshold       = 0.8    (:110,:119,:128,:138)
  {*}.low.utilization.threshold                    = 0.0    (:148-175)
  max.replicas.per.broker                          = 10000  (:194)
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES


@dataclasses.dataclass(frozen=True)
class BalancingConstraint:
    # per-resource, indexed by Resource (CPU, NW_IN, NW_OUT, DISK)
    balance_threshold: tuple[float, ...] = (1.10, 1.10, 1.10, 1.10)
    capacity_threshold: tuple[float, ...] = (0.8, 0.8, 0.8, 0.8)
    low_utilization_threshold: tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)
    replica_count_balance_threshold: float = 1.10
    leader_replica_count_balance_threshold: float = 1.10
    topic_replica_count_balance_threshold: float = 3.00
    max_replicas_per_broker: int = 10_000
    # goal-violation detection uses a slacker multiplier on distribution goals
    # (reference AnalyzerConfig.java:316)
    goal_violation_distribution_threshold_multiplier: float = 1.0

    def balance_upper(self) -> np.ndarray:
        return np.asarray(self.balance_threshold, np.float32)

    def balance_lower(self) -> np.ndarray:
        # reference uses avg * max(0, 2 - threshold) as the lower bound
        # (ResourceDistributionGoal balanceLowerThreshold semantics)
        return np.maximum(0.0, 2.0 - np.asarray(self.balance_threshold, np.float32))

    def capacity(self) -> np.ndarray:
        return np.asarray(self.capacity_threshold, np.float32)


DEFAULT_CONSTRAINT = BalancingConstraint()
