"""Canonical REST endpoint names — a LEAF module.

The single source of truth consumed by the server's dispatch tables, the
parameter registry, the response-schema registry, and the config defs
({endpoint}.parameters.class / .request.class keys).  Lives in the config
layer so building a CruiseControlConfig never imports the service package
(app_config.py guards that layering: module imports here close cycles
through package __init__s).
"""

GET_ENDPOINTS = (
    "bootstrap", "train", "load", "partition_load", "proposals", "state",
    "kafka_cluster_state", "user_tasks", "review_board",
)
POST_ENDPOINTS = (
    "add_broker", "remove_broker", "fix_offline_replicas", "rebalance",
    "stop_proposal_execution", "pause_sampling", "resume_sampling",
    "demote_broker", "admin", "review", "topic_configuration",
)
ALL_ENDPOINTS = GET_ENDPOINTS + POST_ENDPOINTS
