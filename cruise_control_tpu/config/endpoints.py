"""Canonical REST endpoint names — a LEAF module.

The single source of truth consumed by the server's dispatch tables, the
parameter registry, the response-schema registry, and the config defs
({endpoint}.parameters.class / .request.class keys).  Lives in the config
layer so building a CruiseControlConfig never imports the service package
(app_config.py guards that layering: module imports here close cycles
through package __init__s).
"""

GET_ENDPOINTS = (
    "bootstrap", "train", "load", "partition_load", "proposals", "state",
    "kafka_cluster_state", "user_tasks", "review_board", "rightsize",
    "trace", "metrics", "fleet", "slo", "explain", "ledger",
)

#: endpoints that are fleet-GLOBAL: in fleet mode they answer for the
#: whole instance (rollups, shared stores) and never require `cluster=`;
#: every other endpoint is cluster-scoped and must name its cluster
FLEET_GLOBAL_ENDPOINTS = frozenset(
    {"fleet", "metrics", "trace", "user_tasks", "review_board", "review",
     "slo"}
)
POST_ENDPOINTS = (
    "add_broker", "remove_broker", "fix_offline_replicas", "rebalance",
    "stop_proposal_execution", "pause_sampling", "resume_sampling",
    "demote_broker", "admin", "review", "topic_configuration", "simulate",
)
ALL_ENDPOINTS = GET_ENDPOINTS + POST_ENDPOINTS

#: endpoint category (reference CruiseControlEndPoint.java:17-36) — drives
#: the per-category completed-user-task caches/retention
#: (config/constants/UserTaskManagerConfig.java)
ENDPOINT_TYPES = {
    "bootstrap": "CRUISE_CONTROL_ADMIN",
    "train": "CRUISE_CONTROL_ADMIN",
    "load": "KAFKA_MONITOR",
    "partition_load": "KAFKA_MONITOR",
    "proposals": "KAFKA_MONITOR",
    "state": "CRUISE_CONTROL_MONITOR",
    "add_broker": "KAFKA_ADMIN",
    "remove_broker": "KAFKA_ADMIN",
    "fix_offline_replicas": "KAFKA_ADMIN",
    "rebalance": "KAFKA_ADMIN",
    "stop_proposal_execution": "KAFKA_ADMIN",
    "pause_sampling": "CRUISE_CONTROL_ADMIN",
    "resume_sampling": "CRUISE_CONTROL_ADMIN",
    "kafka_cluster_state": "KAFKA_MONITOR",
    "demote_broker": "KAFKA_ADMIN",
    "user_tasks": "CRUISE_CONTROL_MONITOR",
    "review_board": "CRUISE_CONTROL_MONITOR",
    "admin": "CRUISE_CONTROL_ADMIN",
    "review": "CRUISE_CONTROL_ADMIN",
    "topic_configuration": "KAFKA_ADMIN",
    # planner endpoints are read-only analysis over the monitor's model
    "simulate": "KAFKA_MONITOR",
    "rightsize": "KAFKA_MONITOR",
    # observability: trace replay + Prometheus exposition (both read-only)
    "trace": "CRUISE_CONTROL_MONITOR",
    "metrics": "CRUISE_CONTROL_MONITOR",
    # fleet controller: whole-instance rollup over every managed cluster
    "fleet": "CRUISE_CONTROL_MONITOR",
    # SLO registry: burn rates + episode state (read-only)
    "slo": "CRUISE_CONTROL_MONITOR",
    # decision ledger: structured explanation of one published/executed
    # proposal, and the raw joined episode stream (both read-only;
    # cluster-scoped — each cluster owns its own ledger)
    "explain": "CRUISE_CONTROL_MONITOR",
    "ledger": "CRUISE_CONTROL_MONITOR",
}
assert set(ENDPOINT_TYPES) == set(ALL_ENDPOINTS)


def reference_key_name(endpoint: str) -> str:
    """The reference's dotted endpoint spelling for {endpoint}.parameters.class
    / .request.class keys (CruiseControlParametersConfig.java uses e.g.
    add.broker.parameters.class, stop.proposal.request.class)."""
    if endpoint == "stop_proposal_execution":
        return "stop.proposal"
    return endpoint.replace("_", ".")
