"""Application configuration — domain-grouped, reference-compatible keys.

Reference: config/KafkaCruiseControlConfig.java:38 (chained define across
domain constant classes) with the domain groups AnalyzerConfig.java,
MonitorConfig.java, ExecutorConfig.java, AnomalyDetectorConfig.java,
WebServerConfig.java.  Key names match the reference's where the concept
carries over, so existing cruisecontrol.properties files remain readable;
TPU-specific knobs (candidate batch etc.) are new keys under the
`analyzer.tpu` group.
"""

from __future__ import annotations

from typing import Any

# NOTE: analyzer modules import config.balancing; importing analyzer at
# module scope here would close an import cycle through the package
# __init__s, so goal/optimizer symbols are imported lazily inside functions.
from cruise_control_tpu.config.balancing import BalancingConstraint
from cruise_control_tpu.config.config_def import (
    AbstractConfig,
    ConfigDef,
    ConfigException,
    ConfigType as T,
    Importance as I,
    in_range,
    in_values,
)

_HARD_GOALS_DEFAULT = (
    "RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal,NetworkInboundCapacityGoal,"
    "NetworkOutboundCapacityGoal,CpuCapacityGoal"
)


def _analyzer_defs() -> ConfigDef:
    """Reference config/constants/AnalyzerConfig.java."""
    from cruise_control_tpu.analyzer.goals import DEFAULT_GOAL_ORDER

    d = ConfigDef()
    g = "analyzer"
    d.define("default.goals", T.LIST, ",".join(DEFAULT_GOAL_ORDER), I.HIGH,
             "goal names in priority order", group=g)
    d.define("hard.goals", T.LIST, _HARD_GOALS_DEFAULT, I.HIGH, "hard goal subset", group=g)
    for res in ("cpu", "disk", "network.inbound", "network.outbound"):
        d.define(f"{res}.balance.threshold", T.DOUBLE, 1.10, I.MEDIUM,
                 f"balance band multiplier for {res}", in_range(lo=1.0), group=g)
        d.define(f"{res}.capacity.threshold", T.DOUBLE, 0.8, I.MEDIUM,
                 f"usable capacity fraction for {res}", in_range(lo=0.0, hi=1.0), group=g)
        d.define(f"{res}.low.utilization.threshold", T.DOUBLE, 0.0, I.LOW,
                 f"below this the {res} balance is ignored", group=g)
    d.define("replica.count.balance.threshold", T.DOUBLE, 1.10, I.MEDIUM,
             "replica count band multiplier", in_range(lo=1.0), group=g)
    d.define("leader.replica.count.balance.threshold", T.DOUBLE, 1.10, I.MEDIUM,
             "leader count band multiplier", in_range(lo=1.0), group=g)
    d.define("topic.replica.count.balance.threshold", T.DOUBLE, 3.0, I.LOW,
             "per-topic replica band multiplier", in_range(lo=1.0), group=g)
    d.define("max.replicas.per.broker", T.LONG, 10_000, I.MEDIUM,
             "replica capacity per broker", in_range(lo=1), group=g)
    d.define("proposal.expiration.ms", T.LONG, 900_000, I.MEDIUM,
             "cached proposal validity", in_range(lo=0), group=g)
    d.define("goal.violation.distribution.threshold.multiplier", T.DOUBLE, 1.0, I.LOW,
             "slack multiplier for violation detection", in_range(lo=1.0), group=g)
    d.define("num.proposal.precompute.threads", T.INT, 1, I.LOW,
             "proposal precompute workers", in_range(lo=0), group=g)
    d.define("goal.balancedness.priority.weight", T.DOUBLE, 1.1, I.LOW,
             "weight multiplier between adjacent goal priorities in the "
             "balancedness score (reference "
             "KafkaCruiseControlUtils.balancednessCostByGoal:511-537)",
             in_range(lo=1.0), group=g)
    d.define("goal.balancedness.strictness.weight", T.DOUBLE, 1.5, I.LOW,
             "extra weight of hard goals in the balancedness score",
             in_range(lo=1.0), group=g)
    d.define("topics.excluded.from.partition.movement", T.STRING, "", I.MEDIUM,
             "regex of topics whose replicas never move in ANY optimization "
             "(merged with per-request excluded_topics; reference "
             "AnalyzerConfig topics.excluded.from.partition.movement)", group=g)
    d.define("allow.capacity.estimation.on.proposal.precompute", T.BOOLEAN, True,
             I.LOW, "precompute models may estimate missing broker capacities",
             group=g)
    from cruise_control_tpu.analyzer.goals import DEFAULT_INTRA_BROKER_GOAL_ORDER

    d.define("intra.broker.goals", T.LIST,
             ",".join(DEFAULT_INTRA_BROKER_GOAL_ORDER), I.MEDIUM,
             "goal chain for rebalance_disk (JBOD) operations "
             "(reference AnalyzerConfig.java:236)", group=g)
    # --- mixed-precision goal scoring (new in this framework) ---
    def _valid_score_dtype(name, value):
        if str(value) not in ("float32", "bfloat16"):
            raise ConfigException(
                f"{name} must be 'float32' or 'bfloat16', got {value!r}"
            )

    d.define("analyzer.precision.score.dtype", T.STRING, "float32", I.MEDIUM,
             "accumulation dtype of the goal-score inner loops (per-broker "
             "term sums and the weighted objective reduction); 'bfloat16' "
             "halves accumulator bandwidth on the annealer's hot path, "
             "'float32' (default) pins today's graphs bit-for-bit — "
             "reports, violations and proposal scoring stay float32 "
             "either way", _valid_score_dtype, group=g)
    d.define("analyzer.precision.tolerance", T.DOUBLE, 0.02, I.LOW,
             "relative objective-quality tolerance the bfloat16 scoring "
             "path must hold against the float32 reference (the parity "
             "gate tests/benches assert before the low-precision path is "
             "trusted)", in_range(lo=0.0), group=g)
    # --- TPU optimizer knobs (new in this framework) ---
    g = "analyzer.tpu"
    d.define("tpu.num.candidates", T.INT, 2048, I.MEDIUM,
             "candidate moves evaluated per optimization step", in_range(lo=16), group=g)
    d.define("tpu.leadership.candidates", T.INT, 512, I.MEDIUM,
             "of which leadership transfers", in_range(lo=0), group=g)
    d.define("tpu.swap.candidates", T.INT, 512, I.MEDIUM,
             "of which replica swaps (clamped to half the non-leadership budget)",
             in_range(lo=0), group=g)
    d.define("tpu.steps.per.round", T.INT, 64, I.MEDIUM, "scan length per round",
             in_range(lo=1), group=g)
    d.define("tpu.num.rounds", T.INT, 10, I.MEDIUM, "annealing rounds", in_range(lo=1), group=g)
    d.define("tpu.init.temperature.scale", T.DOUBLE, 1e-2, I.LOW,
             "T0 as fraction of initial objective", group=g)
    d.define("tpu.temperature.decay", T.DOUBLE, 0.5, I.LOW, "per-round decay", group=g)
    d.define("tpu.replica.move.cost", T.DOUBLE, 0.5, I.MEDIUM,
             "objective price per replica moved off its original broker",
             in_range(lo=0.0), group=g)
    d.define("tpu.leadership.move.cost", T.DOUBLE, 1.0, I.MEDIUM,
             "objective price per partition leadership moved off its original leader",
             in_range(lo=0.0), group=g)
    d.define("tpu.importance.fraction", T.DOUBLE, 0.5, I.LOW,
             "fraction of candidates importance-sampled toward violating brokers",
             in_range(lo=0.0, hi=1.0), group=g)
    def _valid_parallel_mode(name, value):
        from cruise_control_tpu.analyzer.optimizer import parse_parallel_mode

        try:
            parse_parallel_mode(str(value))
        except ValueError as e:
            raise ConfigException(f"{name}: {e}") from e

    d.define("tpu.parallel.mode", T.STRING, "single", I.MEDIUM,
             "multi-device strategy: single / sharded (candidate axis "
             "sharded over the mesh, parallel/mesh.py) / grid:RxM "
             "(restart portfolio over model shards)",
             _valid_parallel_mode, group=g)
    d.define("tpu.mesh.max.devices", T.INT, 0, I.MEDIUM,
             "cap on the devices the mesh engine layer builds its mesh "
             "from for sharded/grid parallel modes (0 = every visible "
             "device) — lets operators keep chips free for other tenants "
             "or pin a power-of-two shard count", in_range(lo=0), group=g)
    d.define("tpu.mesh.model.shard.min.partitions", T.INT, 500_000, I.MEDIUM,
             "partition count at which the mesh engine layer shards the "
             "flattened model itself over the model axis (contiguous "
             "replica/partition row blocks per chip, broker aggregates "
             "psum-assembled) instead of replicating it — per-chip model "
             "memory and per-step row FLOPs drop ~1/n while placements "
             "stay byte-identical; below the threshold the replicated "
             "model wins on collective volume (0 = never shard the model)",
             in_range(lo=0), group=g)
    d.define("tpu.mesh.ft.enabled", T.BOOLEAN, True, I.MEDIUM,
             "mesh fault tolerance (parallel/ft.py): on a classified mesh "
             "failure (device lost / collective stall) the optimizer "
             "rebuilds the mesh over the surviving devices at the next "
             "lower power-of-two width and resumes from the last carry "
             "checkpoint, under per-width breakers that never open the "
             "single-device breaker; false restores the pre-FT behavior "
             "(any mesh failure degrades straight to the CPU greedy "
             "fallback)", group=g)
    d.define("tpu.mesh.ft.checkpoint.every.slices", T.INT, 0, I.MEDIUM,
             "capture a host-side carry checkpoint every N slice "
             "boundaries of a segmented mesh anneal (one in-flight "
             "snapshot, capture wall excluded from the supervisor's hang "
             "budget) so a degrade-and-resume continues the round "
             "schedule instead of restarting it; 0 (default) disables "
             "checkpointing — byte-for-byte the uncheckpointed dispatch "
             "stream", in_range(lo=0), group=g)
    d.define("tpu.shape.bucket.enabled", T.BOOLEAN, True, I.MEDIUM,
             "round cluster-model shapes (replicas/brokers/partitions/"
             "topics/racks/hosts) up to geometric buckets so compiled "
             "engines survive topology churn — partition creates and "
             "broker adds within a bucket rebind the cached engine with "
             "zero recompilation", group=g)
    d.define("tpu.shape.bucket.growth", T.DOUBLE, 1.25, I.MEDIUM,
             "bucket growth factor between adjacent shape buckets; larger "
             "values recompile less often but pad (and compute over) more",
             in_range(lo=1.01), group=g)
    d.define("tpu.shape.bucket.floor", T.INT, 8, I.LOW,
             "smallest shape bucket (series base)", in_range(lo=1), group=g)
    d.define("tpu.engine.cache.size", T.INT, 8, I.MEDIUM,
             "max compiled engines kept per optimizer (LRU; evicted "
             "engines' device buffers are released) — bounds HBM growth "
             "across shape-bucket transitions", in_range(lo=1), group=g)
    d.define("tpu.compilation.cache.dir", T.STRING,
             "~/.cache/cruise_control_tpu/xla", I.LOW,
             "persistent XLA compilation cache directory; empty disables "
             "(compiled programs survive service restarts)", group=g)
    d.define("tpu.compile.cache.dir", T.STRING, None, I.LOW,
             "preferred spelling of tpu.compilation.cache.dir (takes "
             "precedence when both are set): the on-disk XLA executable "
             "cache a restarted service/controller reloads instead of "
             "re-tracing unchanged shape buckets; boot logs the cache's "
             "entry count and the first proposal pass logs how many "
             "executables were compiled fresh (misses) vs available warm",
             group=g)
    # --- supervised optimizer runtime (common/device_watchdog.py) ---
    g = "analyzer.tpu.supervisor"
    d.define("tpu.supervisor.enabled", T.BOOLEAN, True, I.MEDIUM,
             "run every service-path engine invocation under the device "
             "supervisor: bounded budget, failure classification "
             "(hang/compile/OOM/transient), retry, circuit breaker with "
             "CPU-greedy degraded mode while the breaker is open", group=g)
    d.define("tpu.supervisor.op.timeout.s", T.DOUBLE, 300.0, I.MEDIUM,
             "hard wall-clock budget per supervised engine invocation; a "
             "call not finished by then is classified as a device HANG "
             "(observed MULTICHIP_r05: a wedged runtime hangs every op)",
             in_range(lo=0.001), group=g)
    d.define("tpu.supervisor.max.retries", T.INT, 2, I.LOW,
             "retries (with jittered backoff) for TRANSIENT-classified "
             "failures before one operation-level failure is counted "
             "toward the breaker", in_range(lo=0), group=g)
    d.define("tpu.supervisor.retry.backoff.ms", T.LONG, 250, I.LOW,
             "base of the full-jitter exponential retry backoff",
             in_range(lo=1), group=g)
    d.define("tpu.supervisor.retry.backoff.max.ms", T.LONG, 5_000, I.LOW,
             "cap of the retry backoff", in_range(lo=1), group=g)
    d.define("tpu.supervisor.breaker.failure.threshold", T.INT, 3, I.MEDIUM,
             "consecutive classified operation failures that open the "
             "circuit breaker (degraded CPU-greedy serving starts)",
             in_range(lo=1), group=g)
    d.define("tpu.supervisor.probe.interval.s", T.DOUBLE, 30.0, I.MEDIUM,
             "while the breaker is open, one half-open recovery probe "
             "(the trivial-op watchdog) runs at most this often",
             in_range(lo=0.0), group=g)
    d.define("tpu.supervisor.probe.timeout.s", T.DOUBLE, 20.0, I.LOW,
             "budget for the half-open recovery probe",
             in_range(lo=0.001), group=g)
    d.define("tpu.supervisor.degraded.greedy.budget.s", T.DOUBLE, 30.0, I.MEDIUM,
             "wall-clock budget for the CPU greedy fallback that serves "
             "proposals while the breaker is open", in_range(lo=0.001),
             group=g)
    # --- opt-in device profiling (common/profiling.py) ---
    g = "analyzer.tpu.profiler"
    d.define("tpu.profiler.enabled", T.BOOLEAN, False, I.LOW,
             "wrap every engine run in a jax.profiler trace dumped to "
             "tpu.profiler.dump.dir — the XLA-level op timeline for "
             "slow-run forensics (TensorBoard/XProf readable).  Costs "
             "real time and disk per run; keep off outside an "
             "investigation", group=g)
    d.define("tpu.profiler.dump.dir", T.STRING,
             "/tmp/cruise-control-tpu-profiler", I.LOW,
             "directory jax.profiler trace dumps land in when "
             "tpu.profiler.enabled is on", group=g)
    # --- boot prewarm manifest + AOT programs (analyzer/prewarm.py) ---
    g = "analyzer.tpu.prewarm"
    d.define("tpu.prewarm.enabled", T.BOOLEAN, True, I.MEDIUM,
             "persist the active engine working set (bucketed shape + "
             "search config) to a durable manifest on every engine "
             "build, and replay it on start_up so a restarted service's "
             "active buckets are compiling BEFORE the first proposal is "
             "needed — the cold-start-to-first-proposal SLO "
             "(bench.py --coldstart)", group=g)
    d.define("tpu.prewarm.manifest.dir", T.STRING, None, I.LOW,
             "directory of the boot-prewarm manifest and AOT-serialized "
             "engine programs; unset derives the 'prewarm' subdirectory "
             "inside the persistent XLA compile cache "
             "(tpu.compile.cache.dir — same mount, one durability "
             "story; the cache's boot inventory prunes it), empty "
             "disables prewarm even when tpu.prewarm.enabled is on",
             group=g)
    d.define("tpu.prewarm.aot.enabled", T.BOOLEAN, True, I.MEDIUM,
             "serialize the fused anneal program per (bucket, config "
             "fingerprint) via jax.export so a warm-disk restart skips "
             "Python tracing too; artifacts load only on warm-pool "
             "workers and any version/aval/checksum mismatch falls back "
             "to the plain jit path — correctness never depends on an "
             "artifact", group=g)
    d.define("tpu.prewarm.max.entries", T.INT, 6, I.LOW,
             "manifest entries kept (most-recently-used buckets win) — "
             "bounds how many engines a boot prewarm compiles",
             in_range(lo=1), group=g)
    # --- convergence diagnostics + decision ledger + calibration ---
    g = "analyzer.diagnostics"
    d.define("analyzer.diagnostics.enabled", T.BOOLEAN, True, I.MEDIUM,
             "compile convergence diagnostics into the fused anneal: "
             "per-round objective trajectory, per-goal violation vector "
             "at round boundaries, acceptance counts by move kind and "
             "prior-draw usage ride the run's existing single host "
             "extraction (zero extra blocking syncs) into "
             "OptimizerResult.history, the analyzer.optimize span, and "
             "the decision ledger.  Placements are byte-identical either "
             "way (pinned); false restores today's outputs bit-for-bit",
             group=g)
    g = "analyzer.ledger"
    d.define("analyzer.ledger.enabled", T.BOOLEAN, True, I.MEDIUM,
             "durably record one `decision` record per published "
             "proposal (trace id, generation, bucket + config "
             "fingerprint, per-goal pre/post scores, predicted load, "
             "per-move features, convergence summary) into an "
             "append-only crash-tolerant JSONL ledger, joined by an "
             "`outcome` record at execution completion and a "
             "`calibration` record once the next complete metric window "
             "measures what the moves actually did — the training "
             "corpus for learned optimization and the GET /explain "
             "surface.  Needs a durable directory (analyzer.ledger.dir, "
             "or derived from executor.journal.dir); without one the "
             "ledger stays off and writes zero bytes", group=g)
    d.define("analyzer.ledger.dir", T.STRING, None, I.LOW,
             "directory of the decision ledger (decision-ledger.jsonl; "
             "fleet deployments namespace one subdirectory per "
             "cluster).  Unset derives '_ledger' inside "
             "executor.journal.dir — decisions must survive exactly the "
             "crashes the journal survives; explicitly empty disables",
             group=g)
    d.define("analyzer.ledger.retention.count", T.INT, 32, I.LOW,
             "rotated ledger archives kept (newest first); archives "
             "holding a decision whose outcome is still pending are "
             "never pruned", in_range(lo=1), group=g)
    d.define("analyzer.ledger.retention.hours", T.DOUBLE, 336.0, I.LOW,
             "age bound on rotated ledger archives (hours); the live "
             "file and pending-outcome episodes are never pruned",
             in_range(lo=0.1), group=g)
    g = "analyzer.calibration"
    d.define("analyzer.calibration.enabled", T.BOOLEAN, True, I.MEDIUM,
             "after an executed proposal's moves land and the next "
             "complete metric window rolls, score the MEASURED cluster "
             "state through the same goal chain (one batched "
             "ScenarioEvaluator dispatch) and append a calibration "
             "record — predicted vs realized per-goal scores and "
             "per-broker load prediction error — to the decision "
             "ledger, the analyzer.calibration.* sensors and the /fleet "
             "per-cluster rollup.  No-op while the ledger is off",
             group=g)
    d.define("analyzer.calibration.drift.threshold", T.DOUBLE, 0.05, I.MEDIUM,
             "mean absolute per-goal prediction error (worst goal, over "
             "the last drift.min.samples calibrated executions) past "
             "which one alert-only MODEL_DRIFT anomaly fires per "
             "episode through the detector/notifier; the episode "
             "re-arms when the mean falls back under the threshold",
             in_range(lo=0.0), group=g)
    d.define("analyzer.calibration.drift.min.samples", T.INT, 3, I.LOW,
             "calibrated executions required before MODEL_DRIFT may "
             "fire (one bad sample is noise, not drift)",
             in_range(lo=1), group=g)
    return d


def _controller_defs() -> ConfigDef:
    """Streaming-controller keys (controller/streaming.py — no reference
    analog: the reference recomputes proposals from scratch on a timer)."""
    d = ConfigDef()
    g = "controller"
    d.define("controller.enabled", T.BOOLEAN, False, I.MEDIUM,
             "run the always-on streaming controller: the flattened "
             "cluster model stays device-resident, metric-window deltas "
             "apply in place (no re-flatten while the shape bucket holds) "
             "and every window roll re-anneals incrementally — warm-"
             "started from the previous accepted placement and the "
             "learned move-acceptance prior — publishing into the "
             "proposal cache.  Replaces the legacy proposal-precompute "
             "loop while on", group=g)
    d.define("controller.poll.interval.ms", T.LONG, 1_000, I.MEDIUM,
             "how often the controller checks the partition aggregator "
             "for a rolled metric window (cheap generation reads; the "
             "expensive work only runs on an actual roll)",
             in_range(lo=10), group=g)
    d.define("controller.warm.start.enabled", T.BOOLEAN, True, I.MEDIUM,
             "seed each incremental anneal's carry from the previous "
             "accepted placement instead of the current cluster placement "
             "(movement pricing still charges strays against the real "
             "cluster); off = every anneal is cold", group=g)
    d.define("controller.delta.enabled", T.BOOLEAN, True, I.MEDIUM,
             "apply metric-window deltas to the device-resident model in "
             "place; off forces a full model re-flatten every window roll "
             "(the parity/diagnosis mode the streaming bench gates "
             "against)", group=g)
    d.define("controller.fusion.enabled", T.BOOLEAN, True, I.MEDIUM,
             "fuse delta-scatter + warm re-anneal + proposal extraction "
             "into ONE donated device program per steady-state window "
             "roll (one dispatch, one host extraction); off pins the "
             "staged scatter-then-anneal path bit-for-bit — the fusion "
             "parity/diagnosis mode", group=g)
    d.define("controller.plan.sizing.enabled", T.BOOLEAN, True, I.MEDIUM,
             "size each steady-state cycle's candidate plan from the "
             "delta's changed-partition count (quantized to 1/2, 1/4 or "
             "1/8 of the configured width — bounded compile count, "
             "brownout-style); reflatten cycles always run full-K; off "
             "pins full-K every cycle", group=g)
    d.define("controller.plan.candidates.per.partition", T.INT, 16, I.LOW,
             "candidate-plan width budgeted per changed partition when "
             "delta-sized plans are on; the needed width is "
             "max(plan.min.candidates, changed x this) before "
             "quantization", in_range(lo=1), group=g)
    d.define("controller.plan.min.candidates", T.INT, 256, I.LOW,
             "floor on the delta-sized candidate need, so tiny deltas "
             "still explore a meaningful neighborhood",
             in_range(lo=1), group=g)
    d.define("controller.prior.mix", T.DOUBLE, 0.5, I.MEDIUM,
             "fraction of the annealer's replica-move DESTINATION draws "
             "taken from the learned per-topic-pair move-acceptance "
             "prior once it is ready; 0 disables prior sampling entirely "
             "(the engine program stays byte-identical to the request "
             "path's)", in_range(lo=0.0, hi=1.0), group=g)
    d.define("controller.prior.decay", T.DOUBLE, 0.9, I.LOW,
             "exponential decay applied to the prior's acceptance counts "
             "per observation batch, so stale traffic patterns fade",
             in_range(lo=0.01, hi=1.0), group=g)
    d.define("controller.prior.min.observations", T.INT, 64, I.LOW,
             "decayed (topic, destination) observations required before "
             "the prior's mix turns on; below it the prior is COLD and "
             "destination draws reproduce the uniform stream byte-for-"
             "byte", in_range(lo=0), group=g)
    return d


def _observability_defs() -> ConfigDef:
    """Flight recorder + Prometheus exposition keys (common/trace.py,
    common/exposition.py — no reference analog: the reference's
    observability is JMX sensors only)."""
    d = ConfigDef()
    g = "observability.trace"
    d.define("trace.enabled", T.BOOLEAN, True, I.MEDIUM,
             "record flight-recorder spans for every pipeline stage "
             "(model build, optimize, device ops, execution, planner, "
             "detector) — served by GET /trace; async responses carry "
             "_traceId.  Overhead is gated <2% of a smoke proposal run "
             "(scripts/check.sh)", group=g)
    d.define("trace.retention.spans.per.component", T.INT, 512, I.LOW,
             "bounded ring-buffer size PER COMPONENT (service/monitor/"
             "analyzer/device/executor/planner/detector) — a chatty "
             "component evicts its own history, never another's; a trace "
             "expires when its spans age out of every ring",
             in_range(lo=16), group=g)
    d.define("trace.max.events.per.span", T.INT, 512, I.LOW,
             "events kept per span (task transitions, retries, breaker "
             "flips); beyond it events are counted as dropped, not kept — "
             "a 100k-task execution must not hold 100k dicts",
             in_range(lo=8), group=g)
    g = "observability.metrics"
    d.define("metrics.prometheus.namespace", T.STRING, "cruisecontrol",
             I.LOW,
             "metric-name prefix of the GET /metrics Prometheus "
             "exposition (sensor catalog names are sanitized beneath it)",
             lambda n, v: None if __import__("re").fullmatch(
                 r"[a-zA-Z_][a-zA-Z0-9_]*", str(v)
             ) else (_ for _ in ()).throw(ConfigException(
                 f"{n}={v!r} is not a valid Prometheus name prefix")),
             group=g)
    # --- black-box dispatch spool (common/blackbox.py) ---
    g = "observability.blackbox"
    d.define("blackbox.enabled", T.BOOLEAN, True, I.MEDIUM,
             "record every device dispatch (supervised calls, engine "
             "runs, segmented-anneal slices, scheduler grants, "
             "controller cycles) to a crash/hang-durable on-disk JSONL "
             "ring spool — a hung or killed process leaves a readable "
             "'last dispatch in flight' trail instead of a bare return "
             "code.  Needs a durable directory (blackbox.dir, or derived "
             "from executor.journal.dir / tpu.compile.cache.dir); "
             "without one the recorder stays off.  Overhead is gated "
             "<2% of a smoke proposal run (bench.py "
             "--blackbox-overhead)", group=g)
    d.define("blackbox.dir", T.STRING, None, I.LOW,
             "directory of the black-box spool files "
             "(spool-<pid>.jsonl).  Unset derives '_blackbox' inside "
             "executor.journal.dir (the service's durable mount), "
             "falling back to a 'blackbox' subdirectory of the "
             "persistent compile cache; explicitly empty disables",
             group=g)
    d.define("blackbox.spool.max.records", T.INT, 2048, I.LOW,
             "ring size: the active spool file rotates past this many "
             "records, keeping one previous generation — bounded disk "
             "forever", in_range(lo=64), group=g)
    d.define("blackbox.fsync.batch.records", T.INT, 32, I.LOW,
             "records between fsyncs.  Every record is flushed to the "
             "kernel synchronously (process death of any flavor cannot "
             "lose it); fsync batching only bounds what machine power "
             "loss could cost, exactly like the executor journal's "
             "batch knob", in_range(lo=1), group=g)
    # --- SLO registry + burn-rate alerting (common/slo.py) ---
    g = "observability.slo"
    d.define("slo.enabled", T.BOOLEAN, True, I.MEDIUM,
             "continuously evaluate the service-level objectives "
             "(per-cluster proposal freshness against "
             "fleet.scheduler.freshness.slo.s, cold-start-to-first-"
             "proposal, streaming publish latency, urgent queue wait) "
             "with fast/slow multi-window error-budget burn rates; a "
             "sustained breach raises one alert-only SLO_BURN anomaly "
             "per episode through the detector/notifier and is served "
             "by GET /slo + Prometheus slo.* gauges", group=g)
    d.define("slo.tick.interval.s", T.DOUBLE, 5.0, I.LOW,
             "cadence of the background SLO evaluation loop (probes "
             "sampled, burn rates re-evaluated, episodes fired/cleared); "
             "GET /slo additionally evaluates on every scrape",
             in_range(lo=0.1), group=g)
    d.define("slo.burn.fast.window.s", T.DOUBLE, 300.0, I.MEDIUM,
             "fast burn-rate window: catches a new fire quickly; an "
             "episode fires only when BOTH windows burn past "
             "slo.burn.threshold", in_range(lo=1.0), group=g)
    d.define("slo.burn.slow.window.s", T.DOUBLE, 3600.0, I.MEDIUM,
             "slow burn-rate window: keeps one noisy sample from paging "
             "— must be >= the fast window",
             in_range(lo=1.0), group=g)
    d.define("slo.burn.threshold", T.DOUBLE, 10.0, I.MEDIUM,
             "error-budget burn multiple (1.0 = consuming the budget "
             "exactly at the sustainable rate) both windows must reach "
             "to open a breach episode", in_range(lo=1.0), group=g)
    d.define("slo.streaming.publish.target.s", T.DOUBLE, 1.0, I.MEDIUM,
             "good/bad threshold of the streaming-publish SLO: a window "
             "roll whose superseding proposal publishes within this wall "
             "is a good sample (ROADMAP item 4's sub-second control-loop "
             "target, measured by "
             "controller.window-roll-to-publish-seconds)",
             in_range(lo=0.001), group=g)
    d.define("slo.coldstart.target.s", T.DOUBLE, 60.0, I.MEDIUM,
             "good/bad threshold of the cold-start SLO: start_up to the "
             "first served/published proposal (PR 10's restart SLO, "
             "bench.py --coldstart), one sample per process",
             in_range(lo=0.1), group=g)
    return d


def _planner_defs() -> ConfigDef:
    """Scenario planner keys (no reference analog — the reference's
    provision analysis is a fixed single-hypothetical check)."""
    d = ConfigDef()
    g = "planner"
    d.define("planner.max.scenarios", T.INT, 32, I.MEDIUM,
             "cap on scenarios per /simulate batch (every scenario is a "
             "full padded cluster model on device)", in_range(lo=1), group=g)
    d.define("planner.simulate.optimize.default", T.BOOLEAN, False, I.LOW,
             "run the full anneal per scenario when /simulate omits the "
             "optimize parameter (projected post-fix view; slower)", group=g)
    d.define("planner.forecast.method", T.STRING, "linear", I.MEDIUM,
             "per-topic load trend fitter: linear (OLS over the windowed "
             "history) or holt (double exponential smoothing)",
             in_values("linear", "holt"), group=g)
    d.define("planner.forecast.horizons.ms", T.LIST, "3600000,21600000",
             I.MEDIUM, "horizons of the trend outlook every /rightsize "
             "response carries (fitted per-topic scale factors, no extra "
             "anneals; the full forecast VERDICT needs an explicit "
             "horizon_ms)", group=g)
    d.define("planner.forecast.min.windows", T.INT, 3, I.LOW,
             "completed windows a topic must have before its trend is "
             "trusted (fewer: the topic is left unforecast at factor 1.0)",
             in_range(lo=2), group=g)
    d.define("planner.forecast.max.factor", T.DOUBLE, 10.0, I.LOW,
             "clamp on projected per-topic load multipliers — a trend fit "
             "over a handful of noisy windows must not 1000x a topic",
             in_range(lo=1.0), group=g)
    d.define("planner.rightsize.min.brokers", T.INT, 1, I.MEDIUM,
             "floor of the rightsizing search (the replication-factor "
             "floor is always applied on top)", in_range(lo=1), group=g)
    d.define("planner.rightsize.max.broker.factor", T.DOUBLE, 2.0, I.MEDIUM,
             "ceiling of the rightsizing search as a multiple of the "
             "current broker count", in_range(lo=1.0), group=g)
    d.define("planner.rightsize.max.anneals", T.INT, 16, I.LOW,
             "full-anneal budget of one rightsize search; the binary "
             "search reports UNDECIDED when it runs out mid-bracket",
             in_range(lo=1), group=g)
    return d


#: cluster ids become journal subdirectories, sensor label values and
#: Prometheus label data — keep them filesystem- and exposition-safe
_CLUSTER_ID_RE = r"[A-Za-z0-9][A-Za-z0-9._-]*"


def _fleet_defs() -> ConfigDef:
    """Fleet controller keys (fleet/manager.py — no reference analog: one
    reference deployment watches exactly one Kafka cluster)."""
    import re

    def _valid_cluster_ids(name, value):
        for cid in value:
            if not re.fullmatch(_CLUSTER_ID_RE, cid):
                raise ConfigException(
                    f"{name}: cluster id {cid!r} must match {_CLUSTER_ID_RE} "
                    "(ids become journal subdirectories and metric labels)"
                )
            if cid == "ha":
                # fleet.ha.* are the HA keys themselves — a cluster named
                # "ha" would make its fleet.ha.<key> overrides ambiguous
                raise ConfigException(
                    f"{name}: cluster id 'ha' is reserved (fleet.ha.* are "
                    "the lease-ownership keys)"
                )
        if len(set(value)) != len(value):
            raise ConfigException(f"{name}: duplicate cluster ids in {value}")

    d = ConfigDef()
    g = "fleet"
    d.define("fleet.clusters", T.LIST, "", I.HIGH,
             "cluster ids this instance manages as a fleet; empty (the "
             "default) keeps the classic single-cluster deployment "
             "byte-for-byte unchanged.  Each id gets its own monitor, "
             "executor (journal under <executor.journal.dir>/<id>/), "
             "detector and sample stream behind ONE shared optimizer + "
             "device supervisor + compiled-engine cache; per-cluster "
             "overrides ride fleet.<id>.<key> keys (e.g. "
             "fleet.east.bootstrap.servers) over the base config — "
             "cluster-scoped keys only: overriding a shared-core or "
             "webserver key (tpu.*, default.goals, balance/capacity "
             "thresholds, planner.*, trace.*, webserver.*, ...) is "
             "rejected because the fleet builds those once from the base",
             _valid_cluster_ids, group=g)
    d.define("fleet.tenant.max.pending.tasks", T.INT, 8, I.MEDIUM,
             "per-cluster cap on concurrently Active async user tasks in "
             "fleet mode — admission control on the async purgatory so one "
             "noisy cluster's request storm cannot starve the other "
             "clusters' proposal refreshes (breach: 429 + "
             "fleet.tenant-rejections sensor); 0 disables",
             in_range(lo=0), group=g)
    d.define("fleet.tenant.retry.after.s", T.DOUBLE, 5.0, I.LOW,
             "fallback Retry-After (seconds) on admission-control and "
             "scheduler-shed 429 responses when no queue drain rate has "
             "been observed yet; with history, Retry-After is computed "
             "from the tenant queue's actual drain rate",
             in_range(lo=1.0), group=g)
    # --- fleet device scheduler: QoS-aware dispatch (fleet/scheduler.py) ---
    g = "fleet.scheduler"
    d.define("fleet.scheduler.enabled", T.BOOLEAN, False, I.HIGH,
             "QoS-aware device scheduler: every engine dispatch (detector "
             "fix pipelines = URGENT, REST proposals/simulate/rightsize = "
             "INTERACTIVE, streaming drift cycles / fleet scoring / "
             "speculative prewarm = BACKGROUND) runs under one arbitrated "
             "device slot with per-class deadlines, aging, bounded-wall "
             "preemption of segmented anneals, and a shed/brownout "
             "overload ladder.  Off (the default): dispatch order is "
             "byte-for-byte unscheduled", group=g)
    d.define("fleet.scheduler.slice.budget.s", T.DOUBLE, 1.0, I.MEDIUM,
             "wall-clock bound per segmented-anneal slice: a granted "
             "non-urgent anneal dispatches the fused round schedule in "
             "slices no longer than this, with a preemption check between "
             "slices — an URGENT request waits at most one slice",
             in_range(lo=0.01), group=g)
    d.define("fleet.scheduler.freshness.slo.s", T.DOUBLE, 60.0, I.MEDIUM,
             "per-cluster proposal-freshness SLO the scheduler derives "
             "request deadlines from: BACKGROUND cycles must dispatch "
             "within the SLO, INTERACTIVE within a quarter of it, URGENT "
             "within one slice budget.  Per-cluster overridable "
             "(fleet.<id>.fleet.scheduler.freshness.slo.s); the published "
             "proposal age it protects is observable as "
             "analyzer.proposal-age-seconds", in_range(lo=0.1), group=g)
    d.define("fleet.scheduler.fast.path.enabled", T.BOOLEAN, True, I.LOW,
             "grant INTERACTIVE work an unsegmented slot when no other "
             "tenant is waiting at grant time: an idle device gets the "
             "whole anneal as one dispatch (no between-slice preemption "
             "checks, no segmentation overhead) — the streaming "
             "controller's fused sub-second cycles ride this; off "
             "segments every non-urgent grant as before",
             group=g)
    d.define("fleet.scheduler.aging.s", T.DOUBLE, 30.0, I.LOW,
             "wait after which a BACKGROUND ticket is ranked with the "
             "INTERACTIVE class (its older deadline then wins the "
             "earliest-deadline tiebreak) — background can be delayed by "
             "load, never starved", in_range(lo=0.0), group=g)
    d.define("fleet.scheduler.shed.queue.depth", T.INT, 8, I.MEDIUM,
             "queued-dispatch depth at which overload protection engages: "
             "BACKGROUND submissions shed (counted in "
             "fleet.scheduler.shed-total.background) at this depth, "
             "INTERACTIVE admissions 429 with Retry-After at twice it; "
             "URGENT is never shed.  A >=50% deadline-miss ratio over "
             "recent grants also counts as overload",
             in_range(lo=1), group=g)
    d.define("fleet.scheduler.brownout.after.s", T.DOUBLE, 20.0, I.LOW,
             "overload sustained this long switches BACKGROUND handling "
             "from shed to BROWNOUT: re-anneals run with the reduced "
             "candidate width below instead of being skipped, so proposal "
             "freshness degrades gracefully instead of going dark",
             in_range(lo=0.0), group=g)
    d.define("fleet.scheduler.brownout.candidate.factor", T.DOUBLE, 0.5, I.LOW,
             "candidate/restart width multiplier for browned-out "
             "background anneals (one quantized step per base config, so "
             "brownout costs at most one extra compiled program per "
             "bucket)", in_range(lo=0.05, hi=1.0), group=g)
    # --- fleet HA: lease-sharded ownership (fleet/leases.py) ---
    g = "fleet.ha"
    d.define("fleet.ha.enabled", T.BOOLEAN, False, I.HIGH,
             "lease-sharded cluster ownership: M instances jointly serve "
             "one fleet.clusters set, each cluster owned (executed "
             "against) by exactly the instance holding its lease — "
             "per-cluster leases with monotonically increasing fencing "
             "epochs live in <executor.journal.dir>/_leases, every "
             "journal append and cluster mutation is fenced on the "
             "epoch, and a lost lease steps the cluster down to "
             "read-only degraded mode.  Requires executor.journal.dir "
             "(the lease store shares the journal's durability).  Off "
             "(the default): single-instance and classic fleet "
             "deployments run byte-for-byte unchanged with no lease "
             "store on disk", group=g)
    d.define("fleet.ha.lease.ttl.s", T.DOUBLE, 30.0, I.MEDIUM,
             "lease lifetime granted per acquisition/renewal; a peer may "
             "take a cluster over once its lease has been expired for "
             "fleet.ha.skew.slack.s", in_range(lo=0.1), group=g)
    d.define("fleet.ha.renew.s", T.DOUBLE, 10.0, I.MEDIUM,
             "renewal-heartbeat cadence; must be well below the ttl so "
             "transient store hiccups don't cost the lease",
             in_range(lo=0.01), group=g)
    d.define("fleet.ha.skew.slack.s", T.DOUBLE, 2.0, I.MEDIUM,
             "tolerated per-instance clock error: a holder's fence "
             "self-revokes at deadline - slack (on ITS clock) while "
             "takeover is only granted after deadline + slack (on the "
             "acquirer's) — skew within the slack cannot create two "
             "writers", in_range(lo=0.0), group=g)
    d.define("fleet.ha.instance.id", T.STRING, None, I.MEDIUM,
             "this instance's lease holder id; unset derives "
             "<hostname>-<pid>.  Must be unique across the instances "
             "sharing one lease store", group=g)
    return d


def _monitor_defs() -> ConfigDef:
    """Reference config/constants/MonitorConfig.java."""
    d = ConfigDef()
    g = "monitor"
    d.define("num.partition.metrics.windows", T.INT, 5, I.HIGH,
             "windows kept for partition metrics", in_range(lo=1), group=g)
    d.define("partition.metrics.window.ms", T.LONG, 3_600_000, I.HIGH,
             "partition metric window span", in_range(lo=1), group=g)
    d.define("min.samples.per.partition.metrics.window", T.INT, 3, I.MEDIUM,
             "samples for a window to avoid extrapolation", in_range(lo=1), group=g)
    d.define("num.broker.metrics.windows", T.INT, 20, I.MEDIUM, "broker windows",
             in_range(lo=1), group=g)
    d.define("broker.metrics.window.ms", T.LONG, 300_000, I.MEDIUM, "broker window span",
             in_range(lo=1), group=g)
    d.define("min.samples.per.broker.metrics.window", T.INT, 1, I.LOW, "",
             in_range(lo=1), group=g)
    d.define("metric.sampling.interval.ms", T.LONG, 120_000, I.MEDIUM, "sampler cadence",
             in_range(lo=1), group=g)
    from cruise_control_tpu.monitor.reporter_sampler import (
        CruiseControlMetricsReporterSampler as _sampler,
    )

    d.define("monitor.excluded.topics.pattern", T.STRING,
             _sampler.DEFAULT_EXCLUDED,  # ONE source of truth with the sampler
             I.MEDIUM,
             "regex of topics invisible to the cluster model — the service's "
             "own metrics/sample-store topics must not be modeled as workload",
             group=g)
    d.define("num.metric.fetchers", T.INT, 1, I.MEDIUM,
             "parallel metric fetcher threads; each samples a disjoint "
             "partition set per round (reference num.metric.fetchers)",
             in_range(lo=1), group=g)
    d.define("min.valid.partition.ratio", T.DOUBLE, 0.95, I.MEDIUM,
             "monitored partition ratio gate", in_range(lo=0.0, hi=1.0), group=g)
    d.define("metric.sampler.class", T.CLASS,
             "cruise_control_tpu.testing.synthetic.SyntheticWorkloadSampler", I.HIGH,
             "MetricSampler plugin", group=g)
    d.define("cruise.control.metrics.topic", T.STRING, "__CruiseControlMetrics",
             I.MEDIUM,
             "metrics-reporter topic the sampler consumes (reference "
             "CruiseControlMetricsReporterConfig cruise.control.metrics.topic)",
             group=g)
    d.define("cruise.control.metrics.serde.format", T.STRING, "native", I.MEDIUM,
             "wire format of the metrics topic: 'native' (this framework's "
             "reporter) or 'reference' (records produced by the reference's "
             "in-broker CruiseControlMetricsReporter plugin — drop-in "
             "ingestion of broker-internal metrics)",
             lambda n, v: None if v in ("native", "reference") else
             (_ for _ in ()).throw(ConfigException(
                 f"{n}: {v!r} not in ('native', 'reference')")),
             group=g)
    d.define("sample.store.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.NoopSampleStore", I.MEDIUM,
             "SampleStore plugin", group=g)
    d.define("capacity.config.file", T.STRING, None, I.MEDIUM,
             "broker capacity JSON (reference config/capacity.json schema)", group=g)
    d.define("max.allowed.extrapolations.per.partition", T.INT, 5, I.LOW,
             "partitions extrapolating more windows than this are invalid "
             "(reference MonitorConfig:135)", in_range(lo=0), group=g)
    d.define("max.allowed.extrapolations.per.broker", T.INT, 5, I.LOW,
             "broker-window analog (reference MonitorConfig:179)",
             in_range(lo=0), group=g)
    d.define("skip.loading.samples", T.BOOLEAN, False, I.LOW,
             "do not replay the sample store on startup "
             "(reference MonitorConfig skip.loading.samples)", group=g)
    d.define("sampling.allow.cpu.capacity.estimation", T.BOOLEAN, True, I.LOW,
             "sampling may attribute CPU for brokers that reported no CPU "
             "metric (reference MonitorConfig:293-295)", group=g)
    d.define("use.linear.regression.model", T.BOOLEAN, False, I.LOW,
             "train the CPU regression continuously from broker samples and "
             "use it once bucket coverage suffices (reference "
             "MonitorConfig:302)", group=g)
    d.define("linear.regression.model.cpu.util.bucket.size", T.INT, 5, I.LOW,
             "CPU-util bucket width in percent points "
             "(reference MonitorConfig:268)", in_range(lo=1, hi=100), group=g)
    d.define("linear.regression.model.required.samples.per.bucket", T.INT, 100,
             I.LOW, "samples per bucket before it counts as covered "
             "(reference MonitorConfig:277)", in_range(lo=1), group=g)
    d.define("linear.regression.model.min.num.cpu.util.buckets", T.INT, 5,
             I.LOW, "distinct covered buckets required to train "
             "(reference MonitorConfig:286)", in_range(lo=1), group=g)
    d.define("leader.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.7, I.LOW,
             "static follower-CPU model coefficient "
             "(reference MonitorConfig:241)", in_range(lo=0.0), group=g)
    d.define("leader.network.outbound.weight.for.cpu.util", T.DOUBLE, 0.15,
             I.LOW, "(reference MonitorConfig:250)", in_range(lo=0.0), group=g)
    d.define("follower.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.15,
             I.LOW, "(reference MonitorConfig:259)", in_range(lo=0.0), group=g)
    d.define("broker.capacity.config.resolver.class", T.CLASS, None, I.MEDIUM,
             "custom BrokerCapacityConfigResolver; called with the "
             "CruiseControlConfig (reference "
             "config/BrokerCapacityConfigResolver.java); unset uses "
             "capacity.config.file / fixed defaults", group=g)
    d.define("metric.sampler.partition.assignor.class", T.CLASS, None, I.LOW,
             "custom MetricSamplerPartitionAssignor; called with no args "
             "(reference monitor/sampling/MetricSamplerPartitionAssignor.java)",
             group=g)
    d.define("topic.config.provider.class", T.CLASS, None, I.LOW,
             "custom TopicConfigProvider; called with (config, admin) "
             "(reference config/TopicConfigProvider.java) — "
             "KafkaTopicConfigProvider pulls the wire client off the admin",
             group=g)
    return d


def _executor_defs() -> ConfigDef:
    """Reference config/constants/ExecutorConfig.java."""
    d = ConfigDef()
    g = "executor"
    d.define("num.concurrent.partition.movements.per.broker", T.INT, 5, I.HIGH,
             "inter-broker move cap per broker", in_range(lo=1), group=g)
    d.define("num.concurrent.intra.broker.partition.movements", T.INT, 2, I.MEDIUM,
             "intra-broker move cap per broker", in_range(lo=1), group=g)
    d.define("num.concurrent.leader.movements", T.INT, 1000, I.MEDIUM,
             "cluster-wide leadership batch", in_range(lo=1), group=g)
    d.define("default.replication.throttle", T.LONG, None, I.MEDIUM,
             "bytes/s replication throttle during execution", group=g)
    d.define("execution.progress.check.interval.ms", T.LONG, 10_000, I.MEDIUM,
             "progress poll cadence", in_range(lo=1), group=g)
    d.define("task.execution.alerting.threshold.ms", T.LONG, 90_000, I.LOW,
             "slow-task alert threshold", in_range(lo=1), group=g)
    d.define("default.replica.movement.strategies", T.LIST,
             "BaseReplicaMovementStrategy", I.LOW,
             "ordered strategy chain applied to every execution unless the "
             "request overrides it", group=g)
    d.define("replica.movement.strategies", T.LIST,
             "PostponeUrpReplicaMovementStrategy,"
             "PrioritizeLargeReplicaMovementStrategy,"
             "PrioritizeSmallReplicaMovementStrategy,"
             "BaseReplicaMovementStrategy", I.LOW,
             "the pool of strategies requests may reference (reference "
             "ExecutorConfig replica.movement.strategies); dotted paths "
             "register custom classes", group=g)
    d.define("inter.broker.replica.movement.rate.alerting.threshold", T.DOUBLE,
             0.1, I.LOW, "MB/s floor; slower long-running inter-broker moves "
             "alert (reference ExecutorConfig:142)", in_range(lo=0.0), group=g)
    d.define("intra.broker.replica.movement.rate.alerting.threshold", T.DOUBLE,
             0.2, I.LOW, "MB/s floor for intra-broker (logdir) copies "
             "(reference ExecutorConfig:153)", in_range(lo=0.0), group=g)
    d.define("executor.notifier.class", T.CLASS, None, I.LOW,
             "object notified after every execution finishes; called with "
             "no args, must expose on_execution_finished(result, uuid) "
             "(reference ExecutorConfig executor.notifier.class)", group=g)
    d.define("max.num.cluster.movements", T.INT, 1250, I.MEDIUM,
             "global cap on concurrently ongoing movements (replica + "
             "leadership) cluster-wide, regardless of the per-broker caps "
             "(reference ExecutorConfig max.num.cluster.movements)",
             in_range(lo=1), group=g)
    d.define("leader.movement.timeout.ms", T.LONG, 180_000, I.LOW,
             "a leadership move not confirmed by the topology within this "
             "window is declared DEAD (reference ExecutorConfig "
             "leader.movement.timeout.ms)", in_range(lo=1), group=g)
    d.define("removal.history.retention.time.ms", T.LONG, 1_209_600_000, I.LOW,
             "how long removed brokers stay in the recently-removed set "
             "(default 14 days, reference ExecutorConfig "
             "removal.history.retention.time.ms)", in_range(lo=1), group=g)
    d.define("demotion.history.retention.time.ms", T.LONG, 1_209_600_000, I.LOW,
             "how long demoted brokers stay in the recently-demoted set",
             in_range(lo=1), group=g)
    # --- crash-safe execution (executor/journal.py) ---
    g = "executor.journal"
    d.define("executor.journal.dir", T.STRING, None, I.MEDIUM,
             "directory of the durable execution journal (append-only "
             "JSONL); a restarted executor replays it, reconciles any "
             "in-flight execution against the live cluster and resumes it "
             "(RECOVERING state).  Unset disables journaling — a crash "
             "mid-rebalance then strands in-flight reassignments and leaks "
             "throttles, exactly what the reference's persisted executor "
             "state prevents", group=g)
    d.define("executor.journal.fsync.batch.size", T.INT, 1, I.LOW,
             "journal records buffered before flush+fsync; 1 makes every "
             "record durable before the next cluster mutation (execution "
             "start, throttle and reaper records always fsync regardless)",
             in_range(lo=1), group=g)
    d.define("executor.journal.retention.count", T.INT, 64, I.LOW,
             "terminal (cleanly finished) journal archives kept per "
             "cluster; older ones are pruned during start-up "
             "reconciliation.  Unfinished journals awaiting recovery are "
             "NEVER pruned", in_range(lo=0), group=g)
    d.define("executor.journal.retention.hours", T.DOUBLE, 168.0, I.LOW,
             "terminal journal archives older than this are pruned during "
             "start-up reconciliation regardless of count (default 7 "
             "days)", in_range(lo=0.0), group=g)
    # --- stuck-move reaper ---
    g = "executor.reaper"
    d.define("executor.reaper.enabled", T.BOOLEAN, True, I.MEDIUM,
             "enforce the slow-task signal: a replica move whose progress "
             "watermark stalls past the timeout is cancelled (rolled back "
             "to the original replica set where the controller supports "
             "per-partition cancellation, else declared DEAD) and an "
             "EXECUTION_STUCK anomaly is raised — the rest of the batch "
             "keeps flowing", group=g)
    d.define("executor.reaper.stuck.timeout.s", T.DOUBLE, 900.0, I.MEDIUM,
             "seconds without observable progress (remaining-bytes "
             "decrease, or completion for admins that cannot report "
             "per-move bytes) before an in-flight move is reaped",
             in_range(lo=1.0), group=g)
    # --- load-aware adaptive concurrency (reference ConcurrencyAdjuster) ---
    g = "executor.adaptive"
    d.define("executor.adaptive.enabled", T.BOOLEAN, True, I.MEDIUM,
             "AIMD the per-broker and cluster-wide movement caps each "
             "progress tick: multiplicative backoff while the cluster "
             "shows stress (under-replicated partitions above the "
             "execution-start baseline, or task throughput collapse), "
             "additive recovery toward the configured caps once it clears",
             group=g)
    d.define("executor.adaptive.min", T.INT, 1, I.MEDIUM,
             "floor of the adaptive per-broker movement cap",
             in_range(lo=1), group=g)
    d.define("executor.adaptive.max", T.INT, 64, I.MEDIUM,
             "ceiling of the adaptive per-broker movement cap",
             in_range(lo=1), group=g)
    d.define("executor.adaptive.backoff.factor", T.DOUBLE, 0.5, I.LOW,
             "multiplicative decrease applied to the caps on a stressed "
             "tick", in_range(lo=0.05, hi=0.95), group=g)
    d.define("executor.adaptive.recover.step", T.INT, 1, I.LOW,
             "additive per-tick cap recovery once stress clears",
             in_range(lo=1), group=g)
    d.define("executor.adaptive.urp.slack", T.INT, 0, I.LOW,
             "under-replicated partitions above the execution-start "
             "baseline tolerated before backoff", in_range(lo=0), group=g)
    d.define("executor.adaptive.stall.ticks", T.INT, 16, I.LOW,
             "consecutive progress ticks without a single task completion "
             "(while moves are in flight) that count as cluster stress; "
             "0 disables the throughput signal", in_range(lo=0), group=g)
    return d


def _anomaly_defs() -> ConfigDef:
    """Reference config/constants/AnomalyDetectorConfig.java."""
    d = ConfigDef()
    g = "anomaly.detector"
    d.define("anomaly.detection.interval.ms", T.LONG, 300_000, I.MEDIUM,
             "detector cadence", in_range(lo=1), group=g)
    # per-detector cadence overrides; unset falls back to
    # anomaly.detection.interval.ms (reference AnomalyDetectorConfig:161-204)
    for det in ("goal.violation", "metric.anomaly", "disk.failure", "topic.anomaly"):
        d.define(f"{det}.detection.interval.ms", T.LONG, None, I.LOW,
                 f"{det} detector cadence override", group=g)
    d.define("broker.failure.detection.backoff.ms", T.LONG, 300_000, I.MEDIUM,
             "broker-failure detector polling backoff "
             "(reference AnomalyDetectorConfig:188)", in_range(lo=1), group=g)
    d.define("anomaly.detection.goals", T.LIST,
             "RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal", I.MEDIUM,
             "goals the violation detector watches "
             "(reference AnomalyDetectorConfig:103-107)", group=g)
    d.define("anomaly.detection.allow.capacity.estimation", T.BOOLEAN, True, I.LOW,
             "detector models may estimate missing broker capacities", group=g)
    d.define("self.healing.goals", T.LIST, "", I.MEDIUM,
             "goal chain used by self-healing fixes; empty means the default "
             "goals (reference AnomalyDetectorConfig:88)", group=g)
    d.define("self.healing.exclude.recently.demoted.brokers", T.BOOLEAN, True,
             I.MEDIUM, "self-healing never gives leadership to recently "
             "demoted brokers", group=g)
    d.define("self.healing.exclude.recently.removed.brokers", T.BOOLEAN, True,
             I.MEDIUM, "self-healing never moves replicas onto recently "
             "removed brokers", group=g)
    d.define("num.cached.recent.anomaly.states", T.INT, 10, I.LOW,
             "per-type anomaly history depth "
             "(reference AnomalyDetectorConfig:48)", in_range(lo=1, hi=100), group=g)
    d.define("fixable.failed.broker.count.threshold", T.INT, 10, I.MEDIUM,
             "self-healing refuses to remove more than this many failed "
             "brokers at once (reference AnomalyDetectorConfig:138)",
             in_range(lo=1), group=g)
    d.define("fixable.failed.broker.percentage.threshold", T.DOUBLE, 0.4, I.MEDIUM,
             "self-healing refuses to remove more than this fraction of the "
             "cluster (reference AnomalyDetectorConfig:147)",
             in_range(lo=0.0, hi=1.0), group=g)
    d.define("anomaly.notifier.class", T.CLASS,
             "cruise_control_tpu.detector.notifier.SelfHealingNotifier", I.MEDIUM,
             "AnomalyNotifier plugin", group=g)
    for t in ("broker.failure", "goal.violation", "disk.failure", "metric.anomaly",
              "topic.anomaly"):
        d.define(f"self.healing.{t}.enabled", T.BOOLEAN, False, I.MEDIUM,
                 f"auto-fix {t} anomalies", group=g)
    d.define("broker.failure.alert.threshold.ms", T.LONG, 900_000, I.MEDIUM, "", group=g)
    d.define("broker.failure.self.healing.threshold.ms", T.LONG, 1_800_000, I.MEDIUM,
             "", group=g)
    d.define("slow.broker.removal.enabled", T.BOOLEAN, False, I.LOW, "", group=g)
    d.define("slow.broker.history.percentile", T.DOUBLE, 90.0, I.LOW,
             "own-history percentile a slow broker must exceed",
             in_range(lo=0.0, hi=100.0), group=g)
    d.define("slow.broker.peer.comparison.ratio", T.DOUBLE, 3.0, I.LOW,
             "multiple of the peer median flagged as slow", in_range(lo=1.0), group=g)
    d.define("slow.broker.strike.removal.threshold", T.INT, 3, I.LOW,
             "consecutive detections before removal is proposed",
             in_range(lo=1), group=g)
    d.define("broker.failure.persisted.path", T.STRING, None, I.LOW,
             "file persisting broker-failure times across restarts "
             "(reference persists to a ZK node)", group=g)
    d.define("topic.anomaly.target.replication.factor", T.INT, 2, I.LOW, "", group=g)
    d.define("metric.anomaly.finder.class", T.CLASS, None, I.LOW,
             "custom metric-anomaly finder (reference AnomalyDetectorConfig "
             "metric.anomaly.finder.class); called with the "
             "CruiseControlConfig, must expose detect(evidence) -> "
             "Anomaly | None; unset uses the built-in SlowBrokerFinder",
             group=g)
    d.define("topic.anomaly.finder.class", T.CLASS, None, I.LOW,
             "custom topic-anomaly finder; called with (topology_provider, "
             "config), must expose detect() -> Anomaly | None; unset uses "
             "the built-in TopicReplicationFactorAnomalyFinder", group=g)
    d.define("partition.size.detection.enabled", T.BOOLEAN, False, I.LOW,
             "also run the PartitionSizeAnomalyFinder each topic-anomaly "
             "round (reference detector/PartitionSizeAnomalyFinder.java)",
             group=g)
    d.define("self.healing.partition.size.threshold.byte", T.LONG,
             500 * 1024 * 1024, I.LOW,
             "partitions larger than this are anomalous "
             "(reference PartitionSizeAnomalyFinder:49-50)",
             in_range(lo=1), group=g)
    d.define("topic.excluded.from.partition.size.check", T.STRING, "", I.LOW,
             "regex of topics the size check ignores "
             "(reference PartitionSizeAnomalyFinder:51)", group=g)
    # Slack alerting (reference detector/notifier/SlackSelfHealingNotifier.java)
    d.define("slack.self.healing.notifier.webhook", T.STRING, None, I.LOW,
             "Slack incoming-webhook URL; enables the Slack notifier", group=g)
    d.define("slack.self.healing.notifier.channel", T.STRING, None, I.LOW,
             "override channel for alerts", group=g)
    d.define("slack.self.healing.notifier.user", T.STRING, "cruise-control-tpu",
             I.LOW, "sender username", group=g)
    return d


def _webserver_defs() -> ConfigDef:
    """Reference config/constants/WebServerConfig.java + UserTaskManagerConfig."""
    d = ConfigDef()
    g = "webserver"
    d.define("webserver.http.port", T.INT, 9090, I.HIGH, "REST port",
             in_range(lo=0, hi=65535), group=g)
    d.define("webserver.http.address", T.STRING, "127.0.0.1", I.MEDIUM, "bind address", group=g)
    d.define("webserver.api.urlprefix", T.STRING, "/kafkacruisecontrol", I.LOW, "", group=g)
    d.define("webserver.session.maxExpiryPeriodMs", T.LONG, 3_600_000, I.LOW, "", group=g)
    d.define("webserver.session.path", T.STRING, "/", I.LOW,
             "session cookie Path attribute (reference webserver.session.path)",
             group=g)
    d.define("max.cached.completed.user.tasks", T.INT, 100, I.LOW, "", group=g)
    d.define("completed.user.task.retention.time.ms", T.LONG, 86_400_000, I.LOW, "", group=g)
    d.define("max.active.user.tasks", T.INT, 25, I.LOW,
             "cap on concurrently Active async user tasks; beyond it new "
             "operations are rejected (reference WebServerConfig "
             "max.active.user.tasks)", in_range(lo=1), group=g)
    # per-category completed-task caches (reference UserTaskManagerConfig:
    # unset falls back to the general cap/retention above)
    for cat in ("kafka.monitor", "cruise.control.monitor",
                "kafka.admin", "cruise.control.admin"):
        d.define(f"max.cached.completed.{cat}.user.tasks", T.INT, None, I.LOW,
                 f"completed-task cache size for {cat} endpoints", group=g)
        d.define(f"completed.{cat}.user.task.retention.time.ms", T.LONG, None,
                 I.LOW, f"completed-task retention for {cat} endpoints", group=g)
    d.define("request.reason.required", T.BOOLEAN, False, I.LOW,
             "POST requests must carry a reason parameter "
             "(reference WebServerConfig request.reason.required)", group=g)
    d.define("two.step.purgatory.max.requests", T.INT, 25, I.LOW,
             "cap on requests parked for review "
             "(reference WebServerConfig:149)", in_range(lo=1), group=g)
    d.define("two.step.purgatory.retention.time.ms", T.LONG, 1_209_600_000,
             I.LOW, "how long parked requests stay reviewable "
             "(reference WebServerConfig:141, default 336h)",
             in_range(lo=1), group=g)
    # CORS (reference WebServerConfig:42-70)
    d.define("webserver.http.cors.enabled", T.BOOLEAN, False, I.LOW,
             "emit CORS headers + answer OPTIONS preflight", group=g)
    d.define("webserver.http.cors.origin", T.STRING, "*", I.LOW,
             "Access-Control-Allow-Origin value", group=g)
    d.define("webserver.http.cors.allowmethods", T.STRING, "OPTIONS, GET, POST",
             I.LOW, "Access-Control-Allow-Methods value", group=g)
    d.define("webserver.http.cors.exposeheaders", T.STRING, "User-Task-ID",
             I.LOW, "Access-Control-Expose-Headers value", group=g)
    # NCSA access log (reference WebServerConfig:119-134; Jetty NCSARequestLog)
    d.define("webserver.accesslog.enabled", T.BOOLEAN, False, I.LOW,
             "write an NCSA-format access log (reference defaults true; off "
             "here so embedded instances stay hermetic)", group=g)
    d.define("webserver.accesslog.path", T.STRING, "access.log", I.LOW,
             "access log file; rolled daily", group=g)
    d.define("webserver.accesslog.retention.days", T.INT, 7, I.LOW,
             "rolled access logs older than this are deleted",
             in_range(lo=1), group=g)
    d.define("webserver.security.enable", T.BOOLEAN, False, I.MEDIUM, "", group=g)
    d.define("webserver.security.provider", T.CLASS, None, I.MEDIUM,
             "custom SecurityProvider (reference WebServerConfig:164); "
             "called with the CruiseControlConfig, must expose "
             "authenticate(headers) and authorize(role, method, endpoint); "
             "unset selects JWT/basic from the other keys", group=g)
    # static UI serving (reference WebServerConfig:84-91 serves
    # cruise-control-ui from disk)
    d.define("webserver.ui.diskpath", T.STRING, None, I.LOW,
             "directory of UI static files; unset disables UI serving",
             group=g)
    d.define("webserver.ui.urlprefix", T.STRING, "/ui", I.LOW,
             "URL prefix the UI is served under", group=g)
    d.define("basic.auth.credentials.file", T.STRING, None, I.MEDIUM,
             "htpasswd-style user:password[:role] lines", group=g)
    d.define("webserver.auth.credentials.file", T.STRING, None, I.MEDIUM,
             "reference name for basic.auth.credentials.file; takes "
             "precedence when both are set (WebServerConfig:179)", group=g)
    d.define("jwt.secret.key", T.STRING, None, I.MEDIUM,
             "enables HS256 bearer-token auth when set", group=g)
    d.define("jwt.authentication.certificate.location", T.STRING, None, I.MEDIUM,
             "PEM public key or X.509 certificate enabling RS256 bearer-token "
             "auth (reference servlet/security/jwt/JwtAuthenticator)", group=g)
    d.define("jwt.auth.certificate.location", T.STRING, None, I.MEDIUM,
             "reference name for jwt.authentication.certificate.location; "
             "takes precedence when both are set", group=g)
    d.define("jwt.cookie.name", T.STRING, None, I.LOW,
             "also accept the JWT from this cookie (reference "
             "WebServerConfig:243; Authorization header still wins)", group=g)
    d.define("jwt.expected.audiences", T.LIST, "", I.LOW,
             "token aud claim must intersect this list when set "
             "(reference JwtAuthenticator audience check)", group=g)
    d.define("jwt.authentication.provider.url", T.STRING, None, I.LOW,
             "unauthenticated browser requests are redirected (302) here; "
             "{redirect} in the URL is replaced with the original request "
             "(reference WebServerConfig:233)", group=g)
    d.define("two.step.verification.enabled", T.BOOLEAN, False, I.MEDIUM,
             "POSTs park in the review purgatory first", group=g)
    # TLS for the REST listener (reference KafkaCruiseControlApp.java:100-120
    # SSL connector; PEM files instead of JKS keystores)
    d.define("webserver.ssl.enable", T.BOOLEAN, False, I.MEDIUM,
             "serve the REST API over TLS", group=g)
    d.define("webserver.ssl.certificate.location", T.STRING, None, I.MEDIUM,
             "PEM certificate chain file", group=g)
    d.define("webserver.ssl.key.location", T.STRING, None, I.MEDIUM,
             "PEM private-key file (defaults to the certificate file)", group=g)
    d.define("webserver.ssl.key.password", T.STRING, None, I.LOW,
             "private-key passphrase", group=g)
    d.define("webserver.ssl.protocol", T.STRING, "TLS", I.LOW,
             "minimum TLS version for the listener: TLS (library default), "
             "TLSv1.2 or TLSv1.3 (reference WebServerConfig:226)", group=g)
    # SASL toward the Kafka cluster (reference rides JAAS,
    # config/cruise_control_jaas.conf_template; the wire client speaks
    # SaslHandshake + SCRAM itself)
    d.define("sasl.mechanism", T.STRING, None, I.MEDIUM,
             "PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512; unset disables SASL",
             group=g)
    d.define("sasl.username", T.STRING, None, I.MEDIUM,
             "SASL username toward the Kafka cluster", group=g)
    d.define("sasl.password", T.STRING, None, I.MEDIUM,
             "SASL password (prefer sasl.password.file in production)", group=g)
    d.define("sasl.password.file", T.STRING, None, I.MEDIUM,
             "file holding the SASL password (overrides sasl.password)",
             group=g)
    # per-endpoint parameter/request class override maps (reference
    # config/constants/CruiseControlParametersConfig.java:1 +
    # CruiseControlRequestConfig.java:1): every endpoint's parameter
    # declaration and request execution are pluggable
    from cruise_control_tpu.config.endpoints import ALL_ENDPOINTS, reference_key_name

    for ep in sorted(ALL_ENDPOINTS):
        d.define(f"{ep}.parameters.class", T.CLASS, None, I.LOW,
                 f"dotted path of a custom parameters class for /{ep}; "
                 "called with (endpoint, builtin_parameters), must expose "
                 ".parse(raw_query_dict)", group=g)
        d.define(f"{ep}.request.class", T.CLASS, None, I.LOW,
                 f"dotted path of a custom request handler for /{ep}; "
                 "called with (app, endpoint, params) -> (status, payload)",
                 group=g)
        ref = reference_key_name(ep)
        if ref != ep:
            # accept the reference's dotted spelling too, so an existing
            # cruisecontrol.properties keeps working unmodified
            d.define(f"{ref}.parameters.class", T.CLASS, None, I.LOW,
                     f"reference spelling of {ep}.parameters.class", group=g)
            d.define(f"{ref}.request.class", T.CLASS, None, I.LOW,
                     f"reference spelling of {ep}.request.class", group=g)
    return d


def cruise_control_config_def() -> ConfigDef:
    return (
        _analyzer_defs()
        .merge(_controller_defs())
        .merge(_observability_defs())
        .merge(_fleet_defs())
        .merge(_planner_defs())
        .merge(_monitor_defs())
        .merge(_executor_defs())
        .merge(_anomaly_defs())
        .merge(_webserver_defs())
    )


class CruiseControlConfig(AbstractConfig):
    """Reference config/KafkaCruiseControlConfig.java:38 + goal-name sanity
    checks (:106-120)."""

    def __init__(self, props: dict[str, Any] | None = None):
        #: raw operator props, kept for fleet per-cluster derivation
        #: (cluster_config overlays fleet.<id>.* keys over this base)
        self._raw_props = dict(props or {})
        super().__init__(cruise_control_config_def(), props or {})
        self._sanity_check_goals()
        self._sanity_check_fleet_keys()

    # ------------------------------------------------------------------
    # fleet (fleet/manager.py)
    # ------------------------------------------------------------------

    def fleet_cluster_ids(self) -> list[str]:
        return self.get("fleet.clusters")

    def _sanity_check_fleet_keys(self):
        """Every non-builtin `fleet.*` key must be a `fleet.<id>.<key>`
        override whose <id> is in fleet.clusters — unknown keys are
        tolerated config-wide, but a typo'd cluster prefix
        (fleet.eastt.bootstrap.servers) would otherwise silently fold
        nothing and the fleet would run against the base settings."""
        ids = set(self.get("fleet.clusters"))
        defined = self.definition.keys()
        for k in self._raw_props:
            if not k.startswith("fleet.") or k in defined:
                continue
            cid, _, rest = k[len("fleet."):].partition(".")
            if cid not in ids or not rest:
                raise ConfigException(
                    f"{k!r} is not a per-cluster override: "
                    f"{cid!r} is not in fleet.clusters ({sorted(ids)})"
                )

    #: keys the SHARED half of a fleet deployment consumes — the one
    #: AnalyzerCore (goal chain, balancing constraint, optimizer/engine
    #: cache, device supervisor, planner, tracer) and the one webserver /
    #: user-task purgatory, all built from the BASE config.  A
    #: fleet.<id>.<key> override of these would validate, fold into the
    #: cluster's facade config, and then be silently ignored — reject it
    #: at config time instead of misleading the operator.
    _FLEET_SHARED_KEY_PREFIXES = (
        "default.goals", "goal.balancedness.", "planner.", "tpu.", "trace.",
        "webserver.", "jwt.", "basic.auth.", "max.active.user.tasks",
        "max.cached.completed", "completed.", "two.step.",
        "request.reason.required", "metrics.prometheus.",
        "max.replicas.per.broker", "goal.violation.distribution.threshold",
    )
    _FLEET_SHARED_KEY_SUFFIXES = (  # BalancingConstraint inputs
        ".balance.threshold", ".capacity.threshold",
        ".low.utilization.threshold",
    )
    #: shared-prefixed keys that ARE legitimately per-cluster: the device
    #: scheduler is one shared object, but each cluster's freshness SLO
    #: is a per-request deadline input its facade/controller reads
    _FLEET_SHARED_KEY_EXEMPT = ("fleet.scheduler.freshness.slo.s",)

    def cluster_config(self, cluster_id: str) -> "CruiseControlConfig":
        """Per-cluster config: the base props with every `fleet.<id>.<key>`
        override folded onto its bare `<key>`.  All `fleet.*` keys are
        stripped from the derived config — a cluster-scoped config must
        never look like a fleet of its own — EXCEPT the builtin
        fleet.scheduler.*/fleet.tenant.* knobs, which carry no
        fleet-shaped meaning and which per-cluster facades read (the
        freshness SLO).  Overrides of shared-core / webserver keys are
        rejected (see _FLEET_SHARED_KEY_PREFIXES); of the scheduler/
        tenant knobs only the per-cluster freshness SLO
        (_FLEET_SHARED_KEY_EXEMPT) is overridable — the rest configure
        the ONE instance-level scheduler/purgatory built from the base."""
        if cluster_id not in self.get("fleet.clusters"):
            raise ConfigException(
                f"unknown fleet cluster {cluster_id!r}; "
                f"fleet.clusters={self.get('fleet.clusters')}"
            )
        prefix = f"fleet.{cluster_id}."
        base = {
            k: v for k, v in self._raw_props.items()
            if not k.startswith("fleet.")
            or k.startswith(("fleet.scheduler.", "fleet.tenant."))
        }
        overrides = {
            k[len(prefix):]: v
            for k, v in self._raw_props.items()
            if k.startswith(prefix)
        }
        shared = sorted(
            k for k in overrides
            if (
                k.startswith(self._FLEET_SHARED_KEY_PREFIXES)
                or k.endswith(self._FLEET_SHARED_KEY_SUFFIXES)
                # the scheduler and the admission/Retry-After knobs are
                # instance-level objects read from the BASE config — a
                # per-cluster override would fold and then be silently
                # ignored, except the explicitly per-cluster SLO
                or (
                    k.startswith(("fleet.scheduler.", "fleet.tenant."))
                    and k not in self._FLEET_SHARED_KEY_EXEMPT
                )
            )
        )
        if shared:
            raise ConfigException(
                f"fleet.{cluster_id}.* cannot override shared keys {shared}: "
                "the fleet builds ONE goal chain / constraint / optimizer / "
                "supervisor / planner / tracer / webserver from the base "
                "config, so a per-cluster value would be silently ignored — "
                "set these on the base config instead"
            )
        return CruiseControlConfig({**base, **overrides})

    def _sanity_check_goals(self):
        """Reference KafkaCruiseControlConfig.java:106-120 validates every
        configured goal-name list against the registry."""
        from cruise_control_tpu.analyzer.goals import GOALS_BY_NAME

        for key in ("default.goals", "hard.goals", "anomaly.detection.goals",
                    "self.healing.goals", "intra.broker.goals"):
            names = self.get(key)
            unknown = [g for g in names if g not in GOALS_BY_NAME]
            if unknown:
                raise ConfigException(f"unknown goals in {key}: {unknown}")
        if not self.get("default.goals"):
            raise ConfigException("default.goals must not be empty")

    def balancing_constraint(self) -> BalancingConstraint:
        g = self.get
        return BalancingConstraint(
            balance_threshold=(
                g("cpu.balance.threshold"),
                g("network.inbound.balance.threshold"),
                g("network.outbound.balance.threshold"),
                g("disk.balance.threshold"),
            ),
            capacity_threshold=(
                g("cpu.capacity.threshold"),
                g("network.inbound.capacity.threshold"),
                g("network.outbound.capacity.threshold"),
                g("disk.capacity.threshold"),
            ),
            low_utilization_threshold=(
                g("cpu.low.utilization.threshold"),
                g("network.inbound.low.utilization.threshold"),
                g("network.outbound.low.utilization.threshold"),
                g("disk.low.utilization.threshold"),
            ),
            replica_count_balance_threshold=g("replica.count.balance.threshold"),
            leader_replica_count_balance_threshold=g("leader.replica.count.balance.threshold"),
            topic_replica_count_balance_threshold=g("topic.replica.count.balance.threshold"),
            max_replicas_per_broker=g("max.replicas.per.broker"),
            goal_violation_distribution_threshold_multiplier=g(
                "goal.violation.distribution.threshold.multiplier"
            ),
        )

    def optimizer_config(self):
        from cruise_control_tpu.analyzer.engine import OptimizerConfig

        g = self.get
        return OptimizerConfig(
            num_candidates=g("tpu.num.candidates"),
            leadership_candidates=g("tpu.leadership.candidates"),
            swap_candidates=g("tpu.swap.candidates"),
            steps_per_round=g("tpu.steps.per.round"),
            num_rounds=g("tpu.num.rounds"),
            init_temperature_scale=g("tpu.init.temperature.scale"),
            temperature_decay=g("tpu.temperature.decay"),
            replica_move_cost=g("tpu.replica.move.cost"),
            leadership_move_cost=g("tpu.leadership.move.cost"),
            importance_fraction=g("tpu.importance.fraction"),
            diagnostics=g("analyzer.diagnostics.enabled"),
            score_dtype=g("analyzer.precision.score.dtype"),
        )

    def compile_cache_dir(self) -> str | None:
        """Persistent XLA compile-cache directory: the preferred
        tpu.compile.cache.dir when SET (an explicitly empty value
        disables the cache — it must not fall through to the legacy
        key's non-empty default), else the legacy
        tpu.compilation.cache.dir (empty/None disables)."""
        v = self.get("tpu.compile.cache.dir")
        if v is not None:
            return v or None
        return self.get("tpu.compilation.cache.dir") or None

    def prewarm_manifest_dir(self) -> str | None:
        """Directory of the boot-prewarm manifest + AOT artifacts, or
        None when prewarm is off.  Unset derives the 'prewarm'
        subdirectory INSIDE the persistent compile cache — the same
        mount, so they share one durability story (a sibling of the
        cache dir could land outside the operator's volume when the
        volume is mounted exactly at the cache path); the cache's boot
        inventory scan prunes the subdirectory so manifest/artifact
        writes never count as XLA cache entries.  An explicitly empty
        value disables, like compile_cache_dir."""
        import os

        if not self.get("tpu.prewarm.enabled"):
            return None
        v = self.get("tpu.prewarm.manifest.dir")
        if v is not None:
            return v or None
        cache = self.compile_cache_dir()
        if not cache:
            return None
        return os.path.join(os.path.expanduser(cache), "prewarm")

    def blackbox_dir(self) -> str | None:
        """Directory of the black-box dispatch spool (common/blackbox.py),
        or None when disabled / no durable directory exists.  Unset
        derives '_blackbox' inside executor.journal.dir — the spool must
        survive exactly the crashes the journal survives, so they share
        one mount — falling back to a 'blackbox' subdirectory of the
        persistent compile cache.  An explicitly empty value disables,
        like compile_cache_dir."""
        import os

        if not self.get("blackbox.enabled"):
            return None
        v = self.get("blackbox.dir")
        if v is not None:
            return v or None
        journal = self.get("executor.journal.dir")
        if journal:
            return os.path.join(os.path.expanduser(journal), "_blackbox")
        cache = self.compile_cache_dir()
        if not cache:
            return None
        return os.path.join(os.path.expanduser(cache), "blackbox")

    def ledger_dir(self) -> str | None:
        """Directory of the decision ledger (analyzer/ledger.py), or None
        when disabled / no durable directory exists.  Unset derives
        '_ledger' inside executor.journal.dir — decision records must
        survive exactly the crashes the execution journal survives, so
        they share one mount.  An explicitly empty value disables, like
        blackbox_dir."""
        import os

        if not self.get("analyzer.ledger.enabled"):
            return None
        v = self.get("analyzer.ledger.dir")
        if v is not None:
            return v or None
        journal = self.get("executor.journal.dir")
        if journal:
            return os.path.join(os.path.expanduser(journal), "_ledger")
        return None

    def parallel_mode(self) -> str:
        return self.get("tpu.parallel.mode")

    def mesh_max_devices(self) -> int:
        return self.get("tpu.mesh.max.devices")

    def mesh_model_shard_min_partitions(self) -> int:
        return self.get("tpu.mesh.model.shard.min.partitions")

    def mesh_ft_controller(self, *, sensors=None):
        """MeshFtController from the tpu.mesh.ft.* keys (parallel/ft.py);
        None in single-device mode — there is no mesh to degrade.  The
        per-width breakers re-probe on the supervisor's probe cadence."""
        if self.parallel_mode() == "single":
            return None
        from cruise_control_tpu.parallel.ft import MeshFtController

        return MeshFtController(
            enabled=self.get("tpu.mesh.ft.enabled"),
            checkpoint_every_slices=self.get(
                "tpu.mesh.ft.checkpoint.every.slices"
            ),
            probe_interval_s=self.get("tpu.supervisor.probe.interval.s"),
            sensors=sensors,
        )

    def device_supervisor(self, *, sensors=None, probe=None, tracer=None):
        """DeviceSupervisor from the tpu.supervisor.* keys; None when
        supervision is disabled (offline tools, parity benchmarks)."""
        if not self.get("tpu.supervisor.enabled"):
            return None
        from cruise_control_tpu.common.device_watchdog import DeviceSupervisor

        return DeviceSupervisor(
            op_timeout_s=self.get("tpu.supervisor.op.timeout.s"),
            max_retries=self.get("tpu.supervisor.max.retries"),
            retry_backoff_s=self.get("tpu.supervisor.retry.backoff.ms") / 1000.0,
            retry_backoff_cap_s=self.get("tpu.supervisor.retry.backoff.max.ms")
            / 1000.0,
            breaker_failure_threshold=self.get(
                "tpu.supervisor.breaker.failure.threshold"
            ),
            probe_interval_s=self.get("tpu.supervisor.probe.interval.s"),
            probe_timeout_s=self.get("tpu.supervisor.probe.timeout.s"),
            sensors=sensors,
            probe=probe,
            tracer=tracer,
        )

    def tracer(self):
        """Flight-recorder Tracer from the trace.* keys (one per service;
        the facade shares it across every subsystem)."""
        from cruise_control_tpu.common.trace import Tracer

        return Tracer(
            enabled=self.get("trace.enabled"),
            retention_per_component=self.get(
                "trace.retention.spans.per.component"
            ),
            max_events_per_span=self.get("trace.max.events.per.span"),
        )

    def shape_bucket_policy(self):
        from cruise_control_tpu.models.state import ShapeBucketPolicy

        return ShapeBucketPolicy(
            enabled=self.get("tpu.shape.bucket.enabled"),
            growth=self.get("tpu.shape.bucket.growth"),
            floor=self.get("tpu.shape.bucket.floor"),
        )


def load_properties(path: str) -> dict[str, str]:
    """Java-style .properties loader (reference reads cruisecontrol.properties)."""
    props: dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(("#", "!")):
                continue
            if "=" in line:
                k, _, v = line.partition("=")
                props[k.strip()] = v.strip()
    return props
