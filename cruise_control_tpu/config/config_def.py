"""Typed configuration kernel.

Reference: cruise-control-core common/config/ConfigDef.java (a copy of
Kafka's typed ConfigDef: chained define() with type/default/validator/
importance/doc), AbstractConfig, CruiseControlConfigurable (configure
callback on instantiated plugins).
"""

from __future__ import annotations

import dataclasses
import enum
import importlib
from typing import Any, Callable


class ConfigType(enum.Enum):
    BOOLEAN = "boolean"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    STRING = "string"
    LIST = "list"  # comma-separated string -> list[str]
    CLASS = "class"  # dotted path -> class object


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


class ConfigException(ValueError):
    pass


NO_DEFAULT = object()


@dataclasses.dataclass
class ConfigKey:
    name: str
    type: ConfigType
    default: Any
    importance: Importance
    doc: str
    validator: Callable[[str, Any], None] | None = None
    group: str = ""

    @property
    def has_default(self) -> bool:
        return self.default is not NO_DEFAULT


def in_range(lo=None, hi=None):
    """Reference ConfigDef.Range.between / atLeast."""

    def check(name, v):
        if lo is not None and v < lo:
            raise ConfigException(f"{name}={v} below minimum {lo}")
        if hi is not None and v > hi:
            raise ConfigException(f"{name}={v} above maximum {hi}")

    return check


def in_values(*allowed):
    """Reference ConfigDef.ValidString.in."""

    def check(name, v):
        if v not in allowed:
            raise ConfigException(f"{name}={v!r} not in {allowed}")

    return check


class ConfigDef:
    def __init__(self):
        self._keys: dict[str, ConfigKey] = {}

    def define(
        self,
        name: str,
        type: ConfigType,
        default: Any = NO_DEFAULT,
        importance: Importance = Importance.MEDIUM,
        doc: str = "",
        validator: Callable[[str, Any], None] | None = None,
        group: str = "",
    ) -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"config {name} already defined")
        self._keys[name] = ConfigKey(name, type, default, importance, doc, validator, group)
        return self

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for k in other._keys.values():
            if k.name in self._keys:
                raise ConfigException(f"config {k.name} defined in two groups")
            self._keys[k.name] = k
        return self

    def keys(self) -> dict[str, ConfigKey]:
        return dict(self._keys)

    def parse(self, props: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        unknown = set(props) - set(self._keys)
        # unknown keys are tolerated (reference logs them) but kept raw
        for name, key in self._keys.items():
            if name in props:
                value = _coerce(name, props[name], key.type)
            elif key.has_default:
                value = _coerce(name, key.default, key.type) if key.default is not None else None
            else:
                raise ConfigException(f"missing required config {name}")
            if key.validator is not None and value is not None:
                key.validator(name, value)
            out[name] = value
        for name in unknown:
            out[name] = props[name]
        return out

    def doc_table(self) -> list[dict]:
        """Configuration reference documentation rows."""
        return [
            {
                "name": k.name,
                "type": k.type.value,
                "default": None if not k.has_default else k.default,
                "importance": k.importance.value,
                "group": k.group,
                "doc": k.doc,
            }
            for k in sorted(self._keys.values(), key=lambda k: (k.group, k.name))
        ]


def _coerce(name: str, value: Any, t: ConfigType) -> Any:
    try:
        if t == ConfigType.BOOLEAN:
            if isinstance(value, bool):
                return value
            return str(value).strip().lower() in ("true", "1", "yes")
        if t in (ConfigType.INT, ConfigType.LONG):
            return int(value)
        if t == ConfigType.DOUBLE:
            return float(value)
        if t == ConfigType.STRING:
            return None if value is None else str(value)
        if t == ConfigType.LIST:
            if isinstance(value, (list, tuple)):
                return [str(v) for v in value]
            if value is None or value == "":
                return []
            return [s.strip() for s in str(value).split(",") if s.strip()]
        if t == ConfigType.CLASS:
            if value is None or isinstance(value, type):
                return value
            mod, _, cls = str(value).rpartition(".")
            return getattr(importlib.import_module(mod), cls)
    except ConfigException:
        raise
    except Exception as e:  # noqa: BLE001
        raise ConfigException(f"cannot parse {name}={value!r} as {t.value}: {e}") from e
    raise ConfigException(f"unknown config type {t}")


class AbstractConfig:
    """Reference common/config/AbstractConfig.java + getConfiguredInstance
    (config/KafkaCruiseControlConfig.java:63-104): plugins are instantiated
    from CLASS configs and, if they expose `configure(config)`, called back
    with the full config."""

    def __init__(self, definition: ConfigDef, props: dict[str, Any]):
        self.definition = definition
        self._values = definition.parse(props)

    def get(self, name: str) -> Any:
        if name not in self._values:
            raise ConfigException(f"unknown config {name}")
        return self._values[name]

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def get_configured_instance(self, name: str, expected_type: type | None = None, **kwargs):
        cls = self.get(name)
        if cls is None:
            return None
        obj = cls(**kwargs)
        if expected_type is not None and not isinstance(obj, expected_type):
            raise ConfigException(f"{name}={cls} is not a {expected_type}")
        configure = getattr(obj, "configure", None)
        if callable(configure):
            configure(self)
        return obj

    def values(self) -> dict[str, Any]:
        return dict(self._values)
