"""cruise_control_tpu — a TPU-native cluster-rebalancing framework.

A ground-up, JAX/XLA-first rebuild of the capability surface of LinkedIn
Cruise Control (reference: /root/reference): resource-load monitoring with
windowed metric aggregation, an array-encoded workload cluster model,
multi-goal rebalance proposal generation, throttled proposal execution with
progress tracking, anomaly detection and self-healing, a REST API with async
user tasks, and a CLI client.

Unlike the reference's single-threaded goal-by-goal greedy search
(reference: analyzer/GoalOptimizer.java), the analyzer core here is a
batched combinatorial optimizer: cluster state is flattened into device
arrays and thousands of candidate replica-move plans are scored in parallel
with vmap'd goal functions under a simulated-annealing/beam acceptance loop,
sharded across TPU devices with jax.sharding.
"""

__version__ = "0.1.0"

from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES

__all__ = ["Resource", "NUM_RESOURCES", "__version__"]
