"""Cluster administration SPI + simulated backend.

Reference boundary: the Scala ZK bridge (ExecutorUtils.scala:31
executeReplicaReassignmentTasks / :95 executePreferredLeaderElection /
:103 partitionsBeingReassigned) + executor/ExecutorAdminUtils.java
(alterReplicaLogDirs, describe logdirs).  Modern Kafka does reassignment
through the AdminClient API, so the SPI is shaped like that — a real
implementation wraps an AdminClient; the simulated one mutates a
StaticMetadataProvider topology with throttle-limited progress, playing
the role of the reference's embedded-cluster test harness
(CCKafkaIntegrationTestHarness).
"""

from __future__ import annotations

import dataclasses
from typing import Protocol

from cruise_control_tpu.monitor.topology import (
    ClusterTopology,
    PartitionInfo,
    StaticMetadataProvider,
)


@dataclasses.dataclass(frozen=True)
class ReassignmentSpec:
    topic: str
    partition: int
    new_replicas: tuple[int, ...]  # target replica list, leader candidate first
    data_to_move: float = 0.0


@dataclasses.dataclass(frozen=True)
class LeadershipSpec:
    topic: str
    partition: int
    preferred_leader: int


class ClusterAdmin(Protocol):
    """What the executor needs from the cluster."""

    def reassign_partitions(self, specs: list[ReassignmentSpec]) -> None:
        ...

    def in_progress_reassignments(self) -> set[tuple[str, int]]:
        ...

    def cancel_reassignments(self) -> None:
        ...

    def elect_leaders(self, specs: list[LeadershipSpec]) -> None:
        ...

    def alter_replica_logdirs(self, moves: list[tuple[str, int, int, int]]) -> None:
        """(topic, partition, broker, target_disk) intra-broker moves."""
        ...

    def in_progress_logdir_moves(self) -> set[tuple[str, int, int]]:
        """(topic, partition, broker) intra-broker copies still in flight
        (reference ExecutorAdminUtils DescribeLogDirs future replicas)."""
        ...

    def set_replication_throttle(self, rate_bytes_per_s: float, topics: set[str]) -> None:
        ...

    def clear_replication_throttle(self) -> None:
        ...

    def topology(self) -> ClusterTopology:
        ...

    # Optional capabilities the executor probes with hasattr():
    #
    #   reassignment_remaining_bytes() -> dict[(topic, part), float]
    #       per-reassignment bytes still to copy — feeds the stuck-move
    #       reaper's progress watermark (a KIP-455 admin derives this from
    #       replica log-end offsets vs the leader)
    #   cancel_partition_reassignments(keys: list[(topic, part)]) -> None
    #       cancel INDIVIDUAL reassignments, rolling each partition back to
    #       its original replica set (KIP-455 supports per-partition
    #       cancellation; cancel_reassignments above nukes everything)


#: ClusterAdmin methods that MUTATE the cluster — the full fencing
#: surface (fleet HA): a deposed lease holder must not be able to touch
#: the cluster through any of these
_MUTATING_ADMIN_OPS = frozenset({
    "reassign_partitions",
    "cancel_reassignments",
    "cancel_partition_reassignments",
    "elect_leaders",
    "alter_replica_logdirs",
    "set_replication_throttle",
    "clear_replication_throttle",
})


class FencedClusterAdmin:
    """ClusterAdmin decorator stamping the lease fence onto every cluster
    MUTATION (fleet/leases.py): each call in `_MUTATING_ADMIN_OPS` first
    runs `fence.check()` — a stale/absent lease epoch raises `FencedError`
    before anything reaches the cluster, so a zombie instance whose lease
    was taken over can neither submit, cancel, elect, move logdirs nor
    touch throttles.  Reads (topology, in-progress listings, watermarks)
    pass through unfenced — the degraded read-only mode keeps serving
    them — and optional capabilities (`tick`, `reassignment_remaining_
    bytes`, `logdir_of`, ...) delegate transparently so `hasattr` probes
    see exactly the wrapped admin's surface."""

    def __init__(self, admin: "ClusterAdmin", fence):
        self._admin = admin
        self._fence = fence

    def __getattr__(self, name: str):
        attr = getattr(self._admin, name)
        if name in _MUTATING_ADMIN_OPS and callable(attr):
            fence = self._fence

            def fenced(*args, __attr=attr, __name=name, **kwargs):
                fence.check(op=f"admin.{__name}")
                return __attr(*args, **kwargs)

            return fenced
        return attr


@dataclasses.dataclass
class _Inflight:
    spec: ReassignmentSpec
    remaining_bytes: float


class SimulatedClusterAdmin:
    """Deterministic simulated cluster: reassignments progress by
    `tick(seconds)` at min(throttle, link_rate) per partition."""

    def __init__(
        self,
        metadata: StaticMetadataProvider,
        *,
        link_rate_bytes_per_s: float = 50_000.0,
        fail_partitions: set[tuple[str, int]] | None = None,
        drop_partitions: set[tuple[str, int]] | None = None,
        intra_move_bytes: float = 0.0,
    ):
        self.metadata = metadata
        self.link_rate = link_rate_bytes_per_s
        #: bytes each simulated intra-broker (logdir) copy takes; 0 means
        #: moves land instantly
        self.intra_move_bytes = intra_move_bytes
        self._intra_inflight: dict[tuple[str, int, int], float] = {}
        self.throttle_rate: float | None = None
        self.throttled_topics: set[str] = set()
        self._inflight: dict[tuple[str, int], _Inflight] = {}
        self._fail = fail_partitions or set()
        #: reassignments the "controller" silently forgets ONCE: on the next
        #: tick the entry vanishes from in-progress without being applied
        #: (models the dropped reassignments reference
        #: Executor.maybeReexecuteTasks:1430 exists to catch); a re-submitted
        #: reassignment for the same partition then proceeds normally
        self._drop_once = set(drop_partitions or set())
        self.dropped_reassignments: list[tuple[str, int]] = []
        self.reassign_calls = 0
        self.election_calls = 0

    # --- ClusterAdmin SPI ---

    def reassign_partitions(self, specs: list[ReassignmentSpec]) -> None:
        self.reassign_calls += 1
        for s in specs:
            key = (s.topic, s.partition)
            if key in self._inflight:
                raise ValueError(f"reassignment already in progress for {key}")
            self._inflight[key] = _Inflight(s, max(s.data_to_move, 0.0))

    def in_progress_reassignments(self) -> set[tuple[str, int]]:
        return set(self._inflight)

    def cancel_reassignments(self) -> None:
        # reference force-stop deletes the ZK node (Executor.java:1145)
        self._inflight.clear()

    def cancel_partition_reassignments(self, keys) -> None:
        """Per-partition cancellation (KIP-455): the move is dropped and
        the partition keeps its ORIGINAL replica set (the simulated
        topology was never touched mid-flight, so dropping the in-flight
        entry IS the rollback)."""
        for key in keys:
            self._inflight.pop(tuple(key), None)

    def reassignment_remaining_bytes(self) -> dict[tuple[str, int], float]:
        """Bytes still to copy per in-flight reassignment — the reaper's
        progress watermark source."""
        return {k: fl.remaining_bytes for k, fl in self._inflight.items()}

    def stall(self, *keys: tuple[str, int]) -> None:
        """Freeze the given reassignments: they stay in-progress but stop
        making byte progress (a wedged follower / saturated link)."""
        self._fail.update(keys)

    def unstall(self, *keys: tuple[str, int]) -> None:
        for key in keys:
            self._fail.discard(key)

    def elect_leaders(self, specs: list[LeadershipSpec]) -> None:
        self.election_calls += 1
        topo = self.metadata.topology()
        parts = list(topo.partitions)
        index = {(p.topic, p.partition): i for i, p in enumerate(parts)}
        for s in specs:
            i = index[(s.topic, s.partition)]
            p = parts[i]
            if s.preferred_leader in p.replicas:
                parts[i] = dataclasses.replace(p, leader=s.preferred_leader)
        self.metadata.set_topology(dataclasses.replace(topo, partitions=tuple(parts)))

    def alter_replica_logdirs(self, moves) -> None:
        # logdir placement is not modeled in the simulated topology, but
        # move DURATION is: each (t, p, broker) copy drains intra_move_bytes
        # at the link rate via tick() (0 bytes -> instant, the default)
        for topic, part, broker, _disk in moves:
            if self.intra_move_bytes > 0:
                self._intra_inflight[(topic, part, broker)] = self.intra_move_bytes

    def in_progress_logdir_moves(self) -> set[tuple[str, int, int]]:
        return set(self._intra_inflight)

    def set_replication_throttle(self, rate: float, topics: set[str]) -> None:
        self.throttle_rate = rate
        self.throttled_topics = set(topics)

    def clear_replication_throttle(self) -> None:
        self.throttle_rate = None
        self.throttled_topics = set()

    def topology(self) -> ClusterTopology:
        return self.metadata.topology()

    # --- simulation ---

    def tick(self, seconds: float) -> list[tuple[str, int]]:
        """Advance time; returns reassignments that completed this tick."""
        rate = self.link_rate
        if self.throttle_rate is not None:
            rate = min(rate, self.throttle_rate)
        done = []
        for key, fl in list(self._inflight.items()):
            if key in self._drop_once:
                self._drop_once.discard(key)
                self.dropped_reassignments.append(key)
                del self._inflight[key]  # vanishes, topology unchanged
                continue
            if key in self._fail:
                continue  # stuck forever (tests exercise DEAD handling)
            fl.remaining_bytes -= rate * seconds
            if fl.remaining_bytes <= 0:
                self._apply(fl.spec)
                del self._inflight[key]
                done.append(key)
        for key3 in list(self._intra_inflight):
            self._intra_inflight[key3] -= rate * seconds
            if self._intra_inflight[key3] <= 0:
                del self._intra_inflight[key3]
        return done

    def _apply(self, spec: ReassignmentSpec):
        topo = self.metadata.topology()
        parts = list(topo.partitions)
        index = {(p.topic, p.partition): i for i, p in enumerate(parts)}
        i = index[(spec.topic, spec.partition)]
        p = parts[i]
        leader = p.leader if p.leader in spec.new_replicas else spec.new_replicas[0]
        parts[i] = PartitionInfo(
            topic=p.topic,
            partition=p.partition,
            leader=leader,
            replicas=tuple(spec.new_replicas),
        )
        self.metadata.set_topology(dataclasses.replace(topo, partitions=tuple(parts)))
