"""Executor — applies optimization proposals to the cluster.

Reference: executor/Executor.java:72 — executeProposals():395,
ProposalExecutionRunnable.run():749 (phase 1 inter/intra-broker moves,
phase 2 leadership), updateOngoingExecutionState():912 (progress loop),
maybeReexecuteTasks():1430, graceful + forced stop (:1145 deletes the ZK
reassignment node; here admin.cancel_reassignments), per-broker
concurrency caps (Executor.java:485-510), removed/demoted broker history.

The execution loop is tick-driven: each `progress_check` round collects
finished reassignments from the ClusterAdmin, transitions tasks, and
drains new ones within concurrency caps.  `execute_proposals` runs the
loop synchronously (simulation advances via admin.tick) or in a
background thread against a real cluster.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.admin import ClusterAdmin, LeadershipSpec, ReassignmentSpec
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import ReplicaMovementStrategy
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskTracker,
    TaskState,
    TaskType,
)
from cruise_control_tpu.executor.throttle import ReplicationThrottleHelper


class ExecutorState(enum.Enum):
    """Reference executor/ExecutorState.java states."""

    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


@dataclasses.dataclass
class ExecutionOptions:
    """Concurrency caps (reference config/constants/ExecutorConfig.java:
    num.concurrent.partition.movements.per.broker default 5,
    num.concurrent.intra.broker.partition.movements default 2,
    num.concurrent.leader.movements default 1000)."""

    concurrent_partition_movements_per_broker: int = 5
    concurrent_intra_broker_partition_movements: int = 2
    concurrent_leader_movements: int = 1000
    #: global cap on concurrently ongoing movements cluster-wide, on top of
    #: the per-broker caps (reference ExecutorConfig
    #: max.num.cluster.movements, default 1250)
    max_num_cluster_movements: int = 1250
    #: a leadership move the topology has not confirmed within this window
    #: is declared DEAD (reference ExecutorConfig leader.movement.timeout.ms)
    leader_movement_timeout_s: float = 180.0
    #: MB/s floors for the slow-task alert: a replica move alerts when its
    #: execution time exceeds task_execution_alerting_s AND its data rate is
    #: below this (reference ExecutorConfig
    #: {inter,intra}.broker.replica.movement.rate.alerting.threshold)
    inter_broker_rate_alerting_mb_s: float = 0.1
    intra_broker_rate_alerting_mb_s: float = 0.2
    replication_throttle_bytes_per_s: float | None = None
    progress_check_interval_s: float = 0.5
    #: tasks in progress longer than this raise an alert flag
    task_execution_alerting_s: float = 90.0
    #: times a reassignment the controller dropped (vanished from the
    #: in-progress set without landing) is re-submitted before the task is
    #: declared DEAD.  The reference re-executes unboundedly
    #: (Executor.maybeReexecuteTasks:1430); the bound here exists so a
    #: pathologically dropping controller cannot loop forever, and defaults
    #: HIGH because the landed-check reads topology metadata that can lag
    #: the controller on a real cluster (a completed move that looks
    #: unplaced for a few ticks must not be DEAD-marked — 64 ticks at the
    #: 0.5s default interval tolerates ~30s of metadata staleness)
    max_reexecution_attempts: int = 64
    #: consecutive ticks a finished-looking logdir copy may stay
    #: UNVERIFIABLE (unreachable broker) before its task is declared DEAD
    max_intra_verify_failures: int = 8
    max_ticks: int = 10_000  # simulation safety bound


@dataclasses.dataclass
class ExecutionResult:
    completed: int
    aborted: int
    dead: int
    ticks: int
    stopped: bool
    tracker_status: dict


class OngoingExecutionError(Exception):
    """Reference sanityCheckDryRun / ongoing-execution guard
    (KafkaCruiseControl.java:216-229)."""


class NoOngoingExecutionError(Exception):
    """Mid-execution concurrency change requested while nothing executes
    (reference rejects ChangeExecutionConcurrency in that case)."""


class Executor:
    def __init__(
        self,
        admin: ClusterAdmin,
        *,
        strategy: ReplicaMovementStrategy | None = None,
        topic_names: dict[int, str] | None = None,
        catalog=None,
        sensors=None,
        removal_history_retention_ms: int = 1_209_600_000,
        demotion_history_retention_ms: int = 1_209_600_000,
        notifier=None,
    ):
        """notifier (reference ExecutorConfig executor.notifier.class): an
        object with on_execution_finished(result, uuid), called after every
        execution — success, stop or abort."""
        from cruise_control_tpu.common.sensors import REGISTRY

        self.sensors = sensors if sensors is not None else REGISTRY
        self.admin = admin
        self.strategy = strategy
        self.notifier = notifier
        self.topic_names = topic_names or {}
        #: ClusterCatalog resolving global partition ids -> (topic, partition)
        self.catalog = catalog
        self.state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = False
        self._force_stop = False
        self._lock = threading.RLock()
        self.tracker = ExecutionTaskTracker()
        self._planner: ExecutionTaskPlanner | None = None
        # reference Executor recentlyRemovedBrokers / recentlyDemotedBrokers,
        # timestamped so entries expire after the configured retention
        # (reference ExecutorConfig {removal,demotion}.history.retention.time.ms)
        self._removal_retention_ms = removal_history_retention_ms
        self._demotion_retention_ms = demotion_history_retention_ms
        self._removed_history: dict[int, int] = {}  # broker id -> recorded ms
        self._demoted_history: dict[int, int] = {}
        self.num_executions_started = 0
        self.num_executions_stopped = 0
        self._uuid: str | None = None
        #: re-submission count per dropped reassignment key
        self._reexecutions: dict[tuple[str, int], int] = {}
        #: consecutive unverifiable-completion count per logdir-copy key
        self._intra_unknown: dict[tuple[str, int, int], int] = {}
        #: mid-execution concurrency overrides (reference
        #: Executor.java:485-510 setRequested*MovementConcurrency): the
        #: operator's knob to decelerate or unstick a LIVE execution via
        #: POST /admin.  Consulted every tick; cleared when a new
        #: execution starts so submitted options apply fresh.
        self._requested: dict[str, float | int] = {}

    # ------------------------------------------------------------------
    # mid-execution concurrency control (reference Executor.java:485-510,
    # driven by ADMIN ChangeExecutionConcurrencyParameters)

    def set_requested_concurrency(
        self,
        *,
        inter_broker: int | None = None,
        intra_broker: int | None = None,
        leadership: int | None = None,
        progress_check_interval_s: float | None = None,
    ) -> dict:
        """Adjust the concurrency caps of the ongoing execution.

        Each tick of the execution loop reads these instead of the frozen
        ExecutionOptions, so the change takes effect on the next progress
        check — matching the reference's
        setRequestedInterBrokerPartitionMovementConcurrency family.
        Returns the now-effective override map.
        """
        # validate everything BEFORE applying anything: a rejected call
        # must not leave a partial override active on the live execution
        staged: dict[str, float | int] = {}
        for name, v in (
            ("inter_broker", inter_broker),
            ("intra_broker", intra_broker),
            ("leadership", leadership),
        ):
            if v is not None:
                if v < 1:
                    raise ValueError(f"{name} concurrency must be >= 1, got {v}")
                staged[name] = int(v)
        if progress_check_interval_s is not None:
            if progress_check_interval_s <= 0:
                raise ValueError(
                    "progress_check_interval_s must be > 0, got "
                    f"{progress_check_interval_s}"
                )
            staged["interval_s"] = float(progress_check_interval_s)
        with self._lock:
            # checked under the lock: overrides die with the execution
            # (cleared at the next start), so accepting one after the
            # execution finished would 200 a silent no-op
            if not self.has_ongoing_execution:
                raise NoOngoingExecutionError(
                    "cannot change execution concurrency: no ongoing execution"
                )
            self._requested.update(staged)
        return self.requested_concurrency()

    def requested_concurrency(self) -> dict:
        """The active mid-execution overrides (empty when none set)."""
        with self._lock:
            return dict(self._requested)

    def _inter_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("inter_broker")
        return int(v) if v is not None else options.concurrent_partition_movements_per_broker

    def _intra_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("intra_broker")
        return int(v) if v is not None else options.concurrent_intra_broker_partition_movements

    def _leader_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("leadership")
        return int(v) if v is not None else options.concurrent_leader_movements

    def _interval(self, options: ExecutionOptions) -> float:
        with self._lock:
            v = self._requested.get("interval_s")
        return float(v) if v is not None else options.progress_check_interval_s

    # ------------------------------------------------------------------

    def _pruned(self, history: dict[int, int], retention_ms: int) -> set[int]:
        # readers run on HTTP/detector threads while the execution thread
        # inserts under the lock — prune must take it too
        with self._lock:
            cutoff = int(time.time() * 1000) - retention_ms
            for b in [b for b, ts in history.items() if ts < cutoff]:
                del history[b]
            return set(history)

    @property
    def removed_brokers(self) -> set[int]:
        """Recently removed brokers, expired per the retention window."""
        return self._pruned(self._removed_history, self._removal_retention_ms)

    @property
    def demoted_brokers(self) -> set[int]:
        """Recently demoted brokers, expired per the retention window."""
        return self._pruned(self._demoted_history, self._demotion_retention_ms)

    def drop_removed_brokers(self, broker_ids):
        """Reference ADMIN drop_recently_removed_brokers."""
        with self._lock:
            for b in broker_ids:
                self._removed_history.pop(b, None)

    def drop_demoted_brokers(self, broker_ids):
        with self._lock:
            for b in broker_ids:
                self._demoted_history.pop(b, None)

    @property
    def has_ongoing_execution(self) -> bool:
        return self.state != ExecutorState.NO_TASK_IN_PROGRESS

    def stop_execution(self, *, force: bool = False):
        """Reference Executor.userTriggeredStopExecution (+ force stop :1145)."""
        with self._lock:
            if self.has_ongoing_execution:
                self._stop_requested = True
                self._force_stop = force
                self.num_executions_stopped += 1
                self.state = ExecutorState.STOPPING_EXECUTION
                # reference Executor execution-stopped gauge (:118-125,257)
                self.sensors.counter("executor.execution-stopped").inc()
                if force:
                    self.sensors.counter("executor.execution-stopped.forced").inc()

    def execute_proposals(
        self,
        proposals: list[ExecutionProposal],
        options: ExecutionOptions | None = None,
        *,
        uuid: str | None = None,
        removed_brokers: set[int] | None = None,
        demoted_brokers: set[int] | None = None,
        strategy_context: dict | None = None,
        strategy: ReplicaMovementStrategy | None = None,
    ) -> ExecutionResult:
        """Reference Executor.executeProposals():395 (synchronous variant).

        strategy: per-execution ordering override (reference per-request
        replica_movement_strategies); falls back to the configured default."""
        options = options or ExecutionOptions()
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError("an execution is already in progress")
            self.state = ExecutorState.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._uuid = uuid
            self.num_executions_started += 1
            # reference Executor execution-started sensor (:118-125)
            self.sensors.counter("executor.execution-started").inc()
            now = int(time.time() * 1000)
            for b in removed_brokers or ():
                self._removed_history[b] = now
            for b in demoted_brokers or ():
                self._demoted_history[b] = now
            self.tracker = ExecutionTaskTracker()
            self._reexecutions = {}
            self._intra_unknown = {}
            self._requested = {}  # overrides die with the previous execution
            self._planner = ExecutionTaskPlanner(strategy or self.strategy)
            tasks = self._planner.add_execution_proposals(proposals, strategy_context)
            for t in tasks:
                self.tracker.add(t)

        throttle = ReplicationThrottleHelper(
            self.admin, options.replication_throttle_bytes_per_s
        )
        throttle.set_throttles(proposals, self.topic_names)
        try:
            result = self._run(options)
        finally:
            throttle.clear_throttles()
            with self._lock:
                self.state = ExecutorState.NO_TASK_IN_PROGRESS
                self._planner = None
        if self.notifier is not None:
            try:
                self.notifier.on_execution_finished(result, uuid)
            except Exception:  # noqa: BLE001 — a broken notifier must not fail the execution
                pass
        return result

    # ------------------------------------------------------------------

    def _maybe_alert_slow_task(self, task, data_bytes, floor_mb_s, options, now):
        """Reference slow-task alerting (ExecutorConfig:142-158): alert once
        when a move runs past task.execution.alerting.threshold.ms AND its
        data rate (bytes -> MB/s) is under the configured floor."""
        if task.alert_time_ms >= 0:
            return
        elapsed_ms = now - task.start_time_ms
        if elapsed_ms <= options.task_execution_alerting_s * 1000:
            return
        if data_bytes / 1e6 / max(elapsed_ms / 1000.0, 1e-9) >= floor_mb_s:
            return
        task.alert_time_ms = now
        self.sensors.counter("executor.slow-task-alert").inc()
        if self.notifier is not None and hasattr(self.notifier, "on_task_alert"):
            try:
                self.notifier.on_task_alert(task)
            except Exception:  # noqa: BLE001 — a broken notifier must not fail the execution
                pass

    def _run(self, options: ExecutionOptions) -> ExecutionResult:
        """The proposal execution loop (reference ProposalExecutionRunnable.run:749):
        phase 1 — inter/intra-broker replica moves; phase 2 — leadership."""
        planner = self._planner
        assert planner is not None
        in_flight: dict[tuple[str, int], ExecutionTask] = {}
        #: intra-broker tasks still copying between logdirs:
        #: execution id -> (task, {(topic, partition, broker): target disk})
        intra_in_flight: dict[
            int, tuple[ExecutionTask, dict[tuple[str, int, int], int]]
        ] = {}
        ticks = 0
        simulated = hasattr(self.admin, "tick")
        # admins that cannot report logdir-copy progress complete intra
        # moves on submit (the pre-KIP-113 behavior)
        track_intra = hasattr(self.admin, "in_progress_logdir_moves")

        def now_ms() -> int:
            return int(time.time() * 1000) if not simulated else ticks * 1000

        # --- phase 1: replica movements ---
        self.state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        while ticks < options.max_ticks:
            if self._stop_requested:
                self._handle_stop(in_flight, now_ms())
                if self._force_stop:
                    # logdir copies cannot be cancelled over the wire; the
                    # tasks are recorded aborted (reference behavior: an
                    # intra move is 'cancelled' by moving back later)
                    for t, _keys in intra_in_flight.values():
                        t.aborting(now_ms())
                        t.aborted(now_ms())
                    intra_in_flight.clear()
                    break
                # graceful stop: submit nothing new, but keep collecting
                # completions until everything in flight drains — an
                # untracked reassignment or logdir copy would otherwise sit
                # IN_PROGRESS in the tracker forever and the result counts
                # would not add up to the task total
                if not in_flight and not intra_in_flight:
                    break
            # collect completions.  A key leaving the in-progress set does
            # NOT prove the move landed: the controller may have dropped the
            # reassignment (reference Executor.maybeReexecuteTasks:1430) —
            # verify against the topology and re-submit dropped tasks, up to
            # a bound, before declaring them DEAD.
            in_progress = self.admin.in_progress_reassignments()
            # ONE topology snapshot per tick feeds both the landed-check and
            # the dead-broker sweep below (on a real cluster each topology()
            # is a wire Metadata round trip)
            topo = self.admin.topology()
            placement = None
            for key, task in list(in_flight.items()):
                if key not in in_progress:
                    if placement is None:
                        placement = {
                            (p.topic, p.partition): set(p.replicas)
                            for p in topo.partitions
                        }
                    if placement.get(key) == set(task.proposal.new_replicas):
                        task.completed(now_ms())
                        del in_flight[key]
                        continue
                    n = self._reexecutions.get(key, 0)
                    if n >= options.max_reexecution_attempts:
                        task.kill(now_ms())
                        del in_flight[key]
                        continue
                    self._reexecutions[key] = n + 1
                    # reference Executor sensor analog for re-executed tasks
                    self.sensors.counter("executor.task-reexecuted").inc()
                    self.admin.reassign_partitions([
                        ReassignmentSpec(
                            topic=key[0],
                            partition=key[1],
                            new_replicas=tuple(task.proposal.new_replicas),
                            data_to_move=task.proposal.inter_broker_data_to_move,
                        )
                    ])
                else:
                    self._maybe_alert_slow_task(
                        task,
                        task.proposal.inter_broker_data_to_move,
                        options.inter_broker_rate_alerting_mb_s,
                        options,
                        now_ms(),
                    )
            # mark tasks dead when a destination broker died mid-move
            alive = topo.alive_broker_ids()
            for key, task in list(in_flight.items()):
                if not set(task.proposal.new_replicas) <= alive:
                    task.kill(now_ms())
                    del in_flight[key]
            # same sweep for logdir copies: a copy on a dead broker can
            # never confirm — without this the phase-1 loop would spin on
            # it until max_ticks
            for eid, (t, keys) in list(intra_in_flight.items()):
                if any(b not in alive for (_tn, _pn, b) in keys):
                    t.kill(now_ms())
                    del intra_in_flight[eid]

            # drain new tasks within caps (per-broker AND the global
            # max.num.cluster.movements budget) — unless a graceful stop is
            # draining the in-flight set
            if self._stop_requested:
                new_tasks, intra = [], []
            else:
                ready = self._ready_brokers(options, in_flight, topo)
                budget = max(
                    0,
                    options.max_num_cluster_movements
                    - len(in_flight)
                    - len(intra_in_flight),
                )
                new_tasks = planner.get_inter_broker_replica_movement_tasks(
                    ready, set(in_flight), max_total=budget
                )
                # intra-broker moves share the global movement budget:
                # whatever the inter-broker drain left of it this tick.
                # Copies still in flight consume their broker's slots
                # (num.concurrent.intra.broker.partition.movements caps
                # CONCURRENT copies per broker, not submissions per tick)
                intra_used: dict[int, int] = {}
                for _t, keys in intra_in_flight.values():
                    for (_tn, _pn, b) in keys:
                        intra_used[b] = intra_used.get(b, 0) + 1
                intra_cap = self._intra_cap(options)
                intra = planner.get_intra_broker_replica_movement_tasks(
                    {b: max(0, intra_cap - intra_used.get(b, 0)) for b in alive},
                    max_total=max(0, budget - len(new_tasks)),
                )
            if new_tasks:
                specs = []
                for t in new_tasks:
                    t.in_progress(now_ms())
                    key = self._partition_key(t.proposal)
                    in_flight[key] = t
                    specs.append(
                        ReassignmentSpec(
                            topic=key[0],
                            partition=key[1],
                            new_replicas=tuple(t.proposal.new_replicas),
                            data_to_move=t.proposal.inter_broker_data_to_move,
                        )
                    )
                self.admin.reassign_partitions(specs)
            for t in intra:
                t.in_progress(now_ms())
                tname, pnum = self._partition_key(t.proposal)
                self.admin.alter_replica_logdirs(
                    [
                        (tname, pnum, b, d_new)
                        for (b, _d_old, d_new) in t.proposal.disk_moves
                    ]
                )
                if track_intra:
                    intra_in_flight[t.execution_id] = (t, {
                        (tname, pnum, b): d_new
                        for (b, _d_old, d_new) in t.proposal.disk_moves
                    })
                else:
                    t.completed(now_ms())
            # intra-broker copy progress (reference ExecutorAdminUtils
            # DescribeLogDirs future replicas): a task completes when none
            # of its (t, p, broker) copies are still in flight; long slow
            # copies alert like inter-broker moves
            if intra_in_flight:
                still = self.admin.in_progress_logdir_moves()
                verify = getattr(self.admin, "logdir_of", None)
                for eid, (t, keys) in list(intra_in_flight.items()):
                    pending = {}
                    for key3, disk in keys.items():
                        if key3 in still:
                            pending[key3] = disk
                            # observed pending again: the unverifiable
                            # bound is CONSECUTIVE ticks, so re-observation
                            # resets it (transient blips hours apart must
                            # not accumulate into a kill)
                            self._intra_unknown.pop(key3, None)
                            continue
                        if verify is None:
                            continue  # cannot verify: disappearance = done
                        # disappearance does NOT prove the copy landed (a
                        # broker restart aborts the future log) — check the
                        # replica's actual dir, like the inter-broker path
                        # re-verifies against the topology
                        actual = verify(*key3)
                        if actual == disk:
                            self._intra_unknown.pop(key3, None)
                            continue
                        if actual is None:
                            # unverifiable (e.g. broker unreachable): keep
                            # polling, but bounded — a partitioned broker
                            # must not hold the loop open until max_ticks
                            u = self._intra_unknown.get(key3, 0) + 1
                            self._intra_unknown[key3] = u
                            if u > options.max_intra_verify_failures:
                                t.kill(now_ms())
                                del intra_in_flight[eid]
                                pending = None
                                break
                            pending[key3] = disk
                            continue
                        n = self._reexecutions.get(key3, 0)
                        if n >= options.max_reexecution_attempts:
                            t.kill(now_ms())
                            del intra_in_flight[eid]
                            pending = None
                            break
                        self._reexecutions[key3] = n + 1
                        self.sensors.counter("executor.task-reexecuted").inc()
                        try:
                            self.admin.alter_replica_logdirs([(*key3, disk)])
                        except Exception:  # noqa: BLE001 — a failed resubmit
                            # must not abort the whole execution; the copy
                            # stays pending and the bounds above decide
                            pass
                        # a resubmitted copy starts a fresh consecutive
                        # unverifiable window
                        self._intra_unknown.pop(key3, None)
                        pending[key3] = disk
                    if pending is None:
                        continue
                    if not pending:
                        t.completed(now_ms())
                        del intra_in_flight[eid]
                        continue
                    intra_in_flight[eid] = (t, pending)
                    self._maybe_alert_slow_task(
                        t,
                        t.proposal.intra_broker_data_to_move,
                        options.intra_broker_rate_alerting_mb_s,
                        options,
                        now_ms(),
                    )

            if (
                not in_flight
                and not intra_in_flight
                and not planner.remaining_inter_broker_moves
                and not planner.remaining_intra_broker_moves
            ):
                break
            ticks += 1
            if simulated:
                self.admin.tick(self._interval(options))
            else:
                time.sleep(self._interval(options))

        # --- phase 2: leadership movements ---
        if not self._stop_requested:
            self.state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
            while not self._stop_requested:
                batch = planner.get_leadership_movement_tasks(
                    min(
                        self._leader_cap(options),
                        options.max_num_cluster_movements,
                    )
                )
                if not batch:
                    break
                specs = []
                for t in batch:
                    t.in_progress(now_ms())
                    tname, pnum = self._partition_key(t.proposal)
                    specs.append(
                        LeadershipSpec(
                            topic=tname,
                            partition=pnum,
                            preferred_leader=t.proposal.new_leader,
                        )
                    )
                self.admin.elect_leaders(specs)
                # confirm against the topology; moves not confirmed within
                # leader.movement.timeout.ms are DEAD (reference
                # ExecutorConfig leader.movement.timeout.ms + the executor's
                # leadership wait loop, Executor.java:1091-1136)
                pending = {self._partition_key(t.proposal): t for t in batch}
                deadline = now_ms() + int(options.leader_movement_timeout_s * 1000)
                while pending:
                    topo2 = self.admin.topology()
                    alive2 = topo2.alive_broker_ids()
                    parts = {(p.topic, p.partition): p for p in topo2.partitions}
                    for key, t in list(pending.items()):
                        target = t.proposal.new_leader
                        p = parts.get(key)
                        if p is not None and p.leader == target:
                            t.completed(now_ms())
                            del pending[key]
                        elif target not in alive2:
                            # target broker died — the election can never be
                            # confirmed: DEAD immediately, don't burn the
                            # timeout
                            t.kill(now_ms())
                            del pending[key]
                        elif p is None or target not in p.replicas:
                            # prerequisite replica placement never landed
                            # (e.g. its move task went DEAD) — cancel the
                            # dependent leadership move
                            t.aborting(now_ms())
                            t.aborted(now_ms())
                            del pending[key]
                    if not pending:
                        break
                    if self._stop_requested:
                        # stop mid-confirmation: unconfirmed moves are
                        # aborted, not left dangling
                        for t in pending.values():
                            t.aborting(now_ms())
                            t.aborted(now_ms())
                        pending.clear()
                        break
                    if now_ms() >= deadline:
                        for t in pending.values():
                            t.kill(now_ms())
                            self.sensors.counter(
                                "executor.leader-movement-timeout"
                            ).inc()
                        break
                    if simulated:
                        self.admin.tick(self._interval(options))
                        ticks += 1
                    else:
                        time.sleep(self._interval(options))

        # abort anything still pending after a stop
        for t in self.tracker.tasks(state=TaskState.PENDING):
            t.in_progress(now_ms())
            t.aborting(now_ms())
            t.aborted(now_ms())

        return ExecutionResult(
            completed=self.tracker.count(state=TaskState.COMPLETED),
            aborted=self.tracker.count(state=TaskState.ABORTED),
            dead=self.tracker.count(state=TaskState.DEAD),
            ticks=ticks,
            stopped=self._stop_requested,
            tracker_status=self.tracker.status(),
        )

    def _handle_stop(self, in_flight, now: int):
        """Graceful stop finishes nothing new; forced stop cancels in-flight
        reassignments (reference Executor.java:1145)."""
        if self._force_stop:
            self.admin.cancel_reassignments()
            for task in in_flight.values():
                task.aborting(now)
                task.aborted(now)
            in_flight.clear()

    def _ready_brokers(
        self, options: ExecutionOptions, in_flight, topo=None
    ) -> dict[int, int]:
        cap = self._inter_cap(options)
        if topo is None:
            topo = self.admin.topology()
        alive = topo.alive_broker_ids()
        used: dict[int, int] = {}
        for task in in_flight.values():
            p = task.proposal
            for b in set(p.old_replicas) ^ set(p.new_replicas):
                used[b] = used.get(b, 0) + 1
        ready = {b: max(0, cap - used.get(b, 0)) for b in alive}
        # dead brokers do no replication work: moves off them are only
        # bounded by the destination's slots (replicas rebuild from alive
        # leaders — reference executes dead-broker evacuation uncapped on
        # the failed side)
        for b in topo.broker_ids():
            if b not in alive:
                ready[b] = 1_000_000
        return ready

    def _partition_key(self, proposal: ExecutionProposal) -> tuple[str, int]:
        """(topic name, partition number) for a proposal: the catalog maps
        the array model's global partition id; without one, proposal ids are
        taken at face value (fixture-built proposals)."""
        if self.catalog is not None:
            return self.catalog.partition_key(proposal.partition)
        return (
            self.topic_names.get(proposal.topic, str(proposal.topic)),
            proposal.partition,
        )

    # ------------------------------------------------------------------

    def executor_state(self) -> dict:
        """STATE endpoint payload (reference ExecutorState JSON)."""
        return {
            "state": self.state.value,
            "numFinishedMovements": self.tracker.count(state=TaskState.COMPLETED),
            "numTotalMovements": len(self.tracker.tasks()),
            "finishedDataMovementMB": self.tracker.finished_data_bytes(),
            # per-type PENDING/IN_PROGRESS/ABORTING/ABORTED/DEAD/COMPLETED
            # breakdown (reference ExecutorState task-state sets)
            "taskStatus": self.tracker.status(),
            "numReexecutedTasks": sum(self._reexecutions.values()),
            "recentlyRemovedBrokers": sorted(self.removed_brokers),
            "recentlyDemotedBrokers": sorted(self.demoted_brokers),
            "numExecutionsStarted": self.num_executions_started,
            "numExecutionsStopped": self.num_executions_stopped,
            "triggeredUserTaskId": self._uuid,
            # operator-requested mid-execution overrides, if any (reference
            # ExecutorState requested*MovementConcurrency fields)
            "requestedConcurrency": self.requested_concurrency(),
        }
